"""One serving replica: an engine+scheduler+RPC-server triple the router
owns.

A replica is the fleet's unit of failure and of scale-out: each one runs the
full single-engine serving stack (:mod:`maggy_tpu.serve`) on its own RPC
port, leasing a disjoint accelerator device group exactly the way the
experiment drivers lease trial sub-slices (``core.driver.base.device_groups``
— one host, N concurrent workloads, zero chip contention). The router talks
to it over the same :mod:`maggy_tpu.core.rpc` client any remote process
would use, so an in-process replica (tests, single-host fleets) and a future
cross-host replica present identical surfaces.

Lifecycle: ``start()`` builds the engine and opens the port;
``stop(drain=True)`` finishes resident requests before closing (the clean
path the router's shutdown uses); ``kill()`` drops everything on the floor —
the chaos path (``MAGGY_TPU_CHAOS="replica_kill:replica=N"``), standing in
for a preempted or wedged host. ``respawn()`` rebuilds the whole stack after
a kill, charged against the router's restart budget.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

from maggy_tpu.core import lockdebug

# replica lifecycle states (the quarantine overlay lives in the router's
# QuarantineTracker, not here — a replica can be UP yet quarantined)
STARTING = "starting"
UP = "up"
DEAD = "dead"


@dataclasses.dataclass
class ReplicaSpec:
    """Everything needed to build (and rebuild) one replica's stack."""

    cfg: Any
    params: Any
    num_slots: int = 4
    mesh: Any = None
    async_decode: Optional[bool] = None
    prefix_reuse: Optional[bool] = None
    # disaggregated prefill/decode (docs/fleet.md): "any" replicas serve the
    # classic full stack; "prefill" replicas only run prompt prefills (the
    # router wraps them in PrefillWorker and never dispatches SUBMIT to
    # them); "decode" replicas admit handed-off KV packs (and still CAN
    # prefill — the fallback when every prefill replica is down)
    role: str = "any"
    # paged KV cache knobs threaded to each replica's engine (None: engine
    # defaults — paged on, DEFAULT_PAGE_SIZE)
    paged: Optional[bool] = None
    page_size: Optional[int] = None
    num_pages: Optional[int] = None
    # TTFT budget handed to each replica's scheduler so per-replica SSTATS
    # carry exact slo_ok/slo_miss counters (launch_fleet seeds it from
    # RouterConfig.slo_ttft_ms)
    slo_ttft_ms: Optional[float] = None
    # index -> telemetry recorder, so each replica's gauges land in its own
    # worker JSONL (exported like any worker's)
    telemetry_factory: Optional[Callable[[int], Any]] = None


class Replica:
    """In-process serving replica with a router-facing client."""

    def __init__(
        self,
        index: int,
        spec: ReplicaSpec,
        secret: str,
        host: str = "127.0.0.1",
        devices: Optional[list] = None,
    ):
        self.index = index
        self.spec = spec
        self.secret = secret
        self.host = host
        # the device lease this replica serves on (observability; the mesh
        # in the spec is what actually places computation)
        self.devices = list(devices or [])
        self.state = STARTING
        self.restarts = 0
        self.started_ts: Optional[float] = None
        self.addr: Optional[Tuple[str, int]] = None
        self.server = None  # ServeServer
        self.client = None  # router-owned ServeClient
        self._lock = lockdebug.lock("replica._lock")

    # -------------------------------------------------------------- lifecycle

    def start(self) -> Tuple[str, int]:
        from maggy_tpu.serve import Engine, Scheduler, ServeClient, ServeServer

        spec = self.spec
        tel = (
            spec.telemetry_factory(self.index)
            if spec.telemetry_factory is not None
            else None
        )
        engine = Engine(
            spec.cfg,
            spec.params,
            num_slots=spec.num_slots,
            mesh=spec.mesh,
            telemetry_recorder=tel,
            async_decode=spec.async_decode,
            prefix_reuse=spec.prefix_reuse,
            paged=spec.paged,
            page_size=spec.page_size,
            num_pages=spec.num_pages,
        )
        self.server = ServeServer(
            Scheduler(engine, slo_ttft_ms=spec.slo_ttft_ms),
            secret=self.secret,
            name=f"replica-{self.index}",
        )
        self.addr = self.server.start(host=self.host, port=0)
        # the router's private client: plain single-shot calls — fleet-level
        # failover lives in the router, not in this hop
        self.client = ServeClient(self.addr, self.secret, failover=False)
        with self._lock:
            self.state = UP
        self.started_ts = time.time()
        return self.addr

    def alive(self) -> bool:
        with self._lock:
            return self.state == UP

    def kill(self) -> None:  # thread-entry — chaos/pump threads hard-kill replicas
        """Chaos/hard death: close the port first (every in-flight and
        future router call fails the way a preempted host's would), then
        abandon the scheduler without draining."""
        with self._lock:
            if self.state == DEAD:
                return
            self.state = DEAD
        if self.client is not None:
            try:
                self.client.close()
            except Exception:  # noqa: BLE001 - already half-dead
                pass
        if self.server is not None:
            self.server._rpc.stop()
            self.server.scheduler.stop(timeout=2.0)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Clean shutdown: finish resident work before closing sockets."""
        with self._lock:
            if self.state == DEAD:
                return
            self.state = DEAD
        if self.server is not None:
            if drain:
                self.server.scheduler.drain(timeout=timeout)
            if self.client is not None:
                try:
                    self.client.close()
                except Exception:  # noqa: BLE001 - socket may already be gone
                    pass
            self.server.stop()

    def respawn(self) -> Tuple[str, int]:
        """Rebuild the full stack after a death (new engine, new port).
        Counts one restart; the router enforces the budget."""
        self.restarts += 1
        with self._lock:
            self.state = STARTING
        addr = self.start()
        return addr

    # ------------------------------------------------------------------ stats

    def local_stats(self) -> Optional[Dict[str, Any]]:
        """Freshest scheduler stats for an in-process replica — lock-guarded
        host state only, no sockets, so the router's SSTATS handler may call
        it on the event loop (the exact contract ServeServer's own SSTATS
        handler follows). None when the replica is down (or remote, where
        only the probe cache exists)."""
        with self._lock:
            if self.state != UP or self.server is None:
                return None
        try:
            return self.server.scheduler.stats()
        except Exception:  # noqa: BLE001 - racing a concurrent kill()
            return None

    def submit_prefilled(self, payload: Dict[str, Any], pack: Dict[str, Any]) -> str:
        """Disaggregated handoff (in-process seam): enqueue a request whose
        KV pack a prefill replica produced. Returns the downstream request
        id, exactly like ``client.submit`` — POLL/CANCEL work unchanged.
        Raises for a remote/dead replica; the router falls back to a plain
        submit (the decode engine prefills for itself)."""
        with self._lock:
            if self.state != UP or self.server is None:
                raise RuntimeError(f"replica {self.index} cannot accept a handoff")
        from maggy_tpu.serve.request import SamplingParams

        params = SamplingParams(
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            max_new=int(payload.get("max_new", 16)),
            eos_id=int(payload.get("eos_id", -1)),
            seed=int(payload.get("seed", 0)),
        )
        deadline_s = payload.get("deadline_s")
        req = self.server.scheduler.submit_prefilled(
            payload["prompt"],
            params,
            pack,
            deadline_s=float(deadline_s) if deadline_s else None,
            trace=payload.get("trace"),
        )
        return req.id

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            state = self.state
        return {
            "replica": self.index,
            "role": self.spec.role,
            "state": state,
            "addr": f"{self.addr[0]}:{self.addr[1]}" if self.addr else None,
            "restarts": self.restarts,
            "devices": [str(d) for d in self.devices],
            "uptime_s": (
                round(time.time() - self.started_ts, 1)
                if self.started_ts and state == UP
                else None
            ),
        }


def build_replicas(
    spec: ReplicaSpec, n: int, secret: str, host: str = "127.0.0.1"
) -> list:
    """N replicas over this host's accelerator leases: device groups are
    carved exactly like trial leases (one group per replica, round-robin
    when the host has fewer groups than replicas)."""
    from maggy_tpu.core.driver.base import device_groups

    groups = device_groups(devices_per_trial=1)
    return [
        Replica(
            i,
            spec,
            secret,
            host=host,
            devices=groups[i % len(groups)] if groups else [],
        )
        for i in range(n)
    ]
