"""One serving replica: an engine+scheduler+RPC-server triple the router
owns.

A replica is the fleet's unit of failure and of scale-out: each one runs the
full single-engine serving stack (:mod:`maggy_tpu.serve`) on its own RPC
port, leasing a disjoint accelerator device group exactly the way the
experiment drivers lease trial sub-slices (``core.driver.base.device_groups``
— one host, N concurrent workloads, zero chip contention). The router talks
to it over the same :mod:`maggy_tpu.core.rpc` client any remote process
would use, so an in-process replica (tests, single-host fleets) and a future
cross-host replica present identical surfaces.

Lifecycle: ``start()`` builds the engine and opens the port;
``stop(drain=True)`` finishes resident requests before closing (the clean
path the router's shutdown uses); ``kill()`` drops everything on the floor —
the chaos path (``MAGGY_TPU_CHAOS="replica_kill:replica=N"``), standing in
for a preempted or wedged host. ``respawn()`` rebuilds the whole stack after
a kill, charged against the router's restart budget.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

from maggy_tpu.core import lockdebug

# replica lifecycle states (the quarantine overlay lives in the router's
# QuarantineTracker, not here — a replica can be UP yet quarantined)
STARTING = "starting"
UP = "up"
DEAD = "dead"

# circuit-breaker states (docs/resilience.md "Gray failure & circuit
# breakers"): CLOSED dispatches normally; OPEN excludes the replica from
# dispatch; HALF_OPEN lets bounded probation probes through, whose observed
# TTFT closes the breaker or re-opens it
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica gray-failure breaker over latency-outlier scores.

    Health probes catch *dead* replicas; a gray replica answers every probe
    while serving tokens 10x slower than its peers. The router's metrics
    tick scores each replica's windowed TTFT p95 against the best healthy
    peer (:meth:`score`); consecutive outlier scores open the breaker,
    which removes the replica from dispatch without touching its liveness
    state. After ``cooldown_s`` the breaker half-opens: one probation probe
    at a time is dispatched, and the probe's observed TTFT
    (:meth:`observe_ttft`) either closes the breaker or re-opens it.

    Scored from the router's pump thread and read on dispatch; the lock
    keeps the state machine's compound transitions atomic (pinned in
    ``tools/check_concurrency.py`` REQUIRED_MODELS).
    """

    def __init__(self, index: int, trips: int = 2, cooldown_s: float = 5.0):
        self.index = index
        self.trips = int(trips)
        self.cooldown_s = float(cooldown_s)
        self._lock = lockdebug.lock("replica.breaker")
        self._state = BREAKER_CLOSED  # guarded-by: _lock
        self._slow_streak = 0  # guarded-by: _lock
        self._opened_ts: Optional[float] = None  # guarded-by: _lock
        # TTFT a probation probe must beat to close (set when opening,
        # from the peer baseline that tripped us)  # guarded-by: _lock
        self._close_below_ms: float = 0.0
        self._probe_inflight = False  # guarded-by: _lock
        # rid of the probation dispatch: the verdict must come from the
        # probe itself, not an old slow stream polled during probation
        self._probe_rid: Optional[str] = None  # guarded-by: _lock
        self.opened_total = 0  # guarded-by: _lock

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def score(  # thread-entry — router pump's ~1 Hz metrics tick
        self,
        p95_ms: Optional[float],
        peer_p95_ms: Optional[float],
        ratio: float,
        min_ms: float,
        now: float,
    ) -> Optional[str]:
        """Feed one windowed latency score; returns ``"opened"`` on the
        CLOSED→OPEN transition, else None. A score is an outlier when this
        replica's TTFT p95 exceeds ``ratio`` x the best healthy peer's AND
        the absolute floor ``min_ms`` (so microsecond jitter between idle
        replicas never trips anything)."""
        slow = (
            p95_ms is not None
            and peer_p95_ms is not None
            and p95_ms >= min_ms
            and p95_ms > ratio * peer_p95_ms
        )
        with self._lock:
            if self._state != BREAKER_CLOSED:
                # open/half-open windows go stale (no fresh dispatches);
                # recovery is probe-driven, not score-driven
                return None
            self._slow_streak = self._slow_streak + 1 if slow else 0
            if self._slow_streak < self.trips:
                return None
            self._state = BREAKER_OPEN
            self._opened_ts = now
            self._slow_streak = 0
            self._probe_inflight = False
            self.opened_total += 1
            # a recovered replica should look like its peers did when we
            # tripped — with slack so marginal recovery still closes
            self._close_below_ms = max(min_ms, ratio * (peer_p95_ms or 0.0))
            return "opened"

    def ok(self, now: float) -> bool:  # thread-entry — router pump dispatch filter
        """May the router dispatch to this replica right now? Also drives
        the timed OPEN→HALF_OPEN transition."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                if self._opened_ts is not None and (
                    now - self._opened_ts >= self.cooldown_s
                ):
                    self._state = BREAKER_HALF_OPEN
                    self._probe_inflight = False
                else:
                    return False
            # HALF_OPEN: one probation probe at a time
            return not self._probe_inflight

    def take_probe(self, rid: str) -> bool:
        """Claim the half-open probation slot for dispatch ``rid`` (the
        router calls this only after ``ok()``; CLOSED needs no claim)."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state != BREAKER_HALF_OPEN or self._probe_inflight:
                return False
            self._probe_inflight = True
            self._probe_rid = rid
            return True

    def observe_ttft(self, rid: str, ttft_ms: float, now: float) -> Optional[str]:  # thread-entry — router pump's poll loop
        """Feed an observed dispatch TTFT. Only the probation probe's own
        rid renders a HALF_OPEN verdict: fast closes the breaker, slow
        re-opens it (and restarts the cooldown). Returns ``"closed"`` /
        ``"reopened"`` on a transition, else None."""
        with self._lock:
            if self._state != BREAKER_HALF_OPEN or rid != self._probe_rid:
                return None
            self._probe_inflight = False
            self._probe_rid = None
            if ttft_ms <= self._close_below_ms:
                self._state = BREAKER_CLOSED
                self._opened_ts = None
                self._slow_streak = 0
                return "closed"
            self._state = BREAKER_OPEN
            self._opened_ts = now
            return "reopened"

    def probe_lost(self, rid: Optional[str] = None) -> None:
        """The probation dispatch died without a TTFT (replica went down,
        RPC failed): free the probe slot so probation can retry. With a
        rid, only that probe's claim is released."""
        with self._lock:
            if self._state != BREAKER_HALF_OPEN:
                return
            if rid is None or rid == self._probe_rid:
                self._probe_inflight = False
                self._probe_rid = None

    def reset(self) -> None:
        """Back to a pristine CLOSED breaker. For respawned replicas: the
        new engine shares nothing with the dead one, so the scoring window
        and any open/half-open state built from pre-death latency samples
        are stale — carrying them over would re-open a healthy replica on
        its predecessor's ghosts (the router also drops the replica's
        SeriesStore for the same reason)."""
        with self._lock:
            self._state = BREAKER_CLOSED
            self._slow_streak = 0
            self._opened_ts = None
            self._close_below_ms = 0.0
            self._probe_inflight = False
            self._probe_rid = None

    def begin_probation(self, close_below_ms: float) -> None:
        """Half-open-style admission gate for a freshly warmed replica
        (autoscaler scale-up): start in HALF_OPEN so the dispatch loop's
        probation-first path routes one canary request at a time, and an
        observed TTFT at or under ``close_below_ms`` closes the breaker —
        only then does the replica take weighted traffic. A slow canary
        re-opens it, exactly like gray-failure probation."""
        with self._lock:
            self._state = BREAKER_HALF_OPEN
            self._slow_streak = 0
            self._opened_ts = None
            self._close_below_ms = float(close_below_ms)
            self._probe_inflight = False
            self._probe_rid = None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "opened_total": self.opened_total,
                "close_below_ms": round(self._close_below_ms, 3),
            }


class RetryBudget:
    """Token bucket bounding how many requeues a replica's failures may
    inject back into the dispatch queue per window — a requeue storm from a
    flapping replica must not amplify an overload (docs/resilience.md).
    When the bucket is dry the requeue still happens, but deferred
    (``RouteEntry.not_before_ts``), never dropped."""

    def __init__(self, capacity: int = 8, window_s: float = 10.0):
        self.capacity = max(1, int(capacity))
        self.window_s = float(window_s)
        self._lock = lockdebug.lock("replica.retry_budget")
        self._tokens = float(self.capacity)  # guarded-by: _lock
        self._last_ts: Optional[float] = None  # guarded-by: _lock

    def consume(self, now: float) -> bool:  # thread-entry — pump requeue paths
        """Take one token; False means the caller should defer its requeue."""
        with self._lock:
            if self._last_ts is not None:
                refill = (now - self._last_ts) * self.capacity / self.window_s
                self._tokens = min(float(self.capacity), self._tokens + refill)
            self._last_ts = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


@dataclasses.dataclass
class ReplicaSpec:
    """Everything needed to build (and rebuild) one replica's stack."""

    cfg: Any
    params: Any
    num_slots: int = 4
    mesh: Any = None
    async_decode: Optional[bool] = None
    prefix_reuse: Optional[bool] = None
    # disaggregated prefill/decode (docs/fleet.md): "any" replicas serve the
    # classic full stack; "prefill" replicas only run prompt prefills (the
    # router wraps them in PrefillWorker and never dispatches SUBMIT to
    # them); "decode" replicas admit handed-off KV packs (and still CAN
    # prefill — the fallback when every prefill replica is down)
    role: str = "any"
    # paged KV cache knobs threaded to each replica's engine (None: engine
    # defaults — paged on, DEFAULT_PAGE_SIZE)
    paged: Optional[bool] = None
    page_size: Optional[int] = None
    num_pages: Optional[int] = None
    # host-DRAM KV tier (docs/serving.md "Host-DRAM page tier"); None:
    # engine default (on for paged engines, MAGGY_TPU_SERVE_TIER gated)
    tier: Optional[bool] = None
    tier_host_pages: Optional[int] = None
    # TTFT budget handed to each replica's scheduler so per-replica SSTATS
    # carry exact slo_ok/slo_miss counters (launch_fleet seeds it from
    # RouterConfig.slo_ttft_ms)
    slo_ttft_ms: Optional[float] = None
    # index -> telemetry recorder, so each replica's gauges land in its own
    # worker JSONL (exported like any worker's)
    telemetry_factory: Optional[Callable[[int], Any]] = None


class Replica:
    """In-process serving replica with a router-facing client."""

    def __init__(
        self,
        index: int,
        spec: ReplicaSpec,
        secret: str,
        host: str = "127.0.0.1",
        devices: Optional[list] = None,
    ):
        self.index = index
        self.spec = spec
        self.secret = secret
        self.host = host
        # the device lease this replica serves on (observability; the mesh
        # in the spec is what actually places computation)
        self.devices = list(devices or [])
        self.state = STARTING
        self.restarts = 0
        self.started_ts: Optional[float] = None
        self.addr: Optional[Tuple[str, int]] = None
        self.server = None  # ServeServer
        self.client = None  # router-owned ServeClient
        self._lock = lockdebug.lock("replica._lock")

    # -------------------------------------------------------------- lifecycle

    def start(self) -> Tuple[str, int]:
        from maggy_tpu.serve import Engine, Scheduler, ServeClient, ServeServer

        spec = self.spec
        tel = (
            spec.telemetry_factory(self.index)
            if spec.telemetry_factory is not None
            else None
        )
        engine = Engine(
            spec.cfg,
            spec.params,
            num_slots=spec.num_slots,
            mesh=spec.mesh,
            telemetry_recorder=tel,
            async_decode=spec.async_decode,
            prefix_reuse=spec.prefix_reuse,
            paged=spec.paged,
            page_size=spec.page_size,
            num_pages=spec.num_pages,
            tier=spec.tier,
            tier_host_pages=spec.tier_host_pages,
        )
        scheduler = Scheduler(engine, slo_ttft_ms=spec.slo_ttft_ms)
        # the replica_slow chaos seam keys on this index so one replica can
        # be made gray (slow-but-alive) while its peers stay fast
        scheduler.replica_index = self.index
        self.server = ServeServer(
            scheduler,
            secret=self.secret,
            name=f"replica-{self.index}",
        )
        self.addr = self.server.start(host=self.host, port=0)
        # the router's private client: plain single-shot calls — fleet-level
        # failover lives in the router, not in this hop
        self.client = ServeClient(self.addr, self.secret, failover=False)
        with self._lock:
            self.state = UP
        self.started_ts = time.time()
        return self.addr

    def alive(self) -> bool:
        with self._lock:
            return self.state == UP

    def kill(self) -> None:  # thread-entry — chaos/pump threads hard-kill replicas
        """Chaos/hard death: close the port first (every in-flight and
        future router call fails the way a preempted host's would), then
        abandon the scheduler without draining."""
        with self._lock:
            if self.state == DEAD:
                return
            self.state = DEAD
        if self.client is not None:
            try:
                self.client.close()
            except Exception:  # noqa: BLE001 - already half-dead
                pass
        if self.server is not None:
            self.server._rpc.stop()
            self.server.scheduler.stop(timeout=2.0)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Clean shutdown: finish resident work before closing sockets."""
        with self._lock:
            if self.state == DEAD:
                return
            self.state = DEAD
        if self.server is not None:
            if drain:
                self.server.scheduler.drain(timeout=timeout)
            if self.client is not None:
                try:
                    self.client.close()
                except Exception:  # noqa: BLE001 - socket may already be gone
                    pass
            self.server.stop()

    def respawn(self) -> Tuple[str, int]:
        """Rebuild the full stack after a death (new engine, new port).
        Counts one restart; the router enforces the budget."""
        self.restarts += 1
        with self._lock:
            self.state = STARTING
        addr = self.start()
        return addr

    # ------------------------------------------------------------------ stats

    def local_stats(self) -> Optional[Dict[str, Any]]:
        """Freshest scheduler stats for an in-process replica — lock-guarded
        host state only, no sockets, so the router's SSTATS handler may call
        it on the event loop (the exact contract ServeServer's own SSTATS
        handler follows). None when the replica is down (or remote, where
        only the probe cache exists)."""
        with self._lock:
            if self.state != UP or self.server is None:
                return None
        try:
            return self.server.scheduler.stats()
        except Exception:  # noqa: BLE001 - racing a concurrent kill()
            return None

    def submit_prefilled(self, payload: Dict[str, Any], pack: Dict[str, Any]) -> str:
        """Disaggregated handoff (in-process seam): enqueue a request whose
        KV pack a prefill replica produced. Returns the downstream request
        id, exactly like ``client.submit`` — POLL/CANCEL work unchanged.
        Raises for a remote/dead replica; the router falls back to a plain
        submit (the decode engine prefills for itself)."""
        with self._lock:
            if self.state != UP or self.server is None:
                raise RuntimeError(f"replica {self.index} cannot accept a handoff")
        from maggy_tpu.serve.request import SamplingParams

        params = SamplingParams(
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            max_new=int(payload.get("max_new", 16)),
            eos_id=int(payload.get("eos_id", -1)),
            seed=int(payload.get("seed", 0)),
        )
        deadline_s = payload.get("deadline_s")
        req = self.server.scheduler.submit_prefilled(
            payload["prompt"],
            params,
            pack,
            deadline_s=float(deadline_s) if deadline_s else None,
            trace=payload.get("trace"),
            tenant=payload.get("tenant"),
            qos=payload.get("qos"),
        )
        return req.id

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            state = self.state
        return {
            "replica": self.index,
            "role": self.spec.role,
            "state": state,
            "addr": f"{self.addr[0]}:{self.addr[1]}" if self.addr else None,
            "restarts": self.restarts,
            "devices": [str(d) for d in self.devices],
            "uptime_s": (
                round(time.time() - self.started_ts, 1)
                if self.started_ts and state == UP
                else None
            ),
        }


def build_replicas(
    spec: ReplicaSpec, n: int, secret: str, host: str = "127.0.0.1"
) -> list:
    """N replicas over this host's accelerator leases: device groups are
    carved exactly like trial leases (one group per replica, round-robin
    when the host has fewer groups than replicas)."""
    from maggy_tpu.core.driver.base import device_groups

    groups = device_groups(devices_per_trial=1)
    return [
        Replica(
            i,
            spec,
            secret,
            host=host,
            devices=groups[i % len(groups)] if groups else [],
        )
        for i in range(n)
    ]
