"""Fleet autoscaler: close the capacity loop with drain-safe scale events.

The robustness stack below this module reacts on two timescales already:
the brownout ladder degrades best-effort traffic within *seconds* of an SLO
burn (docs/fleet.md "QoS classes & graceful degradation"), and circuit
breakers sideline a gray replica within a couple of metric ticks
(docs/resilience.md "Gray failure & circuit breakers"). What the fleet
could not do was change its own *size*: sustained overload beyond what
brownout can shed was terminal, and sustained idle burned replica-hours.
The :class:`Autoscaler` closes that loop on the *minutes* timescale, from
the same fleet time-series the router already keeps.

Decision inputs (sampled each tick from the router, no new telemetry):

* brownout ladder level — sustained level >= ``escalate_level`` means the
  seconds-scale response is saturated: escalate to scale-out;
* fleet slot utilization (active/total over dispatchable decode replicas)
  against the ``target_util`` knob;
* router queue depth and the minimum replica HBM headroom;
* exact fleet-edge SLO counters (``Router.slo_ok``/``slo_miss``) for the
  post-scale regression guard.

The handoff with the brownout ladder is explicit and hysteretic so the two
controllers never fight: brownout acts in seconds and is the *first*
responder; the autoscaler only escalates after brownout has been pinned at
level >= 2 for ``escalate_hold_s`` (the ladder clearly cannot shed its way
out), and it only scales IN at brownout level 0 with enough slot headroom
that the survivors absorb the victim's load below ``target_util``. Every
decision is separated by ``scale_cooldown_s`` so a burst's edge cannot flap
the fleet.

Scale events are safe by construction:

* **Scale-up** spawns the replica off the pump thread, warms it (engine
  build + compile + one end-to-end probe request), and only then admits it
  to the router behind a half-open-style probation gate
  (:meth:`CircuitBreaker.begin_probation`): the router's dispatch loop
  routes one canary request at a time until an observed TTFT under the SLO
  closes the breaker — a replica that compiles but serves slowly never
  takes weighted traffic.
* **Scale-down** drains the victim: dispatch stops first
  (``Router.begin_drain``), in-flight waves get ``drain_grace_s`` to
  finish, then remaining streams are cancelled downstream — the victim's
  page release spills reusable prefix KV through the host tier seam
  (docs/serving.md "Host-DRAM page tier") — and requeued to survivors, the
  victim's FleetPrefixMap entries and SeriesStore are forgotten, and the
  replica retires. Completions are byte-identical either way because engine
  output is a pure function of (params, prompt, seed). A chaos
  ``replica_kill_mid_drain`` fault mid-drain falls back to the router's
  plain requeue-on-death path — same guarantee, exercised in tests.
* Every decision mirrors the autopilot's baseline→trial→commit-or-rollback
  shape (docs/autotune.md "Rollback semantics"): the pre-event SLO
  attainment is the baseline, the post-event ``guard_window_s`` is the
  trial, and a regression beyond ``regress_tol`` auto-reverts the event
  (scale-in regressed → respawn; scale-up regressed → drain it back out).
  Decisions are journaled as ``fleet.scale.*`` events.

In a disaggregated fleet the prefill:decode role mix scales too: the mix
fraction observed at attach time is the target, scale-out spawns whichever
role is under-represented and scale-in retires from the over-represented
pool, so growing the fleet never starves one side of the handoff.

Ticked by the router's pump thread; the lock guards the phase machine
(pinned in ``tools/check_concurrency.py`` REQUIRED_MODELS). See
docs/fleet.md "Autoscaling".
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu.core import lockdebug
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.serve.fleet.replica import DEAD, UP, Replica

# phase machine states (one scale event in flight at a time, ever)
STEADY = "steady"
WARMING = "warming"
DRAINING = "draining"
GUARD = "guard"


@dataclasses.dataclass
class AutoscaleConfig:
    """Capacity-loop knobs (docs/fleet.md "Autoscaling"). The first four
    are autopilot-registered (``fleet.min_replicas`` / ``fleet.max_replicas``
    / ``fleet.scale_cooldown_s`` / ``fleet.target_util``)."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_cooldown_s: float = 20.0  # minimum gap between scale events
    target_util: float = 0.80  # fleet slot utilization ceiling
    low_util: float = 0.30  # scale-in candidate floor
    # brownout handoff: the ladder must be pinned at >= escalate_level for
    # escalate_hold_s before the autoscaler treats shedding as saturated
    escalate_level: int = 2
    escalate_hold_s: float = 4.0
    high_hold_s: float = 3.0  # util > target must persist this long
    low_hold_s: float = 6.0  # idle must persist this long
    min_headroom_pct: float = 0.05  # scale-in blocked under HBM pressure
    # post-scale regression guard (the autopilot trial-window shape)
    guard_window_s: float = 8.0
    regress_tol: float = 0.10
    # scale-up warm path: compile + end-to-end probe before admission
    warm_timeout_s: float = 120.0
    probe_prompt: Tuple[int, ...] = (2, 3, 4, 5)
    # scale-down drain path: waves get the grace, then streams are
    # cancelled downstream (spilling prefix KV through the tier seam) and
    # requeued; the timeout hard-kills a wedged drain (requeue fallback)
    drain_grace_s: float = 5.0
    drain_timeout_s: float = 30.0

    def validate(self) -> None:
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}"
            )
        if not 0.0 < self.target_util <= 1.0:
            raise ValueError(f"target_util must be in (0, 1], got {self.target_util}")
        if self.low_util >= self.target_util:
            raise ValueError(
                f"low_util {self.low_util} must be below target_util "
                f"{self.target_util} (hysteresis band)"
            )


@dataclasses.dataclass(frozen=True)
class Observation:
    """One tick's decision inputs, separated from actuation so the
    escalation/de-escalation ladder is unit-testable without a fleet."""

    now: float
    replicas: int  # decode-capable, non-draining (the scalable pool)
    util: Optional[float]  # active/total slots over that pool
    queue_depth: int
    brownout_level: int
    headroom_pct: Optional[float]  # minimum over replicas; None = unknown


class Autoscaler:
    """Grow/shrink the fleet from its own time-series, drain-safely.

    Owned by the router (``Router(..., autoscale=...)``) and ticked from
    its pump thread after each metrics tick; the warm worker is the only
    other thread, and it touches nothing but its replica and the
    lock-guarded warm slot.
    """

    def __init__(
        self,
        router,
        config: Optional[AutoscaleConfig] = None,
        spec=None,
        host: Optional[str] = None,
    ):
        self.router = router
        self.config = config or AutoscaleConfig()
        self.config.validate()
        self._lock = lockdebug.lock("fleet.autoscale")
        self._phase = STEADY  # guarded-by: _lock
        # decision-episode hysteresis clocks  # guarded-by: _lock
        self._esc_since: Optional[float] = None
        self._high_since: Optional[float] = None
        self._low_since: Optional[float] = None
        self._last_event_ts: Optional[float] = None  # guarded-by: _lock
        self._at_capacity = False  # guarded-by: _lock
        self._capacity_logged = False  # guarded-by: _lock
        # one in-flight scale event, ever  # guarded-by: _lock
        self._warm: Optional[Dict[str, Any]] = None
        self._drain: Optional[Dict[str, Any]] = None
        self._guard: Optional[Dict[str, Any]] = None
        # journal mirror (the telemetry events are the durable record;
        # this ring is the STATUS/test surface)  # guarded-by: _lock
        self.events: deque = deque(maxlen=64)
        # fleet-edge SLO counter ring for the regression guard
        self._slo_ring: deque = deque(maxlen=512)  # guarded-by: _lock
        # spawn templates: the decode spec (and host) new replicas clone
        template = None
        for r in router.replicas:
            if getattr(r.spec, "role", "any") != "prefill":
                template = r
                break
        self._template_spec = spec if spec is not None else (
            template.spec if template is not None else None
        )
        self._host = host or (template.host if template is not None else "127.0.0.1")
        # disaggregated role mix: the attach-time prefill fraction is the
        # target the scaler preserves while growing/shrinking
        n_prefill = sum(
            1 for r in router.replicas
            if getattr(r.spec, "role", "any") == "prefill"
        )
        n_total = len(router.replicas)
        self._target_prefill_frac = n_prefill / n_total if n_total else 0.0

    # ---------------------------------------------------------------- inputs

    def observe(self, now: float) -> Observation:
        """Sample the decision inputs from router state (pump thread)."""
        router = self.router
        with router._lock:
            pool = [
                r
                for r in router.replicas
                if getattr(r.spec, "role", "any") != "prefill"
                and r.index not in router._draining
                and r.state != DEAD
            ]
            active = total = 0
            headroom: Optional[float] = None
            for r in pool:
                stats = router._stats_cache.get(r.index) or {}
                active += int(stats.get("active_slots") or 0)
                total += int(stats.get("num_slots", r.spec.num_slots) or 0)
                hp = (stats.get("memory") or {}).get("headroom_pct")
                if hp is not None:
                    headroom = (
                        float(hp) if headroom is None else min(headroom, float(hp))
                    )
            queue_depth = len(router._pending)
        return Observation(
            now=now,
            replicas=len(pool),
            util=(active / total) if total else None,
            queue_depth=queue_depth,
            brownout_level=router.brownout.level(),
            headroom_pct=headroom,
        )

    def _record_slo(self, now: float) -> None:
        router = self.router
        if router.config.slo_ttft_ms is None:
            return
        with router._lock:
            ok, miss = router.slo_ok, router.slo_miss
        with self._lock:
            self._slo_ring.append((now, ok, miss))

    def _attainment(self, now: float, window_s: float) -> Optional[float]:
        """Fleet-edge SLO attainment over the trailing window (None until
        a request has been judged inside it)."""
        with self._lock:
            ring = list(self._slo_ring)
        if not ring:
            return None
        base = ring[0]
        for sample in ring:
            if sample[0] <= now - window_s:
                base = sample
            else:
                break
        _, ok0, miss0 = base
        _, ok1, miss1 = ring[-1]
        judged = (ok1 - ok0) + (miss1 - miss0)
        if judged <= 0:
            return None
        return (ok1 - ok0) / judged

    # -------------------------------------------------------------- decisions

    def decide(self, obs: Observation) -> Optional[str]:
        """Pure escalation/de-escalation ladder over one observation:
        returns ``"up"``, ``"down"``, or None. Hysteresis clocks live on
        the instance; cooldown and min/max clamps are applied here so the
        flap-prevention rules are what the unit tests exercise.

        Escalation: brownout pinned at >= ``escalate_level`` for
        ``escalate_hold_s`` (the seconds-scale response is saturated), or
        utilization over ``target_util`` for ``high_hold_s``.
        De-escalation: brownout 0 AND idle (util < ``low_util``, empty
        queue) for ``low_hold_s`` AND enough headroom that the survivors
        absorb the victim's load under ``target_util``."""
        cfg = self.config
        now = obs.now
        with self._lock:
            # ---- escalation pressure clocks
            if obs.brownout_level >= cfg.escalate_level:
                if self._esc_since is None:
                    self._esc_since = now
            else:
                self._esc_since = None
            if obs.util is not None and obs.util > cfg.target_util:
                if self._high_since is None:
                    self._high_since = now
            else:
                self._high_since = None
            want_up = (
                self._esc_since is not None
                and now - self._esc_since >= cfg.escalate_hold_s
            ) or (
                self._high_since is not None
                and now - self._high_since >= cfg.high_hold_s
            )
            # ---- de-escalation clock: only at brownout 0, only when idle
            idle = (
                obs.brownout_level == 0
                and not want_up
                and obs.queue_depth == 0
                and obs.util is not None
                and obs.util < cfg.low_util
            )
            if idle:
                if self._low_since is None:
                    self._low_since = now
            else:
                self._low_since = None
            want_down = (
                self._low_since is not None
                and now - self._low_since >= cfg.low_hold_s
            )
            # ---- clamps + flap prevention
            cooling = (
                self._last_event_ts is not None
                and now - self._last_event_ts < cfg.scale_cooldown_s
            )
            self._at_capacity = bool(want_up and obs.replicas >= cfg.max_replicas)
            if want_up:
                if obs.replicas >= cfg.max_replicas:
                    if not self._capacity_logged:
                        self._capacity_logged = True
                        self._journal_locked(
                            "fleet.scale.blocked", now,
                            reason="at_max_replicas", replicas=obs.replicas,
                        )
                    return None
                if cooling:
                    return None
                return "up"
            self._capacity_logged = False
            if want_down:
                if obs.replicas <= cfg.min_replicas or cooling:
                    return None
                # survivors must absorb the victim's load under target —
                # and HBM headroom must not already be tight
                if obs.util is not None and obs.replicas > 1:
                    projected = obs.util * obs.replicas / (obs.replicas - 1)
                    if projected > cfg.target_util:
                        return None
                if (
                    obs.headroom_pct is not None
                    and obs.headroom_pct < cfg.min_headroom_pct
                ):
                    return None
                return "down"
            return None

    def at_capacity(self) -> bool:
        """Scale-out pressure exists but the fleet is at ``max_replicas``
        (the ``fleet.at_capacity`` gauge / ``alert.fleet_at_capacity``)."""
        with self._lock:
            return self._at_capacity

    # ----------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None) -> None:  # thread-entry — router pump, after each metrics tick
        now = time.time() if now is None else now
        self._record_slo(now)
        with self._lock:
            phase = self._phase
        if phase == STEADY:
            action = self.decide(self.observe(now))
            if action == "up":
                self._begin_scale_up(now, reason=self._pressure_reason(now))
            elif action == "down":
                self._begin_scale_down(now, reason="idle")
        elif phase == WARMING:
            self._tick_warming(now)
        elif phase == DRAINING:
            self._tick_draining(now)
        elif phase == GUARD:
            self._tick_guard(now)

    def _pressure_reason(self, now: float) -> str:
        with self._lock:
            if (
                self._esc_since is not None
                and now - self._esc_since >= self.config.escalate_hold_s
            ):
                return "brownout"
            return "util"

    # ------------------------------------------------------------- journaling

    def _journal_locked(self, event: str, now: float, **attrs: Any) -> None:
        self.events.append({"event": event, "ts": round(now, 3), **attrs})
        self.router.telemetry.event(event, **attrs)

    def _journal(self, event: str, now: float, **attrs: Any) -> None:
        with self._lock:
            self._journal_locked(event, now, **attrs)

    # --------------------------------------------------------------- scale-up

    def _spawn_role(self) -> str:
        """The role whose pool is under its attach-time mix fraction —
        the rule that grows a disaggregated fleet without starving either
        side of the prefill→decode handoff."""
        if self._target_prefill_frac <= 0:
            return getattr(self._template_spec, "role", "any")
        router = self.router
        with router._lock:
            n_prefill = sum(
                1 for r in router.replicas
                if getattr(r.spec, "role", "any") == "prefill" and r.state != DEAD
            )
            n_total = sum(1 for r in router.replicas if r.state != DEAD)
        frac_if_decode = n_prefill / (n_total + 1)
        return "prefill" if frac_if_decode < self._target_prefill_frac else "decode"

    def _begin_scale_up(self, now: float, reason: str, revert: bool = False) -> None:
        if self._template_spec is None:
            return
        router = self.router
        role = self._spawn_role()
        spec = self._template_spec
        if getattr(spec, "role", "any") != role:
            spec = dataclasses.replace(spec, role=role)
        index = router.allocate_index()
        replica = Replica(index, spec, router.secret, host=self._host)
        baseline = None if revert else self._attainment(now, self.config.guard_window_s)
        with self._lock:
            self._phase = WARMING
            self._last_event_ts = now
            self._warm = {
                "replica": replica,
                "started": now,
                "done": False,
                "error": None,
                "revert": revert,
                "baseline": baseline,
                "reason": reason,
            }
            self._journal_locked(
                "fleet.scale.up", now, replica=index, role=role,
                reason=reason, revert=revert,
            )
        router.telemetry.count("fleet.scale_events")
        router.log(
            f"autoscale: scale-out -> replica {index} ({role}, {reason})"
        )
        threading.Thread(
            target=self._warm_worker,
            args=(replica,),
            name=f"maggy-warm-{index}",
            daemon=True,
        ).start()

    def _warm_worker(self, replica: Replica) -> None:  # thread-entry — warms one spawned replica off the pump
        """Engine build + compile + one end-to-end probe; the chaos
        ``replica_spawn_slow`` seam injects warm-up latency here."""
        ch = chaos_mod.get()
        if ch is not None:
            delay = ch.replica_spawn_slow(replica.index)
            if delay > 0:
                time.sleep(delay)
        error: Optional[str] = None
        try:
            replica.start()
            if getattr(replica.spec, "role", "any") != "prefill":
                rid = replica.client.submit(
                    list(self.config.probe_prompt), max_new=2
                )
                deadline = time.time() + self.config.warm_timeout_s
                while time.time() < deadline:
                    snap = replica.client.poll(rid)
                    if snap.get("done"):
                        if snap.get("state") != "done":
                            error = f"probe ended {snap.get('state')!r}"
                        break
                    time.sleep(0.01)
                else:
                    error = "probe timed out"
        except Exception as e:  # noqa: BLE001 - warm failure aborts the event, never the pump
            error = f"{type(e).__name__}: {e}"
        with self._lock:
            if self._warm is not None and self._warm["replica"] is replica:
                self._warm["done"] = True
                self._warm["error"] = error

    def _tick_warming(self, now: float) -> None:
        cfg = self.config
        with self._lock:
            st = self._warm
        if st is None:
            with self._lock:
                self._phase = STEADY
            return
        replica = st["replica"]
        if not st["done"]:
            if now - st["started"] > cfg.warm_timeout_s + 5.0:
                replica.kill()
                self._journal(
                    "fleet.scale.blocked", now, replica=replica.index,
                    reason="warm_timeout",
                )
                self.router.log(
                    f"autoscale: warm timeout on replica {replica.index}; aborted"
                )
                with self._lock:
                    self._warm = None
                    self._phase = STEADY
            return
        if st["error"] is not None:
            replica.kill()
            self._journal(
                "fleet.scale.blocked", now, replica=replica.index,
                reason="warm_failed", error=st["error"],
            )
            self.router.log(
                f"autoscale: warm failed on replica {replica.index}: "
                f"{st['error']}"
            )
            with self._lock:
                self._warm = None
                self._phase = STEADY
            return
        # warmed: admit behind the half-open probation gate
        self.router.admit_replica(replica, probation=True)
        self._journal(
            "fleet.scale.admitted", now, replica=replica.index,
            role=getattr(replica.spec, "role", "any"),
            warm_s=round(now - st["started"], 3),
        )
        with self._lock:
            self._warm = None
            if st["revert"]:
                self._phase = STEADY
            else:
                self._phase = GUARD
                self._guard = {
                    "direction": "up",
                    "since": now,
                    "baseline": st["baseline"],
                    "replica": replica.index,
                }

    # ------------------------------------------------------------- scale-down

    def _pick_victim(self) -> Optional[Replica]:
        """Least-loaded retireable replica. In a disaggregated fleet the
        over-represented role's pool gives up the victim; the last
        decode-capable replica is never a candidate."""
        router = self.router
        with router._lock:
            decode = [
                r for r in router.replicas
                if getattr(r.spec, "role", "any") != "prefill"
                and r.state == UP and r.index not in router._draining
            ]
            prefill = [
                r for r in router.replicas
                if getattr(r.spec, "role", "any") == "prefill"
                and r.state == UP and r.index not in router._draining
            ]
            n_total = len(decode) + len(prefill)
            if self._target_prefill_frac > 0 and n_total > 1:
                frac = len(prefill) / n_total
                if frac > self._target_prefill_frac and len(prefill) > 1:
                    return prefill[-1]
            if len(decode) <= 1:
                return None

            def load(r: Replica) -> Tuple[int, int, int]:
                stats = router._stats_cache.get(r.index) or {}
                return (
                    int(stats.get("active_slots") or 0),
                    int(stats.get("queue_depth") or 0),
                    -r.index,  # tie-break: retire the newest
                )

            return min(decode, key=load)

    def _begin_scale_down(
        self,
        now: float,
        reason: str,
        victim: Optional[Replica] = None,
        revert: bool = False,
    ) -> bool:
        victim = victim or self._pick_victim()
        if victim is None:
            return False
        router = self.router
        baseline = None if revert else self._attainment(now, self.config.guard_window_s)
        router.begin_drain(victim.index)
        with self._lock:
            self._phase = DRAINING
            self._last_event_ts = now
            self._drain = {
                "replica": victim,
                "started": now,
                "spilled": False,
                "revert": revert,
                "baseline": baseline,
                "reason": reason,
            }
            self._journal_locked(
                "fleet.scale.down", now, replica=victim.index,
                role=getattr(victim.spec, "role", "any"),
                reason=reason, revert=revert,
            )
        router.telemetry.count("fleet.scale_events")
        router.log(f"autoscale: draining replica {victim.index} ({reason})")
        return True

    def _tick_draining(self, now: float) -> None:
        cfg = self.config
        with self._lock:
            st = self._drain
        if st is None:
            with self._lock:
                self._phase = STEADY
            return
        victim = st["replica"]
        router = self.router
        ch = chaos_mod.get()
        if (
            victim.state == UP
            and ch is not None
            and ch.replica_kill_mid_drain(victim.index)
        ):
            router.log(
                f"chaos: killing replica {victim.index} mid-drain"
            )
            victim.kill()
        if victim.state != UP:
            # killed mid-drain: the router's down path already requeued its
            # streams (the PR 6 fallback); finish the retire bookkeeping
            router.sweep_now()
            router.retire_replica(victim)
            self._finish_drain(now, st, mode="kill_fallback")
            return
        remaining = router.inflight_on(victim.index)
        if remaining and not st["spilled"] and now - st["started"] > cfg.drain_grace_s:
            moved = router.spill_and_requeue(victim.index)
            with self._lock:
                if self._drain is st:
                    st["spilled"] = True
            router.log(
                f"autoscale: drain grace over on replica {victim.index}; "
                f"spilled + requeued {moved} stream(s)"
            )
            remaining = router.inflight_on(victim.index)
        if remaining == 0:
            router.retire_replica(victim, timeout=cfg.drain_timeout_s)
            self._finish_drain(now, st, mode="drained")
            return
        if now - st["started"] > cfg.drain_timeout_s:
            # wedged drain: hard-kill; the down path requeues (fallback)
            router.log(
                f"autoscale: drain timeout on replica {victim.index}; killing"
            )
            victim.kill()

    def _finish_drain(self, now: float, st: Dict[str, Any], mode: str) -> None:
        victim = st["replica"]
        drain_ms = (now - st["started"]) * 1e3
        self.router.telemetry.histogram("fleet.drain_ms", drain_ms)
        self._journal(
            "fleet.scale.retired", now, replica=victim.index, mode=mode,
            drain_ms=round(drain_ms, 1),
        )
        with self._lock:
            self._drain = None
            if st["revert"]:
                self._phase = STEADY
            else:
                self._phase = GUARD
                self._guard = {
                    "direction": "down",
                    "since": now,
                    "baseline": st["baseline"],
                    "replica": victim.index,
                }

    # ----------------------------------------------------------------- guard

    def _tick_guard(self, now: float) -> None:
        """Post-scale trial window, the autopilot controller shape: commit
        when attainment holds, auto-revert the event on regression."""
        cfg = self.config
        with self._lock:
            st = self._guard
        if st is None:
            with self._lock:
                self._phase = STEADY
            return
        if now - st["since"] < cfg.guard_window_s:
            return
        before = st["baseline"]
        after = self._attainment(now, cfg.guard_window_s)
        regressed = (
            before is not None
            and after is not None
            and after < before * (1.0 - cfg.regress_tol)
        )
        if regressed and st["direction"] == "up":
            obs = self.observe(now)
            if obs.brownout_level >= cfg.escalate_level or (
                obs.util is not None and obs.util > cfg.target_util
            ):
                # the regression is explained by the overload the
                # scale-out answered — a storm keeps blowing attainment
                # down while the backlog's doomed requests complete —
                # not by the new replica. Reverting capacity here would
                # fight the brownout ladder (the no-fight rule), so
                # re-arm the window against the degraded level and judge
                # again once pressure moves.
                with self._lock:
                    if self._guard is st:
                        self._guard = {**st, "since": now, "baseline": after}
                self._journal(
                    "fleet.scale.guard_extended", now,
                    direction=st["direction"], replica=st["replica"],
                    brownout=obs.brownout_level,
                    attainment=round(after, 4),
                )
                return
        with self._lock:
            self._guard = None
            self._phase = STEADY
        if not regressed:
            self._journal(
                "fleet.scale.committed", now, direction=st["direction"],
                replica=st["replica"],
                before=None if before is None else round(before, 4),
                after=None if after is None else round(after, 4),
            )
            return
        self._journal(
            "fleet.scale.rollback", now, direction=st["direction"],
            replica=st["replica"], before=round(before, 4),
            after=round(after, 4),
        )
        self.router.log(
            f"autoscale: ROLLBACK scale-{st['direction']} "
            f"(attainment {before:.3f} -> {after:.3f})"
        )
        if st["direction"] == "down":
            # the retired capacity was load-bearing: respawn a replacement
            self._begin_scale_up(now, reason="rollback", revert=True)
        else:
            # the added replica regressed the fleet: drain it back out
            victim = None
            with self.router._lock:
                for r in self.router.replicas:
                    if r.index == st["replica"]:
                        victim = r
                        break
            if victim is not None:
                self._begin_scale_down(
                    now, reason="rollback", victim=victim, revert=True
                )

    # ------------------------------------------------------------------ status

    def snapshot(self) -> Dict[str, Any]:
        """For FSTATS/STATUS and the monitor's autoscale line."""
        cfg = self.config
        with self._lock:
            last = self.events[-1] if self.events else None
            return {
                "phase": self._phase,
                "min_replicas": cfg.min_replicas,
                "max_replicas": cfg.max_replicas,
                "target_util": cfg.target_util,
                "cooldown_s": cfg.scale_cooldown_s,
                "at_capacity": self._at_capacity,
                "last_event": dict(last) if last else None,
                "events": [dict(e) for e in self.events],
            }
