"""SLO-aware request router over N serving replicas.

The router is the fleet's only public address. It speaks the exact verb set
a single :class:`~maggy_tpu.serve.server.ServeServer` speaks — SUBMIT /
POLL / CANCEL / SSTATS / STATUS / LOG over :mod:`maggy_tpu.core.rpc` — so
every existing client (:class:`~maggy_tpu.serve.ServeClient`, the monitor
dashboard) points at a fleet unchanged. Behind the verbs:

* **Routing.** SUBMIT mints a *router-owned* request id and places the
  request on the least-loaded healthy replica (cached SSTATS: queue depth,
  slot occupancy, TTFT percentiles). The id -> replica binding is sticky:
  POLL and CANCEL always reach the replica that owns the request — and the
  binding, not the replica, is durable: when a replica dies its requests are
  re-bound, the id never changes.
* **SLO-aware admission.** With ``slo_ttft_ms`` set, each SUBMIT is checked
  against the best replica's *projected TTFT* (see ``projected_ttft_ms``).
  Projection over budget either sheds the request with a 429-style ``BUSY``
  reply (``admission="shed"``) or parks it in the router queue until
  capacity frees (``admission="queue"``, the default). No healthy replica
  at all always sheds.
* **Health + requeue.** A pump thread probes replicas (SSTATS heartbeat)
  and feeds failures into :class:`maggy_tpu.resilience.QuarantineTracker` —
  the same policy object that benches flaky HPO workers. A quarantined or
  dead replica's in-flight requests are requeued *ahead of* fresh arrivals
  (the retry-queue-outranks-suggestions rule the HPO driver uses) and
  resubmitted to survivors; until redispatch, POLL reports
  ``state="requeued"``. Dead replicas are respawned within
  ``max_restarts``. The chaos seam
  (``MAGGY_TPU_CHAOS="replica_kill:replica=N"``) kills a busy replica
  deterministically so all of this is testable on one CPU.
* **Autoscaling** (opt-in, docs/fleet.md "Autoscaling"). An
  :class:`~maggy_tpu.serve.fleet.autoscale.Autoscaler` ticked by the pump
  grows/shrinks the fleet from its own time-series: scale-up admits a
  warmed replica behind a half-open probation gate
  (:meth:`admit_replica`); scale-down drains a victim — dispatch stops
  (:meth:`begin_drain`), in-flight waves finish or are spilled and
  requeued to survivors (:meth:`spill_and_requeue`), then the replica and
  every per-replica trace of it are removed (:meth:`retire_replica`).

* **Disaggregated prefill/decode.** Replicas tagged ``role="prefill"``
  (:class:`~maggy_tpu.serve.fleet.replica.ReplicaSpec`) never receive
  SUBMIT dispatches; instead the pump runs each accepted prompt through a
  :class:`~maggy_tpu.serve.fleet.prefill.PrefillWorker` first and hands
  the resulting KV pack to the chosen decode replica
  (``Engine.admit_from_kv`` — the device-put/serialization path).
  ``req.prefilled``/``req.handoff`` events mark the hop on the request's
  trace lane and ``serve.handoff_ms`` measures it; when every prefill
  replica is down the router falls back to plain dispatch (decode replicas
  keep a full engine). See docs/fleet.md "Disaggregated prefill/decode".

Handlers run on the RPC event loop and only touch lock-guarded host state;
every downstream socket round-trip (dispatch, poll fan-out, probes) belongs
to the pump thread.
"""

from __future__ import annotations

import dataclasses
import secrets as secrets_mod
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu import telemetry
from maggy_tpu.core import lockdebug, rpc
from maggy_tpu.exceptions import RpcError, RpcRejectedError
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.resilience.policy import QuarantineTracker
from maggy_tpu.serve.fleet.prefill import (
    PrefillWorker,
    PrefillWorkerError,
    pick_worker,
)
from maggy_tpu.serve.fleet.replica import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    DEAD,
    UP,
    CircuitBreaker,
    Replica,
    RetryBudget,
)
from maggy_tpu.serve.prefix import PrefixIndex
from maggy_tpu.serve.qos import BEST_EFFORT, QOS_CLASSES, validate_qos
from maggy_tpu.serve.scheduler import LATENCY_SIGNALS
from maggy_tpu.serve.tier import FleetPrefixMap
from maggy_tpu.telemetry import timeseries, tracing
from maggy_tpu.telemetry.alerts import AlertEvaluator
from maggy_tpu.telemetry.histogram import merge_dicts

# fleet series surfaced as sparkline trends on the monitor panel
TREND_SIGNALS = (
    "serve.queue_depth",
    "serve.tokens_per_sec",
    "serve.ttft_ms",
    "fleet.healthy_replicas",
    "serve.fragmentation",
    "mem.headroom_pct",
)

# router-side request states (downstream states pass through verbatim)
PENDING = "pending"  # accepted, not yet on a replica
ROUTED = "routed"  # live on a replica
REQUEUED = "requeued"  # owner died; waiting for redispatch


@dataclasses.dataclass
class RouterConfig:
    """Admission and health knobs (docs/fleet.md "Admission control")."""

    slo_ttft_ms: Optional[float] = None  # None: admit everything
    admission: str = "queue"  # "queue" | "shed" when projection > SLO
    max_queue: int = 1024  # router-side pending bound
    probe_interval_s: float = 0.25  # SSTATS heartbeat cadence
    pump_interval_s: float = 0.005  # dispatch/poll loop cadence
    quarantine_threshold: int = 2  # consecutive probe failures
    quarantine_cooldown_s: float = 30.0
    max_restarts: int = 1  # fleet-wide respawn budget
    default_service_ms: float = 100.0  # TTFT prior before any p50 exists
    # gray-failure circuit breakers (docs/resilience.md): a replica whose
    # windowed TTFT p95 exceeds breaker_ratio x the best healthy peer's
    # (and breaker_min_ms absolute) for breaker_trips consecutive metric
    # ticks is ejected from dispatch; after breaker_cooldown_s, half-open
    # probation probes close it on recovery
    breaker_ratio: float = 3.0
    breaker_min_ms: float = 50.0
    breaker_window_s: float = 10.0
    breaker_trips: int = 2
    breaker_cooldown_s: float = 5.0
    # brownout ladder (docs/fleet.md "QoS classes & graceful degradation"):
    # while the TTFT SLO burn-rate alert fires, degrade best-effort one
    # step per brownout_escalate_s (clamp max_new → queue-only → shed);
    # step back down one level per brownout_recover_s of clean burn
    brownout_clamp_tokens: int = 8
    brownout_escalate_s: float = 3.0
    brownout_recover_s: float = 5.0
    # per-replica requeue budget: a flapping replica may inject at most
    # retry_budget requeues per retry_budget_window_s; beyond that the
    # requeues are deferred (never dropped) so storms can't amplify load
    retry_budget: int = 8
    retry_budget_window_s: float = 10.0
    # prefix-affinity routing (docs/fleet.md "Fleet-global KV"): a replica
    # the fleet prefix map reports holding this prompt's prefix resident
    # gets this many ms subtracted from its projected TTFT — roughly the
    # prefill time the resident prefix saves. 0 disables; the autopilot
    # tunes it (``fleet.affinity_weight``) and brownout level >= 2 zeroes
    # it so affinity never fights load-shedding under overload
    affinity_weight_ms: float = 25.0

    def validate(self) -> None:
        if self.admission not in ("queue", "shed"):
            raise ValueError(
                f"admission must be 'queue' or 'shed', got {self.admission!r}"
            )


def projected_ttft_ms(stats: Dict[str, Any], prior_ms: float) -> float:
    """Projected time-to-first-token on a replica with these SSTATS.

    The model is deliberately simple and stated so operators can reason
    about sheds: a free slot with an empty queue costs one prefill
    (~observed TTFT p50, or the prior before one exists); otherwise the
    request waits behind ``queue_depth`` others served ``num_slots`` at a
    time, each wave costing roughly one observed TTFT."""
    p50 = stats.get("ttft_ms_p50") or prior_ms
    free = stats.get("num_slots", 1) - stats.get("active_slots", 0)
    depth = stats.get("queue_depth", 0)
    if free > 0 and depth == 0:
        return float(p50)
    waves = (depth + 1) / max(1, stats.get("num_slots", 1))
    return float(p50) * (1.0 + waves)


# brownout ladder levels, in escalation order (docs/fleet.md "QoS classes
# & graceful degradation"); the level is the fleet.brownout_level gauge
BROWNOUT_LEVELS = ("normal", "clamp", "queue", "shed")


class BrownoutLadder:
    """Hysteretic stepwise degradation of best-effort traffic.

    While the SLO burn-rate alert fires, escalate one level per
    ``escalate_s``: 1 clamps best-effort ``max_new`` at dispatch, 2 parks
    best-effort in the router queue (dispatch skips it), 3 sheds
    best-effort at admission with a typed BUSY. While the alert is clear,
    recover one level per ``recover_s``. Single-step transitions in both
    directions — never a cliff where premium misses SLO while best-effort
    streams, and never a thundering re-admission when the burn clears.

    Stepped by the pump's metrics tick, read by the RPC admission handler
    and the dispatch loop; the lock makes each timed transition atomic.
    """

    def __init__(self, escalate_s: float = 3.0, recover_s: float = 5.0):
        self.escalate_s = float(escalate_s)
        self.recover_s = float(recover_s)
        self._lock = lockdebug.lock("router.brownout")
        self._level = 0  # guarded-by: _lock
        self._burn_since: Optional[float] = None  # guarded-by: _lock
        self._clear_since: Optional[float] = None  # guarded-by: _lock
        # (ts, level) transition log — deterministic test/ops evidence
        self.history: List[Tuple[float, int]] = []  # guarded-by: _lock

    def level(self) -> int:
        with self._lock:
            return self._level

    def step(self, burning: bool, now: float) -> Tuple[int, Optional[str]]:  # thread-entry — router pump's ~1 Hz metrics tick
        """Advance the ladder one tick; returns (level, transition) where
        transition is ``"escalated"``/``"recovered"`` when the level moved."""
        with self._lock:
            transition = None
            if burning:
                self._clear_since = None
                if self._burn_since is None:
                    self._burn_since = now
                if (
                    self._level < len(BROWNOUT_LEVELS) - 1
                    and now - self._burn_since >= self.escalate_s
                ):
                    self._level += 1
                    self._burn_since = now  # one step per escalate_s
                    self.history.append((now, self._level))
                    transition = "escalated"
            else:
                self._burn_since = None
                if self._clear_since is None:
                    self._clear_since = now
                if (
                    self._level > 0
                    and now - self._clear_since >= self.recover_s
                ):
                    self._level -= 1
                    self._clear_since = now  # one step per recover_s
                    self.history.append((now, self._level))
                    transition = "recovered"
            return self._level, transition

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "level": self._level,
                "name": BROWNOUT_LEVELS[self._level],
                "history": [(round(t, 3), lvl) for t, lvl in self.history],
            }


@dataclasses.dataclass
class RouteEntry:
    """One router-owned request and its sticky downstream binding."""

    rid: str
    payload: Dict[str, Any]  # submit kwargs, replayable on requeue
    # request-scoped trace id: adopted from the client's SUBMIT frame (or
    # minted here for traceless clients) and forwarded on every downstream
    # dispatch — durable across replica death, like the rid
    trace: Optional[str] = None
    state: str = PENDING
    replica: Optional[int] = None
    remote_id: Optional[str] = None
    snapshot: Optional[Dict[str, Any]] = None  # last downstream POLL
    final: Optional[Dict[str, Any]] = None  # router-local terminal snapshot
    submitted_ts: float = dataclasses.field(default_factory=time.time)
    deadline_ts: Optional[float] = None
    resubmits: int = 0
    cancel_requested: bool = False
    cancel_sent: bool = False
    counted_done: bool = False
    # retry-budget damping: a requeue charged against an exhausted budget
    # waits until this instant before redispatch (deferred, never dropped)
    not_before_ts: Optional[float] = None

    @property
    def qos(self) -> str:
        return self.payload.get("qos", BEST_EFFORT)

    def done(self) -> bool:
        if self.final is not None:
            return True
        return bool(self.snapshot and self.snapshot.get("done"))

    def wire(self) -> Dict[str, Any]:
        """POLL reply: downstream snapshot under the ROUTER id."""
        if self.final is not None:
            body = dict(self.final)
        elif self.state == ROUTED and self.snapshot is not None:
            body = dict(self.snapshot)
        else:
            body = {
                "state": "queued" if self.state == PENDING else REQUEUED,
                "tokens": [],
                "n_tokens": 0,
                "prompt_len": len(self.payload.get("prompt", [])),
                "error": None,
                "ttft_ms": None,
                "tenant": self.payload.get("tenant"),
                "qos": self.qos,
                "done": False,
            }
        body["id"] = self.rid
        body["trace"] = self.trace
        body["replica"] = self.replica
        body["resubmits"] = self.resubmits
        return body


class Router:
    """Fleet front-end: one RPC server, N replicas, one pump thread."""

    def __init__(
        self,
        replicas: List[Replica],
        config: Optional[RouterConfig] = None,
        secret: Optional[str] = None,
        name: str = "maggy-fleet",
        telemetry_recorder=None,
        autopilot=None,
        autoscale=None,
    ):
        self.config = config or RouterConfig()
        self.config.validate()
        self.replicas = list(replicas)
        self.name = name
        self.telemetry = telemetry_recorder or telemetry.get()
        # autopilot (docs/autotune.md): an online controller the pump
        # thread ticks — admission/SLO knobs move under the fleet guard
        self.autopilot = None
        if autopilot is not None and autopilot is not False:
            from maggy_tpu.autopilot import (
                AutopilotConfig,
                Controller,
                RouterTarget,
            )

            cfg = autopilot if isinstance(autopilot, AutopilotConfig) else None
            self.autopilot = (
                autopilot
                if isinstance(autopilot, Controller)
                else Controller(
                    RouterTarget(self),
                    config=cfg,
                    telemetry_recorder=self.telemetry,
                )
            )
        # disaggregation: prefill-role replicas become pump-owned prefill
        # workers and are excluded from SUBMIT dispatch
        self.prefill_workers = [
            PrefillWorker(r)
            for r in self.replicas
            if getattr(r.spec, "role", "any") == "prefill"
        ]
        if self.prefill_workers and not any(
            getattr(r.spec, "role", "any") != "prefill" for r in self.replicas
        ):
            raise ValueError(
                "a disaggregated fleet needs at least one decode-capable "
                "replica (role 'decode' or 'any')"
            )
        self._pw_rr = 0  # prefill-worker round-robin cursor
        self._rpc = rpc.Server(num_executors=0, secret=secret)
        self._rpc.telemetry = self.telemetry
        self.quarantine = QuarantineTracker(
            threshold=self.config.quarantine_threshold,
            cooldown=self.config.quarantine_cooldown_s,
        )
        self._lock = lockdebug.rlock("router._lock")
        self._entries: Dict[str, RouteEntry] = {}
        self._pending: deque = deque()  # rids; requeues go left, fresh right
        self._stats_cache: Dict[int, Dict[str, Any]] = {}
        self._down_handled: set = set()  # replica idx whose death was requeued
        # replicas mid-retirement (autoscaler drain protocol): no new
        # dispatch, still polled so in-flight waves finish  # guarded-by: _lock
        self._draining: set = set()
        # next fleet index for autoscaler-spawned replicas (indices are
        # never reused; they key breakers, stores, the prefix map)
        self._next_index = (
            max((r.index for r in self.replicas), default=-1) + 1
        )  # guarded-by: _lock
        self._restarts_used = 0
        self._rr = 0  # round-robin tie-break cursor
        self.counters: Dict[str, int] = {
            "routed": 0,
            "requeued": 0,
            "shed": 0,
            "completed": 0,
            "failed": 0,
            "expired": 0,
            "cancelled": 0,
            "respawned": 0,
            # disaggregation: prompts run on a prefill replica, and KV
            # packs handed to a decode replica (docs/fleet.md)
            "prefilled": 0,
            "handoffs": 0,
            # requeues damped by an exhausted per-replica retry budget and
            # best-effort dispatches clamped by the brownout ladder
            "retry_deferred": 0,
            "brownout_clamped": 0,
            # prefix-affinity routing: picks that landed on a replica the
            # fleet prefix map reported resident vs. picks where holders
            # existed but load won (docs/fleet.md "Fleet-global KV")
            "affinity_hits": 0,
            "affinity_misses": 0,
        }
        # exact SLO attainment at the fleet edge: counted per completed
        # request against the configured TTFT budget (histogram-derived
        # attainment in SSTATS is the bucket-resolution view of the same)
        self.slo_ok = 0
        self.slo_miss = 0
        # per-QoS-class split of the same fleet-edge judgement, so the
        # no-cliff property (premium holds while best-effort degrades) is
        # observable from SSTATS alone  # guarded-by: _lock
        self.slo_by_class: Dict[str, Dict[str, int]] = {
            c: {"ok": 0, "miss": 0} for c in QOS_CLASSES
        }
        # gray-failure circuit breakers + requeue budgets, one per replica
        # (docs/resilience.md "Gray failure & circuit breakers"); breakers
        # are scored by the pump's metrics tick and filter dispatch
        cfg = self.config
        self.breakers: Dict[int, CircuitBreaker] = {
            r.index: CircuitBreaker(
                r.index, trips=cfg.breaker_trips,
                cooldown_s=cfg.breaker_cooldown_s,
            )
            for r in self.replicas
        }
        self.retry_budgets: Dict[int, RetryBudget] = {
            r.index: RetryBudget(cfg.retry_budget, cfg.retry_budget_window_s)
            for r in self.replicas
        }
        # fleet prefix map (docs/fleet.md "Fleet-global KV"): digest ->
        # replicas holding it resident, fed from the SSTATS residency
        # snapshots the pump already polls; read at dispatch for the
        # affinity bonus
        self.prefix_map = FleetPrefixMap()
        # brownout ladder: stepped by the pump tick off the SLO burn alert
        self.brownout = BrownoutLadder(
            escalate_s=cfg.brownout_escalate_s,
            recover_s=cfg.brownout_recover_s,
        )
        # shed sequence staggers retry_after_ms hints so synchronized
        # clients desynchronize instead of re-storming  # guarded-by: _lock
        self._shed_seq = 0
        self._log: deque = deque(maxlen=500)
        self._closing = False
        self._stop = threading.Event()
        self._pump: Optional[threading.Thread] = None
        self._started_ts = time.time()
        # fleet observability (docs/observability.md "Time series"): one
        # store per replica fed from the SSTATS probe cache, plus a
        # fleet-aggregate store fed at the *same* tick with the bucket-wise
        # merge — the alignment that lets tools/metrics_query.py reproduce
        # fleet windowed percentiles from per-replica snapshots. Alert
        # rules run at fleet scope over the aggregate store.
        self.metrics = timeseries.SeriesStore()
        self.replica_metrics: Dict[int, timeseries.SeriesStore] = {}
        self.alerts = AlertEvaluator(self.metrics, self.telemetry, scope="fleet")
        self._last_metrics_tick = 0.0
        for verb, handler in (
            ("SUBMIT", self._on_submit),
            ("POLL", self._on_poll),
            ("CANCEL", self._on_cancel),
            ("SSTATS", self._on_stats),
            ("STATUS", self._on_status),
            ("LOG", self._on_log),
        ):
            self._rpc.register_callback(verb, handler)
        self._rpc.register_metrics(self._metrics_body)
        # fleet autoscaler (docs/fleet.md "Autoscaling"): ticked by the
        # pump after each metrics tick; drain/admit seams below are its
        # only write surface into the fleet
        self.autoscaler = None
        if autoscale is not None and autoscale is not False:
            from maggy_tpu.serve.fleet.autoscale import (
                AutoscaleConfig,
                Autoscaler,
            )

            self.autoscaler = (
                autoscale
                if isinstance(autoscale, Autoscaler)
                else Autoscaler(
                    self,
                    config=(
                        autoscale
                        if isinstance(autoscale, AutoscaleConfig)
                        else None
                    ),
                )
            )

    @property
    def secret(self) -> str:
        return self._rpc.secret

    # -------------------------------------------------------------- lifecycle

    def start(self, host: str = "0.0.0.0", port: int = 0) -> Tuple[str, int]:
        for replica in self.replicas:
            if replica.state != UP:
                replica.secret = self.secret
                replica.start()
                self.log(
                    f"replica {replica.index} up at "
                    f"{replica.addr[0]}:{replica.addr[1]}"
                )
        addr = self._rpc.start(host=host, port=port)
        self._stop.clear()
        self._pump = threading.Thread(
            target=self._pump_loop, name="maggy-fleet-pump", daemon=True
        )
        self._pump.start()
        self.log(
            f"router on {addr[0]}:{addr[1]} ({len(self.replicas)} replicas, "
            f"slo_ttft_ms={self.config.slo_ttft_ms}, "
            f"admission={self.config.admission})"
        )
        return addr

    def stop(self, drain_timeout: float = 30.0) -> None:
        """Clean shutdown: stop admitting, let replicas finish resident
        work, then close sockets — in that order, so no accepted request is
        dropped by the shutdown itself."""
        with self._lock:
            self._closing = True
        deadline = time.time() + drain_timeout
        while time.time() < deadline:
            with self._lock:
                live = any(
                    not e.done()
                    for e in self._entries.values()
                )
            if not live:
                break
            time.sleep(0.02)
        self._stop.set()
        if self._pump is not None:
            self._pump.join(timeout=5.0)
            self._pump = None
        for replica in self.replicas:
            # replica drain is second-layer insurance (their own queues)
            replica.stop(drain=replica.state == UP, timeout=drain_timeout)
        self._rpc.stop()

    def log(self, line: str) -> None:
        self._log.append(f"[{time.strftime('%H:%M:%S')}] {line}")

    # ------------------------------------------------------------ projections

    def _healthy(self) -> List[Replica]:
        """Dispatch targets: healthy decode-capable replicas (prefill-only
        replicas are PrefillWorkers, never SUBMIT targets; draining
        replicas finish their waves but take nothing new)."""
        now = time.time()
        return [
            r
            for r in self.replicas
            if r.state == UP
            and getattr(r.spec, "role", "any") != "prefill"
            and r.index not in self._draining
            and not self.quarantine.is_quarantined(r.index, now)
        ]

    def _replica(self, index: int) -> Optional[Replica]:
        """Replica by fleet index. Positional indexing into
        ``self.replicas`` is wrong once the autoscaler has retired or
        added replicas — indices are sparse and never reused."""
        for r in self.replicas:
            if r.index == index:
                return r
        return None

    def _pick_replica(  # guarded-by: _lock
        self,
        healthy: List[Replica],
        digest: Optional[str] = None,
        affinity_ms: float = 0.0,
    ) -> Tuple[Replica, float]:
        """Least projected TTFT; round-robin cursor breaks ties so equal
        replicas share load instead of all traffic piling on index 0.

        With a prompt ``digest``, replicas the fleet prefix map reports
        holding that prefix resident get ``affinity_ms`` subtracted from
        their projection (docs/fleet.md "Fleet-global KV") — a bounded
        nudge, so a genuinely overloaded holder still loses the pick; the
        caller zeroes the bonus at brownout level >= 2."""
        cfg = self.config
        holders = (
            self.prefix_map.replicas_for(digest)
            if digest is not None and affinity_ms > 0
            else frozenset()
        )
        # dispatches the replica hasn't reported yet (routed, no poll
        # snapshot) count against its queue now — within one dispatch
        # sweep the stats cache is frozen, so without this correction the
        # whole pending queue dumps on whichever replica reported least
        # loaded at the last probe tick
        unseen: Dict[int, int] = {}
        for e in self._entries.values():
            if e.state == ROUTED and e.snapshot is None and not e.done():
                unseen[e.replica] = unseen.get(e.replica, 0) + 1
        scored = []
        for offset in range(len(healthy)):
            r = healthy[(self._rr + offset) % len(healthy)]
            stats = self._stats_cache.get(r.index, {})
            extra = unseen.get(r.index, 0)
            if extra:
                stats = dict(
                    stats, queue_depth=stats.get("queue_depth", 0) + extra
                )
            proj = projected_ttft_ms(stats, cfg.default_service_ms)
            if r.index in holders:
                proj -= affinity_ms
            scored.append((proj, r))
        proj, best = min(scored, key=lambda pr: pr[0])
        self._rr += 1
        if holders:
            if best.index in holders:
                self.counters["affinity_hits"] += 1
                self.telemetry.count("tier.affinity_hits")
            else:
                self.counters["affinity_misses"] += 1
                self.telemetry.count("tier.affinity_misses")
        return best, proj

    # ------------------------------------------------------- autoscaler seams
    # (pump-thread internals, invoked via Autoscaler.tick — the drain
    # protocol's write surface; like the rest of the pump machinery, the
    # pump thread is the only writer and compound writes hold _lock)

    def allocate_index(self) -> int:
        """Mint a fleet index for a new replica. Indices are never
        reused: every per-replica structure (breakers, SeriesStores, the
        prefix map) keys on them."""
        with self._lock:
            index = self._next_index
            self._next_index += 1
            return index

    def admit_replica(self, replica: Replica, probation: bool = True) -> None:
        """Add a started, warmed replica to the dispatch set. Its breaker
        and quarantine state are built fresh — admission on stale
        pre-spawn samples is the bug class the respawn path also guards
        against. With ``probation`` the breaker starts HALF_OPEN, so the
        dispatch loop's probation-first path routes one canary request at
        a time; only an observed TTFT under the close bar (the TTFT SLO,
        or 10x the service prior without one) closes it and lets the
        replica take weighted traffic (docs/fleet.md "Autoscaling")."""
        cfg = self.config
        breaker = CircuitBreaker(
            replica.index, trips=cfg.breaker_trips,
            cooldown_s=cfg.breaker_cooldown_s,
        )
        if probation:
            close_below = (
                cfg.slo_ttft_ms
                if cfg.slo_ttft_ms is not None
                else 10.0 * cfg.default_service_ms
            )
            breaker.begin_probation(close_below)
        self.quarantine.record_success(replica.index)
        with self._lock:
            # indices are never reused, even when the replica was built
            # outside allocate_index()
            self._next_index = max(self._next_index, replica.index + 1)
            self.replicas = self.replicas + [replica]
            self.breakers[replica.index] = breaker
            self.retry_budgets[replica.index] = RetryBudget(
                cfg.retry_budget, cfg.retry_budget_window_s
            )
            self._stats_cache.pop(replica.index, None)
            self.replica_metrics.pop(replica.index, None)
            self._down_handled.discard(replica.index)
            self._draining.discard(replica.index)
            if getattr(replica.spec, "role", "any") == "prefill":
                self.prefill_workers = self.prefill_workers + [
                    PrefillWorker(replica)
                ]
        self.log(
            f"replica {replica.index} admitted"
            f"{' (probation)' if probation else ''}"
        )

    def begin_drain(self, index: int) -> None:
        """Drain protocol step 1: stop dispatching to the replica without
        touching its liveness. Routed entries keep polling, so in-flight
        waves finish on the victim; the death path skips respawn for a
        draining replica (retirement is deliberate, not a failure)."""
        with self._lock:
            self._draining.add(index)
        self.log(f"replica {index} draining (dispatch stopped)")

    def inflight_on(self, index: int) -> int:
        """Streams still live on a replica (the drain's exit condition)."""
        with self._lock:
            return sum(
                1
                for e in self._entries.values()
                if e.replica == index and e.state == ROUTED and not e.done()
            )

    def spill_and_requeue(self, index: int) -> int:
        """Drain protocol step 2 (when the grace expires): move the
        victim's remaining streams to survivors. Each downstream request
        is cancelled — the victim's scheduler frees its pages, and
        reusable prefix KV spills through the host tier seam on release
        (docs/serving.md "Host-DRAM page tier") — and the router entry is
        requeued ahead of fresh arrivals. Byte-identical by construction:
        engine output is a pure function of (params, prompt, seed), so
        the replay on a survivor regenerates exactly the tokens the
        victim would have produced."""
        replica = self._replica(index)
        moved: List[Tuple[RouteEntry, Optional[str]]] = []
        with self._lock:
            for entry in self._entries.values():
                if (
                    entry.replica == index
                    and entry.state == ROUTED
                    and not entry.done()
                ):
                    remote = entry.remote_id
                    entry.state = REQUEUED
                    entry.replica = None
                    entry.remote_id = None
                    entry.snapshot = None
                    entry.resubmits += 1
                    entry.not_before_ts = None
                    self._pending.appendleft(entry.rid)
                    self.counters["requeued"] += 1
                    moved.append((entry, remote))
        for entry, remote in moved:
            if replica is not None and replica.state == UP and remote:
                try:
                    replica.client.cancel(remote)
                except (RpcError, OSError):
                    pass  # victim half-gone: requeue already happened
            self.telemetry.event(
                "req.requeued", trace=entry.trace, rid=entry.rid,
                replica=index, resubmits=entry.resubmits,
            )
        if moved:
            self.telemetry.count("fleet.requeued", len(moved))
        return len(moved)

    def rebalance_excess(self) -> int:
        """Shed routed-but-unstarted backlog back into the shared queue
        when capacity comes online (a scale-up's probation breaker
        closes, or a gray replica recovers). Work dispatched before the
        fleet widened stays pinned to the replica that absorbed it — the
        victim of the very overload that triggered the scale-out — so a
        fresh replica would otherwise only ever see new arrivals. Each
        replica keeps two waves per slot; anything beyond that which has
        not produced a token yet is cancelled downstream and requeued
        (byte-identical for the same reason the drain spill is: output
        is a pure function of (params, prompt, seed))."""
        moved: List[Tuple[RouteEntry, Replica, Optional[str]]] = []
        with self._lock:
            per: Dict[int, List[RouteEntry]] = {}
            for e in self._entries.values():
                if (
                    e.state == ROUTED
                    and not e.done()
                    and e.replica is not None
                    and (
                        e.snapshot is None
                        or not e.snapshot.get("n_tokens", 0)
                    )
                ):
                    per.setdefault(e.replica, []).append(e)
            for index, entries in per.items():
                replica = self._replica(index)
                if replica is None or index in self._draining:
                    continue
                keep = 2 * int(getattr(replica.spec, "num_slots", 1) or 1)
                if len(entries) <= keep:
                    continue
                # oldest stay (they are next to start); the tail moves,
                # requeued ahead of fresh arrivals in its original order
                entries.sort(key=lambda e: e.submitted_ts)
                for entry in reversed(entries[keep:]):
                    remote = entry.remote_id
                    entry.state = REQUEUED
                    entry.replica = None
                    entry.remote_id = None
                    entry.snapshot = None
                    entry.resubmits += 1
                    entry.not_before_ts = None
                    self._pending.appendleft(entry.rid)
                    self.counters["requeued"] += 1
                    moved.append((entry, replica, remote))
        for entry, replica, remote in moved:
            if replica.state == UP and remote:
                try:
                    replica.client.cancel(remote)
                except (RpcError, OSError):
                    pass  # source replica will drop it at its own pace
            self.telemetry.event(
                "req.requeued", trace=entry.trace, rid=entry.rid,
                replica=replica.index, resubmits=entry.resubmits,
            )
        if moved:
            self.telemetry.count("fleet.requeued", len(moved))
            self.log(f"rebalanced {len(moved)} queued requests fleet-wide")
        return len(moved)

    def retire_replica(self, replica: Replica, timeout: float = 30.0) -> None:
        """Drain protocol step 3: remove the replica from the fleet for
        good — the graceful twin of the death path. Stops it cleanly when
        still UP, then forgets every per-replica trace: FleetPrefixMap
        entries, breaker, retry budget, stats cache, quarantine state, and
        the per-replica SeriesStore. A retired replica must leave no
        ghosts in FSTATS aggregates (regression-tested)."""
        index = replica.index
        if replica.state == UP:
            replica.stop(drain=True, timeout=timeout)
        self.prefix_map.forget_replica(index)
        self.quarantine.record_success(index)
        with self._lock:
            self.replicas = [r for r in self.replicas if r.index != index]
            self.prefill_workers = [
                w for w in self.prefill_workers if w.index != index
            ]
            self.breakers.pop(index, None)
            self.retry_budgets.pop(index, None)
            self._stats_cache.pop(index, None)
            self.replica_metrics.pop(index, None)
            self._down_handled.discard(index)
            self._draining.discard(index)
        self.log(f"replica {index} retired")

    def sweep_now(self) -> None:
        """Run the down-replica sweep immediately (the pump's own sweep
        already ran this iteration when a chaos kill lands mid-drain)."""
        self._sweep_down_replicas()

    # ----------------------------------------------------------------- verbs
    # (event-loop thread: lock-guarded host state only, no sockets)

    def _busy(
        self,
        why: str,
        projected: Optional[float] = None,
        trace: Optional[str] = None,
    ) -> Dict[str, Any]:
        with self._lock:
            self.counters["shed"] += 1
            seq = self._shed_seq
            self._shed_seq += 1
            # retry hint = projected router-queue drain time: pending
            # requests served num_slots at a time across healthy replicas,
            # one service interval per wave; floor keeps an empty-queue
            # shed (no healthy replica, shutdown) from hinting "now"
            slots = sum(r.spec.num_slots for r in self._healthy()) or 1
            drain_ms = max(
                100.0,
                len(self._pending) * self.config.default_service_ms / slots,
            )
        # stagger consecutive sheds across [0, drain_ms) so the retry wave
        # spreads instead of landing as one synchronized storm
        retry_ms = drain_ms + (seq % 8) * drain_ms / 8.0
        self.telemetry.count("fleet.shed")
        self.telemetry.event("req.shed", trace=trace, reason=why)
        reply: Dict[str, Any] = {"type": "BUSY", "error": why}
        if projected is not None:
            reply["projected_ttft_ms"] = round(projected, 1)
        reply["retry_after_ms"] = round(retry_ms, 1)
        # legacy field older clients sleep on; same hint, coarser unit
        reply["retry_after_s"] = round(retry_ms / 1e3, 3)
        return reply

    def _on_submit(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        prompt = msg.get("prompt")
        if not isinstance(prompt, list) or not all(
            isinstance(t, int) for t in prompt
        ):
            raise ValueError("prompt must be a list of token ids")
        qos = validate_qos(msg.get("qos"))
        tenant = str(msg.get("tenant") or "") or None
        # brownout level 3: shed best-effort at the door with a typed BUSY
        # (premium/standard admission is untouched at every level)
        if qos == BEST_EFFORT and self.brownout.level() >= 3:
            return self._busy(
                "brownout: best-effort shed", trace=msg.get("trace")
            )
        with self._lock:
            if self._closing:
                return self._busy("router shutting down")
            healthy = self._healthy()
            if not healthy:
                return self._busy("no healthy replica")
            pending_depth = len(self._pending)
            if pending_depth >= self.config.max_queue:
                return self._busy(
                    f"router queue full ({self.config.max_queue})"
                )
            cfg = self.config
            if cfg.slo_ttft_ms is not None:
                # admission control: project TTFT on the best replica, plus
                # one wave per router-queued request ahead of this one
                stats_best = min(
                    (
                        projected_ttft_ms(
                            self._stats_cache.get(r.index, {}),
                            cfg.default_service_ms,
                        )
                        for r in healthy
                    ),
                )
                backlog_ms = (
                    pending_depth
                    * cfg.default_service_ms
                    / max(1, sum(r.spec.num_slots for r in healthy))
                )
                projected = stats_best + backlog_ms
                if projected > cfg.slo_ttft_ms and cfg.admission == "shed":
                    return self._busy(
                        f"projected TTFT {projected:.0f}ms exceeds SLO "
                        f"{cfg.slo_ttft_ms:.0f}ms",
                        projected,
                    )
            rid = secrets_mod.token_hex(8)
            # adopt the client's trace id (or mint one for traceless
            # clients); it is forwarded on every downstream dispatch, so
            # the request keeps ONE trace across router, replica, and any
            # requeue-to-survivor hop
            trace = msg.get("trace") or tracing.new_trace_id()
            payload = {
                "prompt": [int(t) for t in prompt],
                "temperature": float(msg.get("temperature", 0.0)),
                "top_k": int(msg.get("top_k", 0)),
                "max_new": int(msg.get("max_new", 16)),
                "eos_id": int(msg.get("eos_id", -1)),
                "seed": int(msg.get("seed", 0)),
                "trace": trace,
                "qos": qos,
            }
            if tenant:
                payload["tenant"] = tenant
            entry = RouteEntry(rid=rid, payload=payload, trace=trace)
            deadline_s = msg.get("deadline_s")
            if deadline_s:
                entry.deadline_ts = time.time() + float(deadline_s)
                entry.payload["deadline_s"] = float(deadline_s)
            self._entries[rid] = entry
            self._pending.append(rid)
        self.telemetry.event(
            "req.accepted", trace=trace, rid=rid, plen=len(prompt),
            tenant=tenant, qos=qos,
        )
        return {"type": "SUBMIT", "id": rid}

    def _on_poll(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            entry = self._entries.get(str(msg.get("id")))
            if entry is None:
                raise ValueError(f"unknown request {msg.get('id')!r}")
            return {"type": "POLL", **entry.wire()}

    def _on_cancel(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            entry = self._entries.get(str(msg.get("id")))
            if entry is None or entry.done():
                return {"type": "CANCEL", "cancelled": False}
            entry.cancel_requested = True
            if entry.state in (PENDING, REQUEUED):
                self._finish_local(entry, "cancelled")
        return {"type": "CANCEL", "cancelled": True}

    def _finish_local(  # guarded-by: _lock
        self, entry: RouteEntry, state: str, error=None
    ) -> None:
        """Terminal without a downstream snapshot (lock held)."""
        entry.final = {
            "state": state,
            "tokens": [],
            "n_tokens": 0,
            "prompt_len": len(entry.payload.get("prompt", [])),
            "error": error,
            "ttft_ms": None,
            "done": True,
        }
        try:
            self._pending.remove(entry.rid)
        except ValueError:
            pass
        key = {"cancelled": "cancelled", "expired": "expired", "failed": "failed"}[
            state
        ]
        self.counters[key] += 1
        entry.counted_done = True

    def _fleet_stats(self) -> Dict[str, Any]:  # guarded-by: _lock
        """Aggregate + per-replica table (lock held).

        Latency is merged honestly: every replica's SSTATS carries its raw
        fixed-log-bucket histograms under ``latency``; those are added
        bucket-wise per signal (TTFT/TPOT/queue-wait/e2e), so the fleet's
        ``ttft_ms_p50/p90/p95/p99`` are true percentiles over ALL requests
        — not the slowest replica's, not a mean of means. The merged
        encodings ride out under ``latency`` for further aggregation
        (docs/observability.md)."""
        now = time.time()
        table = []
        agg = {
            "queue_depth": len(self._pending),
            "active_slots": 0,
            "num_slots": 0,
            "tokens_out": 0,
            "requests_done": 0,
            "requests_failed": 0,
            "prefix_hits": 0,
            "prefix_tokens_saved": 0,
            "prefill_calls": 0,
            # paged KV cache, summed over paged replicas (docs/serving.md)
            "pages_total": 0,
            "pages_free": 0,
            "pages_shared": 0,
            "preemptions": 0,
        }
        latency_dicts: Dict[str, List[Dict[str, Any]]] = {
            name: [] for name in LATENCY_SIGNALS
        }
        # fleet capacity view (docs/observability.md "Capacity"): page heat
        # and residency sum across replicas; headroom reports the MINIMUM
        # (the tightest replica bounds what the fleet can still admit);
        # top prefixes merge by cross-process digest
        capacity: Dict[str, Any] = {
            "pages_hot": 0,
            "pages_warm": 0,
            "pages_cold": 0,
            "fragmentation": None,
            "headroom_pct": None,
            "resident_bytes": 0,
            "resident_prefixes": 0,
            "top_prefixes": [],
        }
        for r in self.replicas:
            # in-process replicas answer fresh (lock-only, no sockets);
            # remote/dead ones fall back to the probe cache
            local = getattr(r, "local_stats", lambda: None)()
            stats = local or self._stats_cache.get(r.index, {})
            quarantined = self.quarantine.is_quarantined(r.index, now)
            breaker = self.breakers.get(r.index)
            row = {
                **r.describe(),
                "quarantined": quarantined,
                "breaker": breaker.state if breaker is not None else None,
                "queue_depth": stats.get("queue_depth", 0),
                "active_slots": stats.get("active_slots", 0),
                "num_slots": stats.get("num_slots", r.spec.num_slots),
                "requests_done": stats.get("requests_done", 0),
                "tokens_per_sec": stats.get("tokens_per_sec", 0.0),
                "prefix_hits": stats.get("prefix_hits", 0),
                "prefix_tokens_saved": stats.get("prefix_tokens_saved", 0),
                "ttft_ms_p50": stats.get("ttft_ms_p50"),
                "ttft_ms_p95": stats.get("ttft_ms_p95"),
            }
            if quarantined:
                row["state"] = "quarantined"
            if r.state == UP and r.index in self._draining:
                row["state"] = "draining"
            table.append(row)
            if r.state == UP and not quarantined:
                agg["queue_depth"] += stats.get("queue_depth", 0)
            for k in (
                "active_slots",
                "num_slots",
                "tokens_out",
                "requests_done",
                "requests_failed",
                "prefix_hits",
                "prefix_tokens_saved",
                "prefill_calls",
                "preemptions",
            ):
                agg[k] += stats.get(k, 0)
            paging = stats.get("paging") or {}
            if paging.get("paged"):
                for k in ("pages_total", "pages_free", "pages_shared"):
                    agg[k] += paging.get(k, 0)
                row["pages_free"] = paging.get("pages_free")
                heat = paging.get("heat") or {}
                capacity["pages_hot"] += int(heat.get("hot") or 0)
                capacity["pages_warm"] += int(heat.get("warm") or 0)
                capacity["pages_cold"] += int(heat.get("cold") or 0)
                fr = (paging.get("fragmentation") or {}).get("frag_ratio")
                if fr is not None:
                    capacity["fragmentation"] = max(
                        capacity["fragmentation"] or 0.0, float(fr)
                    )
            memory = stats.get("memory") or {}
            hp = memory.get("headroom_pct")
            row["headroom_pct"] = hp
            if hp is not None:
                capacity["headroom_pct"] = (
                    float(hp)
                    if capacity["headroom_pct"] is None
                    else min(capacity["headroom_pct"], float(hp))
                )
            resid = stats.get("prefix_residency") or {}
            capacity["resident_bytes"] += int(resid.get("resident_bytes") or 0)
            capacity["resident_prefixes"] += int(
                resid.get("resident_prefixes") or 0
            )
            for t in resid.get("top") or []:
                capacity["top_prefixes"].append(dict(t, replica=r.index))
            tier = stats.get("tier") or {}
            if tier.get("enabled"):
                agg_tier = capacity.setdefault(
                    "tier",
                    {
                        "replicas": 0,
                        "host_pages_total": 0,
                        "host_pages_free": 0,
                        "resident_packs": 0,
                        "spills": 0,
                        "fills": 0,
                    },
                )
                agg_tier["replicas"] += 1
                for k in (
                    "host_pages_total",
                    "host_pages_free",
                    "resident_packs",
                    "spills",
                    "fills",
                ):
                    agg_tier[k] += int(tier.get(k) or 0)
            for name, d in (stats.get("latency") or {}).items():
                latency_dicts.setdefault(name, []).append(d)
        merged = {
            name: merge_dicts(ds) for name, ds in latency_dicts.items()
        }
        ttft = merged.get("ttft_ms")
        for q, key in ((0.50, "p50"), (0.90, "p90"), (0.95, "p95"), (0.99, "p99")):
            agg[f"ttft_ms_{key}"] = ttft.percentile(q) if ttft else None
        tpot = merged.get("tpot_ms")
        agg["tpot_ms_p50"] = tpot.percentile(0.50) if tpot else None
        agg["tpot_ms_p95"] = tpot.percentile(0.95) if tpot else None
        qw = merged.get("queue_wait_ms")
        agg["queue_wait_ms_p50"] = qw.percentile(0.50) if qw else None
        e2e = merged.get("e2e_ms")
        agg["e2e_ms_p50"] = e2e.percentile(0.50) if e2e else None
        agg["e2e_ms_p95"] = e2e.percentile(0.95) if e2e else None
        agg["latency"] = {
            name: h.to_dict() for name, h in merged.items() if h is not None
        }
        if self.config.slo_ttft_ms is not None:
            agg["slo_ttft_ms"] = self.config.slo_ttft_ms
            agg["slo_ok"] = self.slo_ok
            agg["slo_miss"] = self.slo_miss
            judged = self.slo_ok + self.slo_miss
            # exact edge counters when available; the merged histogram's
            # bucket-interpolated view stands in before any completion
            agg["slo_attainment"] = (
                self.slo_ok / judged
                if judged
                else (ttft.attainment(self.config.slo_ttft_ms) if ttft else None)
            )
        # overload-robustness surfaces (docs/fleet.md "QoS classes &
        # graceful degradation", docs/resilience.md "Gray failure"):
        # ladder level, per-replica breaker states, per-class SLO split
        agg["brownout"] = self.brownout.snapshot()
        agg["breaker_open"] = sum(
            1 for b in self.breakers.values() if b.state != BREAKER_CLOSED
        )
        agg["breakers"] = {
            str(i): b.snapshot() for i, b in self.breakers.items()
        }
        if self.config.slo_ttft_ms is not None:
            agg["slo_by_class"] = {
                c: dict(v) for c, v in self.slo_by_class.items()
            }
        if self.autopilot is not None:
            agg["autopilot"] = self.autopilot.status()
        if self.autoscaler is not None:
            agg["autoscale"] = self.autoscaler.snapshot()
        # one residency row per distinct prefix digest: the same system
        # prompt resident on three replicas is ONE fleet anchor pinning
        # 3x the bytes, not three anchors
        by_digest: Dict[str, Dict[str, Any]] = {}
        for t in capacity["top_prefixes"]:
            d = by_digest.setdefault(
                str(t.get("digest")),
                {"digest": t.get("digest"), "bytes": 0, "hits": 0, "replicas": []},
            )
            d["bytes"] += int(t.get("bytes") or 0)
            d["hits"] += int(t.get("hits") or 0)
            d["replicas"].append(t.get("replica"))
        capacity["top_prefixes"] = sorted(
            by_digest.values(),
            key=lambda d: (-d["hits"], -d["bytes"], str(d["digest"])),
        )[:4]
        capacity["prefix_map"] = self.prefix_map.snapshot()
        agg["capacity"] = capacity
        # ALERTS surface: fleet-scope rules plus whatever each replica's
        # worker-scope evaluator reports in its SSTATS
        alerts = list(self.alerts.firing())
        for r in self.replicas:
            stats = self._stats_cache.get(r.index) or {}
            for a in stats.get("alerts") or []:
                alerts.append(dict(a, replica=r.index))
        agg["alerts"] = alerts
        agg["trends"] = self.metrics.trends(TREND_SIGNALS)
        return {
            **agg,
            "replicas": table,
            "routing": dict(self.counters),
            "in_flight": sum(
                1 for e in self._entries.values() if not e.done()
            ),
            "uptime_s": round(time.time() - self._started_ts, 3),
        }

    def _on_stats(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            return {"type": "SSTATS", "fleet": True, **self._fleet_stats()}

    def _metrics_body(self) -> Dict[str, Any]:
        """METRICS verb: aligned per-replica + fleet-aggregate series."""
        with self._lock:
            replicas = {
                str(idx): store.snapshot()
                for idx, store in self.replica_metrics.items()
            }
        return {
            "scope": "fleet",
            "metrics": self.metrics.snapshot(),
            "replicas": replicas,
            "alerts": self.alerts.firing(),
        }

    def _sample_metrics(self, now: float) -> None:
        """One aligned fleet observability tick (pump thread, ~1 Hz).

        Appends each replica's cached cumulative stats to its per-replica
        store AND the bucket-wise merge of the same snapshots to the fleet
        store at the same timestamp, then evaluates the fleet-scope alert
        rules. Using one ``now`` for every append is what makes windowed
        fleet queries equal the merge of windowed per-replica queries."""
        if now - self._last_metrics_tick < self.metrics.interval_s:
            return
        self._last_metrics_tick = now
        with self._lock:
            cache = {
                r.index: self._stats_cache.get(r.index)
                for r in self.replicas
            }
            pending = len(self._pending)
            draining = len(self._draining)
            n_replicas = sum(1 for r in self.replicas if r.state != DEAD)
        latency_all: Dict[str, List[Dict[str, Any]]] = {}
        slo_ok_sum = 0
        slo_miss_sum = 0
        have_replica_slo = False
        fleet_gauges = {
            "serve.queue_depth": float(pending),
            "fleet.healthy_replicas": float(len(self._healthy())),
            # capacity-loop surfaces (docs/fleet.md "Autoscaling"): fleet
            # size, replicas mid-drain, and scale-out pressure pinned at
            # max_replicas (the alert.fleet_at_capacity input)
            "fleet.replicas": float(n_replicas),
            "fleet.draining": float(draining),
            "fleet.at_capacity": (
                1.0
                if self.autoscaler is not None and self.autoscaler.at_capacity()
                else 0.0
            ),
        }
        tokens_per_sec = 0.0
        # fleet capacity accumulators: heat/residency sum across replicas;
        # headroom takes the MINIMUM — the tightest replica is the one the
        # next admission can actually land on
        heat_sum = {"hot": 0.0, "warm": 0.0, "cold": 0.0}
        have_heat = False
        frag_max = None
        resid_bytes = resid_count = 0.0
        have_resid = False
        headroom_min = None
        for idx, stats in cache.items():
            if not stats:
                continue
            with self._lock:
                store = self.replica_metrics.get(idx)
                if store is None:
                    store = timeseries.SeriesStore(self.metrics.interval_s)
                    self.replica_metrics[idx] = store
            hists = {
                f"serve.{name}": d
                for name, d in (stats.get("latency") or {}).items()
            }
            counters = {"serve.requests_done": stats.get("requests_done", 0)}
            if stats.get("slo_ok") is not None:
                have_replica_slo = True
                slo_ok_sum += int(stats.get("slo_ok") or 0)
                slo_miss_sum += int(stats.get("slo_miss") or 0)
                counters["serve.slo_ok"] = stats.get("slo_ok")
                counters["serve.slo_miss"] = stats.get("slo_miss")
            paging = stats.get("paging") or {}
            heat = paging.get("heat") or {}
            frag = paging.get("fragmentation") or {}
            resid = stats.get("prefix_residency") or {}
            memory = stats.get("memory") or {}
            # feed the fleet prefix map from this replica's residency
            # sample — device-resident anchors plus host-tier prefix packs
            # (a spilled prefix is still one cheap swap-in away); called
            # outside _lock (prefix_map has its own leaf lock) so a slow
            # snapshot never stalls dispatch
            self.prefix_map.update(
                idx,
                [
                    str(t.get("digest"))
                    for t in (resid.get("top") or [])
                    if t.get("digest")
                ]
                + [
                    str(d)
                    for d in (stats.get("tier") or {}).get("prefix_digests")
                    or []
                ],
            )
            store.ingest(
                now,
                gauges={
                    "serve.queue_depth": stats.get("queue_depth"),
                    "serve.active_slots": stats.get("active_slots"),
                    "serve.tokens_per_sec": stats.get("tokens_per_sec"),
                    "serve.ttft_ms": stats.get("ttft_ms_p95"),
                    "serve.pages_free": paging.get("pages_free"),
                    "serve.pages_hot": heat.get("hot"),
                    "serve.pages_warm": heat.get("warm"),
                    "serve.pages_cold": heat.get("cold"),
                    "serve.fragmentation": frag.get("frag_ratio"),
                    "serve.prefix_resident_bytes": resid.get("resident_bytes"),
                    "serve.prefix_resident_count": resid.get("resident_prefixes"),
                    "mem.headroom_pct": memory.get("headroom_pct"),
                },
                counters=counters,
                hists=hists,
            )
            if heat:
                have_heat = True
                for k in heat_sum:
                    heat_sum[k] += float(heat.get(k) or 0.0)
            if frag.get("frag_ratio") is not None:
                f = float(frag["frag_ratio"])
                frag_max = f if frag_max is None else max(frag_max, f)
            if resid:
                have_resid = True
                resid_bytes += float(resid.get("resident_bytes") or 0.0)
                resid_count += float(resid.get("resident_prefixes") or 0.0)
            hp = memory.get("headroom_pct")
            if hp is not None:
                headroom_min = (
                    float(hp) if headroom_min is None else min(headroom_min, float(hp))
                )
            tokens_per_sec += float(stats.get("tokens_per_sec") or 0.0)
            for name, d in (stats.get("latency") or {}).items():
                latency_all.setdefault(name, []).append(d)
        fleet_gauges["serve.tokens_per_sec"] = round(tokens_per_sec, 2)
        if have_heat:
            fleet_gauges["serve.pages_hot"] = heat_sum["hot"]
            fleet_gauges["serve.pages_warm"] = heat_sum["warm"]
            fleet_gauges["serve.pages_cold"] = heat_sum["cold"]
        if frag_max is not None:
            fleet_gauges["serve.fragmentation"] = frag_max
        if have_resid:
            fleet_gauges["serve.prefix_resident_bytes"] = resid_bytes
            fleet_gauges["serve.prefix_resident_count"] = resid_count
        if headroom_min is not None:
            fleet_gauges["mem.headroom_pct"] = headroom_min
        merged_hists: Dict[str, Dict[str, Any]] = {}
        for name, ds in latency_all.items():
            h = merge_dicts(ds)
            if h is not None:
                merged_hists[f"serve.{name}"] = h.to_dict()
        if merged_hists.get("serve.ttft_ms"):
            p95 = timeseries.hist_delta(merged_hists["serve.ttft_ms"], None)
            fleet_gauges["serve.ttft_ms"] = p95.percentile(0.95) if p95 else None
        # exact fleet-edge SLO counters when the router judges TTFT itself;
        # the sum of replica-side counters stands in otherwise
        counters = {}
        if self.config.slo_ttft_ms is not None:
            with self._lock:
                counters = {
                    "serve.slo_ok": self.slo_ok,
                    "serve.slo_miss": self.slo_miss,
                }
        elif have_replica_slo:
            counters = {"serve.slo_ok": slo_ok_sum, "serve.slo_miss": slo_miss_sum}
        # brownout ladder: stepped off the LAST tick's burn-rate verdict
        # (one-tick lag is in the noise next to the hysteresis windows);
        # the level gauge lands in the same ingest the alert.brownout
        # threshold rule reads, so entry/exit alerts fire for free
        burning = any(
            a.get("alert") == "alert.ttft_slo_burn" for a in self.alerts.firing()
        )
        level, transition = self.brownout.step(burning, now)
        if transition is not None:
            self.log(
                f"brownout {transition} -> level {level} "
                f"({BROWNOUT_LEVELS[level]})"
            )
        fleet_gauges["fleet.brownout_level"] = float(level)
        self.telemetry.gauge("fleet.brownout_level", float(level))
        # gray-failure breaker scoring over the per-replica windowed TTFT
        # p95s ingested above (docs/resilience.md)
        self._score_breakers(now)
        open_count = sum(
            1 for b in self.breakers.values() if b.state != BREAKER_CLOSED
        )
        fleet_gauges["fleet.breaker_open"] = float(open_count)
        self.telemetry.gauge("fleet.breaker_open", float(open_count))
        self.telemetry.gauge("fleet.replicas", fleet_gauges["fleet.replicas"])
        self.telemetry.gauge(
            "fleet.at_capacity", fleet_gauges["fleet.at_capacity"]
        )
        self.metrics.ingest(now, gauges=fleet_gauges, counters=counters, hists=merged_hists)
        self.alerts.evaluate(now)
        self.telemetry.gauge("alerts.firing", float(len(self.alerts.firing())))

    def _score_breakers(self, now: float) -> None:
        """Feed each dispatchable replica's windowed TTFT p95 to its
        breaker, scored against the BEST (minimum) peer p95 — with two
        replicas a median would be dragged up by the gray one, so the
        healthy peer is the honest baseline (pump thread)."""
        cfg = self.config
        p95s: Dict[int, Optional[float]] = {}
        for r in self.replicas:
            if r.state != UP or getattr(r.spec, "role", "any") == "prefill":
                continue
            with self._lock:
                store = self.replica_metrics.get(r.index)
            series = store.get("serve.ttft_ms") if store is not None else None
            p95s[r.index] = (
                series.percentile(0.95, cfg.breaker_window_s, now)
                if series is not None
                else None
            )
        for idx, p95 in p95s.items():
            breaker = self.breakers.get(idx)
            if breaker is None:
                continue
            peers = [
                v
                for i, v in p95s.items()
                if i != idx
                and v is not None
                and self.breakers[i].state == BREAKER_CLOSED
            ]
            peer = min(peers) if peers else None
            transition = breaker.score(
                p95, peer, cfg.breaker_ratio, cfg.breaker_min_ms, now
            )
            if transition == "opened":
                self.telemetry.count("fleet.breaker_opened")
                self.log(
                    f"breaker OPEN on replica {idx}: ttft p95 "
                    f"{p95:.0f}ms vs peer {peer:.0f}ms"
                )

    def _on_status(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            stats = self._fleet_stats()
        status: Dict[str, Any] = {
            "type": "STATUS",
            "name": self.name,
            "kind": "serve-fleet",
            "state": "closing" if self._closing else "serving",
            "app_id": self.name,
            "run_id": 0,
            "elapsed_s": time.time() - self._started_ts,
            "serve": stats,
            "fleet": {
                "replicas": stats["replicas"],
                "routing": stats["routing"],
            },
        }
        tel = self.telemetry
        if getattr(tel, "active", False):
            snap = tel.snapshot()
            if snap:
                status["telemetry"] = {"router": snap}
        return status

    def _on_log(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            lines = list(self._log)
            self._log.clear()
            stats = self._fleet_stats()
        progress = (
            f"replicas {sum(1 for r in stats['replicas'] if r['state'] == UP)}"
            f"/{len(self.replicas)}  queue {stats['queue_depth']}  "
            f"done {stats['requests_done']}  "
            f"requeued {stats['routing']['requeued']}"
        )
        return {"type": "LOG", "logs": lines, "progress": progress}

    # ------------------------------------------------------------------ pump
    # (single background thread: all downstream sockets live here)

    # terminal entries stay pollable this long (mirrors scheduler retention)
    RETENTION_S = 300.0

    def _retire_old(self, now: float) -> None:
        with self._lock:
            dead = [
                rid
                for rid, e in self._entries.items()
                if e.done() and now - e.submitted_ts > self.RETENTION_S
            ]
            for rid in dead:
                del self._entries[rid]

    def _pump_loop(self) -> None:
        last_probe = 0.0
        while not self._stop.is_set():
            now = time.time()
            try:
                if now - last_probe >= self.config.probe_interval_s:
                    self._probe_replicas()
                    self._sample_metrics(now)
                    self._retire_old(now)
                    last_probe = now
                self._chaos_tick()
                self._sweep_down_replicas()
                self._dispatch_pending(time.time())
                self._poll_routed()
                if self.autopilot is not None:
                    self.autopilot.maybe_sample(time.time())
                if self.autoscaler is not None and not self._closing:
                    self.autoscaler.tick(time.time())
            except Exception as e:  # noqa: BLE001 - pump must survive anything
                self.log(f"pump error: {type(e).__name__}: {e}")
            self._stop.wait(self.config.pump_interval_s)

    def _probe_replicas(self) -> None:
        for replica in self.replicas:
            if replica.state != UP:
                self._note_failure(replica, "down")
                continue
            try:
                stats = replica.client.stats()
            except (RpcError, OSError) as e:
                self._note_failure(replica, f"{type(e).__name__}: {e}")
                continue
            with self._lock:
                self._stats_cache[replica.index] = stats
            self.quarantine.record_success(replica.index)
            with self._lock:
                self._down_handled.discard(replica.index)
        self.telemetry.gauge(
            "fleet.healthy_replicas", float(len(self._healthy()))
        )

    def _note_failure(self, replica: Replica, why: str) -> None:
        tripped = self.quarantine.record_failure(replica.index)
        if tripped:
            self.log(f"replica {replica.index} quarantined ({why})")
            self.telemetry.count("fleet.quarantined")
        # a closed port IS death — don't wait out the probe threshold
        if replica.state == DEAD or self.quarantine.is_quarantined(replica.index):
            self._handle_replica_down(replica)

    def _handle_replica_down(self, replica: Replica) -> None:
        """Requeue the dead/quarantined replica's in-flight requests ahead
        of fresh arrivals, then respawn it if budget remains. Requeues
        beyond the replica's retry budget are deferred (not_before_ts), so
        a flapping replica can't turn its backlog into a requeue storm."""
        now = time.time()
        # a half-open probation probe bound here is lost, not answered
        breaker = self.breakers.get(replica.index)
        if breaker is not None:
            breaker.probe_lost()
        # a dead replica's resident prefixes are unreachable — drop its
        # contribution so affinity never routes toward a corpse
        self.prefix_map.forget_replica(replica.index)
        with self._lock:
            if replica.index in self._down_handled:
                return
            self._down_handled.add(replica.index)
            moved = 0
            deferred = 0
            requeued_entries = []
            budget = self.retry_budgets.get(replica.index)
            for entry in self._entries.values():
                if entry.replica == replica.index and not entry.done():
                    entry.state = REQUEUED
                    entry.replica = None
                    entry.remote_id = None
                    entry.snapshot = None
                    entry.resubmits += 1
                    if budget is not None and not budget.consume(now):
                        # budget dry: still requeued, but the dispatch loop
                        # waits this entry out (backoff grows per resubmit)
                        entry.not_before_ts = now + 0.25 * entry.resubmits
                        deferred += 1
                    self._pending.appendleft(entry.rid)
                    requeued_entries.append(entry)
                    moved += 1
            self.counters["requeued"] += moved
            self.counters["retry_deferred"] += deferred
        if deferred:
            self.telemetry.count("fleet.retry_deferred", deferred)
        for entry in requeued_entries:
            # explicit hop milestone: the SAME trace id continues on the
            # survivor, so the exported lane shows the loss + re-run inline
            self.telemetry.event(
                "req.requeued", trace=entry.trace, rid=entry.rid,
                replica=replica.index, resubmits=entry.resubmits,
            )
        with self._lock:
            self._stats_cache.pop(replica.index, None)
            # a draining replica's death is the kill-mid-drain fallback:
            # its requeue above is the recovery, retirement finishes in
            # the autoscaler — never respawn what we were removing
            respawn = (
                replica.state == DEAD
                and replica.index not in self._draining
                and self._restarts_used < self.config.max_restarts
            )
            if respawn:
                self._restarts_used += 1
        if moved:
            self.log(
                f"replica {replica.index} down: requeued {moved} request(s) "
                "to survivors"
            )
            self.telemetry.count("fleet.requeued", moved)
        if respawn:
            try:
                addr = replica.respawn()
            except Exception as e:  # noqa: BLE001 - respawn is best-effort within budget
                self.log(
                    f"replica {replica.index} respawn failed: "
                    f"{type(e).__name__}: {e}"
                )
                return
            self.quarantine.record_success(replica.index)
            # the respawned stack shares nothing with the dead one: a
            # breaker window or SeriesStore built from pre-death latency
            # samples would re-open/re-trip the fresh replica on its
            # predecessor's ghosts (regression-tested)
            breaker = self.breakers.get(replica.index)
            if breaker is not None:
                breaker.reset()
            with self._lock:
                self._down_handled.discard(replica.index)
                self.replica_metrics.pop(replica.index, None)
                self.counters["respawned"] += 1
            self.log(
                f"replica {replica.index} respawned at {addr[0]}:{addr[1]} "
                f"({self.config.max_restarts - self._restarts_used} restarts left)"
            )

    def _sweep_down_replicas(self) -> None:
        """Catch deaths between probes (chaos kill closes the port at once)."""
        for replica in self.replicas:
            if replica.state == DEAD:
                with self._lock:
                    handled = replica.index in self._down_handled
                if not handled:
                    self._handle_replica_down(replica)

    def _chaos_tick(self) -> None:
        """`replica_kill:replica=N` fires once the target is actually
        decoding (mid-stream by construction, so requeue is exercised)."""
        ch = chaos_mod.get()
        if ch is None:
            return
        for replica in self.replicas:
            if replica.state != UP:
                continue
            with self._lock:
                busy = any(
                    e.replica == replica.index and not e.done()
                    and e.snapshot is not None
                    and e.snapshot.get("n_tokens", 0) > 0
                    for e in self._entries.values()
                )
            if busy and ch.replica_kill(replica.index):
                self.log(f"chaos: killing replica {replica.index}")
                replica.kill()

    def _dispatch_pending(self, now: float) -> None:
        while True:
            level = self.brownout.level()
            with self._lock:
                if not self._pending:
                    return
                healthy = self._healthy()
                if not healthy:
                    return
                # breaker gate: open breakers leave the dispatch set; when
                # EVERY candidate is breaker-blocked, fail static to the
                # full healthy set — a breaker sidelines a gray replica, it
                # must never cause a total outage (docs/resilience.md)
                candidates = [
                    r for r in healthy if self.breakers[r.index].ok(now)
                ]
                breaker_gated = bool(candidates)
                if not candidates:
                    candidates = healthy
                cfg = self.config
                # SLO queue-hold, best-effort only: when the best replica
                # projects over budget, fresh best-effort parks here (cheap
                # to cancel/requeue) while premium/standard dispatch and
                # ride the replica-side priority admission + quota floor —
                # the class-blind hold would head-of-line-block premium
                # behind the very flood it needs to outrank
                hold_best_effort = False
                if cfg.slo_ttft_ms is not None and cfg.admission == "queue":
                    proj_min = min(
                        projected_ttft_ms(
                            self._stats_cache.get(r.index, {}),
                            cfg.default_service_ms,
                        )
                        for r in candidates
                    )
                    hold_best_effort = proj_min > cfg.slo_ttft_ms
                # scan for the first dispatchable entry: requeues damped by
                # an exhausted retry budget wait out not_before_ts, and at
                # brownout level >= 2 best-effort parks in the queue while
                # premium/standard behind it still dispatches
                idx = action = None
                for i, rid in enumerate(self._pending):
                    entry = self._entries.get(rid)
                    if entry is None or entry.done():
                        idx, action = i, "drop"
                        break
                    if entry.deadline_ts is not None and now > entry.deadline_ts:
                        idx, action = i, "expire"
                        break
                    if (
                        entry.not_before_ts is not None
                        and now < entry.not_before_ts
                    ):
                        continue
                    if entry.qos == BEST_EFFORT and (
                        level >= 2
                        or (hold_best_effort and entry.state == PENDING)
                    ):
                        continue
                    idx, action = i, "dispatch"
                    break
                if idx is None:
                    return
                rid = self._pending[idx]
                entry = self._entries.get(rid)
                if action == "drop":
                    del self._pending[idx]
                    continue
                if action == "expire":
                    del self._pending[idx]
                    self._finish_local(
                        entry, "expired", "deadline exceeded in router queue"
                    )
                    continue
                # prefix-affinity term (docs/fleet.md "Fleet-global KV"):
                # brownout level >= 2 zeroes the bonus — under overload,
                # raw load beats locality (level was read outside _lock,
                # keeping the brownout lock out of this critical section)
                digest = None
                affinity_ms = 0.0
                if cfg.affinity_weight_ms > 0 and level < 2:
                    prompt = entry.payload.get("prompt") or ()
                    if prompt:
                        digest = PrefixIndex.digest(
                            tuple(int(t) for t in prompt)
                        )
                        affinity_ms = cfg.affinity_weight_ms
                best, proj = self._pick_replica(
                    candidates, digest=digest, affinity_ms=affinity_ms
                )
                if breaker_gated:
                    # probation first: a half-open replica can never win the
                    # latency pick (its cached stats are the slow ones that
                    # tripped it), so the canary dispatch is routed to it
                    # deliberately — one request per cooldown, by the
                    # breaker's single-probe claim
                    for r in candidates:
                        b = self.breakers[r.index]
                        if b.state == BREAKER_HALF_OPEN and b.take_probe(rid):
                            best = r
                            break
                    else:
                        if not self.breakers[best.index].take_probe(rid):
                            # best is half-open with its probe already out:
                            # try the others, else wait the round out
                            remaining = [
                                r for r in candidates if r.index != best.index
                            ]
                            if not remaining:
                                return
                            best, proj = self._pick_replica(
                                remaining, digest=digest,
                                affinity_ms=affinity_ms,
                            )
                            if not self.breakers[best.index].take_probe(rid):
                                return
                entry.not_before_ts = None
                del self._pending[idx]
                # brownout level >= 1: clamp best-effort output length for
                # this dispatch (the entry keeps its full payload, so a
                # requeue after recovery replays unclamped)
                payload = entry.payload
                if (
                    level >= 1
                    and entry.qos == BEST_EFFORT
                    and int(payload.get("max_new", 16)) > cfg.brownout_clamp_tokens
                ):
                    payload = dict(payload, max_new=max(1, cfg.brownout_clamp_tokens))
                    self.counters["brownout_clamped"] += 1
                    self.telemetry.count("fleet.brownout_clamped")
            # milestone BEFORE the downstream round-trip: the replica's own
            # req.queued lands mid-flight, so stamping after the reply
            # would scramble the lane's dispatched→queued ordering
            self.telemetry.event(
                "req.dispatched", trace=entry.trace, rid=entry.rid,
                replica=best.index, resubmits=entry.resubmits,
            )
            remote_id = None
            if self.prefill_workers:
                remote_id = self._dispatch_disaggregated(entry, best, payload)
            if remote_id is None:
                try:
                    remote_id = best.client.submit(**payload)
                except RpcRejectedError as e:
                    self.breakers[best.index].probe_lost(rid)
                    with self._lock:
                        self._finish_local(entry, "failed", str(e))
                    continue
                except (RpcError, OSError) as e:
                    self.breakers[best.index].probe_lost(rid)
                    with self._lock:
                        entry.state = REQUEUED
                        self._pending.appendleft(rid)
                    self._note_failure(best, f"submit: {type(e).__name__}")
                    return
            with self._lock:
                entry.state = ROUTED
                entry.replica = best.index
                entry.remote_id = remote_id
                self.counters["routed"] += 1
                # book the new load locally so picks between probes see it
                cached = self._stats_cache.setdefault(best.index, {})
                cached["queue_depth"] = cached.get("queue_depth", 0) + 1
            self.telemetry.count("fleet.routed")

    def _dispatch_disaggregated(
        self, entry: RouteEntry, best: Replica, payload: Optional[Dict[str, Any]] = None
    ):
        """Disaggregated dispatch (pump thread): run the prompt on a
        prefill replica, hand the KV pack to the chosen decode replica.
        Returns the downstream request id, or None to fall back to plain
        dispatch (prefill fleet down / handoff unsupported) — the decode
        replica's full engine then prefills for itself, so disaggregation
        degrades, never outages. ``payload`` overrides the entry's payload
        when the brownout ladder clamped this dispatch."""
        payload = payload if payload is not None else entry.payload
        worker = pick_worker(self.prefill_workers, self._pw_rr)
        self._pw_rr += 1
        if worker is None:
            return None
        t0 = time.perf_counter()
        try:
            pack = worker.prefill(payload)
        except PrefillWorkerError as e:
            self.log(f"prefill fallback: {e}")
            return None
        with self._lock:
            self.counters["prefilled"] += 1
        self.telemetry.event(
            "req.prefilled", trace=entry.trace, rid=entry.rid,
            replica=worker.index,
            plen=len(payload.get("prompt", [])),
        )
        try:
            remote_id = best.submit_prefilled(payload, pack)
        except Exception as e:  # noqa: BLE001 - dead/remote decode replica: plain dispatch retries
            self.log(f"handoff fallback: {type(e).__name__}: {e}")
            return None
        # handoff latency: prefill dispatch -> KV pack accepted by the
        # decode replica (covers the device_get serialization; the decode
        # side's device put shows up in its serve.kv_admit span)
        handoff_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self.counters["handoffs"] += 1
        self.telemetry.gauge("serve.handoff_ms", handoff_ms)
        self.telemetry.histogram("serve.handoff_ms", handoff_ms)
        self.telemetry.event(
            "req.handoff", trace=entry.trace, rid=entry.rid,
            prefill_replica=worker.index, decode_replica=best.index,
            handoff_ms=round(handoff_ms, 3),
        )
        return remote_id

    def _poll_routed(self) -> None:
        with self._lock:
            live = [
                (e.rid, e.replica, e.remote_id, e.cancel_requested, e.cancel_sent)
                for e in self._entries.values()
                if e.state == ROUTED and not e.done()
            ]
        for rid, idx, remote_id, want_cancel, cancel_sent in live:
            replica = self._replica(idx)
            if replica is None or replica.state != UP:
                continue  # the down-sweep requeues; don't poke a closed port
            try:
                if want_cancel and not cancel_sent:
                    replica.client.cancel(remote_id)
                    with self._lock:
                        entry = self._entries.get(rid)
                        if entry is not None:
                            entry.cancel_sent = True
                snap = replica.client.poll(remote_id)
            except RpcRejectedError:
                # replica forgot the id (restart/retention): replay it,
                # charged against the replica's retry budget
                self.breakers[idx].probe_lost(rid)
                now = time.time()
                requeued_entry = None
                with self._lock:
                    entry = self._entries.get(rid)
                    if entry is not None and not entry.done():
                        entry.state = REQUEUED
                        entry.replica = None
                        entry.remote_id = None
                        entry.snapshot = None
                        entry.resubmits += 1
                        budget = self.retry_budgets.get(idx)
                        if budget is not None and not budget.consume(now):
                            entry.not_before_ts = now + 0.25 * entry.resubmits
                            self.counters["retry_deferred"] += 1
                            self.telemetry.count("fleet.retry_deferred")
                        self.counters["requeued"] += 1
                        self._pending.appendleft(rid)
                        requeued_entry = entry
                if requeued_entry is not None:
                    self.telemetry.event(
                        "req.requeued", trace=requeued_entry.trace, rid=rid,
                        replica=idx, resubmits=requeued_entry.resubmits,
                    )
                continue
            except (RpcError, OSError) as e:
                self.breakers[idx].probe_lost(rid)
                self._note_failure(replica, f"poll: {type(e).__name__}")
                return
            # gray-failure probation: the probe's first observed TTFT is
            # the verdict (the breaker ignores every other rid)
            if snap.get("ttft_ms") is not None:
                verdict = self.breakers[idx].observe_ttft(
                    rid, float(snap["ttft_ms"]), time.time()
                )
                if verdict == "closed":
                    self.telemetry.count("fleet.breaker_closed")
                    self.log(
                        f"breaker CLOSED on replica {idx} (probe ttft "
                        f"{snap['ttft_ms']:.0f}ms)"
                    )
                    # capacity just came online: spread any backlog that
                    # was pinned to the overloaded peers before this
                    # replica could take weighted traffic
                    self.rebalance_excess()
                elif verdict == "reopened":
                    self.telemetry.count("fleet.breaker_opened")
                    self.log(
                        f"breaker RE-OPENED on replica {idx} (probe ttft "
                        f"{snap['ttft_ms']:.0f}ms)"
                    )
            completed = None
            with self._lock:
                entry = self._entries.get(rid)
                if entry is None or entry.state != ROUTED:
                    continue
                entry.snapshot = snap
                if snap.get("done") and not entry.counted_done:
                    entry.counted_done = True
                    key = {
                        "done": "completed",
                        "cancelled": "cancelled",
                        "expired": "expired",
                        "failed": "failed",
                    }.get(snap.get("state"), "completed")
                    self.counters[key] += 1
                    completed = entry
                    # exact fleet-edge SLO attainment, judged on the TTFT
                    # the serving replica measured for this request, split
                    # per QoS class for the no-cliff view
                    if (
                        self.config.slo_ttft_ms is not None
                        and snap.get("ttft_ms") is not None
                    ):
                        by_class = self.slo_by_class.get(entry.qos)
                        if snap["ttft_ms"] <= self.config.slo_ttft_ms:
                            self.slo_ok += 1
                            if by_class is not None:
                                by_class["ok"] += 1
                        else:
                            self.slo_miss += 1
                            if by_class is not None:
                                by_class["miss"] += 1
            if completed is not None:
                self.telemetry.event(
                    "req.completed", trace=completed.trace, rid=rid,
                    state=snap.get("state"), replica=idx,
                    resubmits=completed.resubmits,
                )
