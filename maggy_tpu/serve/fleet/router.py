"""SLO-aware request router over N serving replicas.

The router is the fleet's only public address. It speaks the exact verb set
a single :class:`~maggy_tpu.serve.server.ServeServer` speaks — SUBMIT /
POLL / CANCEL / SSTATS / STATUS / LOG over :mod:`maggy_tpu.core.rpc` — so
every existing client (:class:`~maggy_tpu.serve.ServeClient`, the monitor
dashboard) points at a fleet unchanged. Behind the verbs:

* **Routing.** SUBMIT mints a *router-owned* request id and places the
  request on the least-loaded healthy replica (cached SSTATS: queue depth,
  slot occupancy, TTFT percentiles). The id -> replica binding is sticky:
  POLL and CANCEL always reach the replica that owns the request — and the
  binding, not the replica, is durable: when a replica dies its requests are
  re-bound, the id never changes.
* **SLO-aware admission.** With ``slo_ttft_ms`` set, each SUBMIT is checked
  against the best replica's *projected TTFT* (see ``projected_ttft_ms``).
  Projection over budget either sheds the request with a 429-style ``BUSY``
  reply (``admission="shed"``) or parks it in the router queue until
  capacity frees (``admission="queue"``, the default). No healthy replica
  at all always sheds.
* **Health + requeue.** A pump thread probes replicas (SSTATS heartbeat)
  and feeds failures into :class:`maggy_tpu.resilience.QuarantineTracker` —
  the same policy object that benches flaky HPO workers. A quarantined or
  dead replica's in-flight requests are requeued *ahead of* fresh arrivals
  (the retry-queue-outranks-suggestions rule the HPO driver uses) and
  resubmitted to survivors; until redispatch, POLL reports
  ``state="requeued"``. Dead replicas are respawned within
  ``max_restarts``. The chaos seam
  (``MAGGY_TPU_CHAOS="replica_kill:replica=N"``) kills a busy replica
  deterministically so all of this is testable on one CPU.

* **Disaggregated prefill/decode.** Replicas tagged ``role="prefill"``
  (:class:`~maggy_tpu.serve.fleet.replica.ReplicaSpec`) never receive
  SUBMIT dispatches; instead the pump runs each accepted prompt through a
  :class:`~maggy_tpu.serve.fleet.prefill.PrefillWorker` first and hands
  the resulting KV pack to the chosen decode replica
  (``Engine.admit_from_kv`` — the device-put/serialization path).
  ``req.prefilled``/``req.handoff`` events mark the hop on the request's
  trace lane and ``serve.handoff_ms`` measures it; when every prefill
  replica is down the router falls back to plain dispatch (decode replicas
  keep a full engine). See docs/fleet.md "Disaggregated prefill/decode".

Handlers run on the RPC event loop and only touch lock-guarded host state;
every downstream socket round-trip (dispatch, poll fan-out, probes) belongs
to the pump thread.
"""

from __future__ import annotations

import dataclasses
import secrets as secrets_mod
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu import telemetry
from maggy_tpu.core import lockdebug, rpc
from maggy_tpu.exceptions import RpcError, RpcRejectedError
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.resilience.policy import QuarantineTracker
from maggy_tpu.serve.fleet.prefill import (
    PrefillWorker,
    PrefillWorkerError,
    pick_worker,
)
from maggy_tpu.serve.fleet.replica import DEAD, UP, Replica
from maggy_tpu.serve.scheduler import LATENCY_SIGNALS
from maggy_tpu.telemetry import timeseries, tracing
from maggy_tpu.telemetry.alerts import AlertEvaluator
from maggy_tpu.telemetry.histogram import merge_dicts

# fleet series surfaced as sparkline trends on the monitor panel
TREND_SIGNALS = (
    "serve.queue_depth",
    "serve.tokens_per_sec",
    "serve.ttft_ms",
    "fleet.healthy_replicas",
)

# router-side request states (downstream states pass through verbatim)
PENDING = "pending"  # accepted, not yet on a replica
ROUTED = "routed"  # live on a replica
REQUEUED = "requeued"  # owner died; waiting for redispatch


@dataclasses.dataclass
class RouterConfig:
    """Admission and health knobs (docs/fleet.md "Admission control")."""

    slo_ttft_ms: Optional[float] = None  # None: admit everything
    admission: str = "queue"  # "queue" | "shed" when projection > SLO
    max_queue: int = 1024  # router-side pending bound
    probe_interval_s: float = 0.25  # SSTATS heartbeat cadence
    pump_interval_s: float = 0.005  # dispatch/poll loop cadence
    quarantine_threshold: int = 2  # consecutive probe failures
    quarantine_cooldown_s: float = 30.0
    max_restarts: int = 1  # fleet-wide respawn budget
    default_service_ms: float = 100.0  # TTFT prior before any p50 exists

    def validate(self) -> None:
        if self.admission not in ("queue", "shed"):
            raise ValueError(
                f"admission must be 'queue' or 'shed', got {self.admission!r}"
            )


def projected_ttft_ms(stats: Dict[str, Any], prior_ms: float) -> float:
    """Projected time-to-first-token on a replica with these SSTATS.

    The model is deliberately simple and stated so operators can reason
    about sheds: a free slot with an empty queue costs one prefill
    (~observed TTFT p50, or the prior before one exists); otherwise the
    request waits behind ``queue_depth`` others served ``num_slots`` at a
    time, each wave costing roughly one observed TTFT."""
    p50 = stats.get("ttft_ms_p50") or prior_ms
    free = stats.get("num_slots", 1) - stats.get("active_slots", 0)
    depth = stats.get("queue_depth", 0)
    if free > 0 and depth == 0:
        return float(p50)
    waves = (depth + 1) / max(1, stats.get("num_slots", 1))
    return float(p50) * (1.0 + waves)


@dataclasses.dataclass
class RouteEntry:
    """One router-owned request and its sticky downstream binding."""

    rid: str
    payload: Dict[str, Any]  # submit kwargs, replayable on requeue
    # request-scoped trace id: adopted from the client's SUBMIT frame (or
    # minted here for traceless clients) and forwarded on every downstream
    # dispatch — durable across replica death, like the rid
    trace: Optional[str] = None
    state: str = PENDING
    replica: Optional[int] = None
    remote_id: Optional[str] = None
    snapshot: Optional[Dict[str, Any]] = None  # last downstream POLL
    final: Optional[Dict[str, Any]] = None  # router-local terminal snapshot
    submitted_ts: float = dataclasses.field(default_factory=time.time)
    deadline_ts: Optional[float] = None
    resubmits: int = 0
    cancel_requested: bool = False
    cancel_sent: bool = False
    counted_done: bool = False

    def done(self) -> bool:
        if self.final is not None:
            return True
        return bool(self.snapshot and self.snapshot.get("done"))

    def wire(self) -> Dict[str, Any]:
        """POLL reply: downstream snapshot under the ROUTER id."""
        if self.final is not None:
            body = dict(self.final)
        elif self.state == ROUTED and self.snapshot is not None:
            body = dict(self.snapshot)
        else:
            body = {
                "state": "queued" if self.state == PENDING else REQUEUED,
                "tokens": [],
                "n_tokens": 0,
                "prompt_len": len(self.payload.get("prompt", [])),
                "error": None,
                "ttft_ms": None,
                "done": False,
            }
        body["id"] = self.rid
        body["trace"] = self.trace
        body["replica"] = self.replica
        body["resubmits"] = self.resubmits
        return body


class Router:
    """Fleet front-end: one RPC server, N replicas, one pump thread."""

    def __init__(
        self,
        replicas: List[Replica],
        config: Optional[RouterConfig] = None,
        secret: Optional[str] = None,
        name: str = "maggy-fleet",
        telemetry_recorder=None,
        autopilot=None,
    ):
        self.config = config or RouterConfig()
        self.config.validate()
        self.replicas = list(replicas)
        self.name = name
        self.telemetry = telemetry_recorder or telemetry.get()
        # autopilot (docs/autotune.md): an online controller the pump
        # thread ticks — admission/SLO knobs move under the fleet guard
        self.autopilot = None
        if autopilot is not None and autopilot is not False:
            from maggy_tpu.autopilot import (
                AutopilotConfig,
                Controller,
                RouterTarget,
            )

            cfg = autopilot if isinstance(autopilot, AutopilotConfig) else None
            self.autopilot = (
                autopilot
                if isinstance(autopilot, Controller)
                else Controller(
                    RouterTarget(self),
                    config=cfg,
                    telemetry_recorder=self.telemetry,
                )
            )
        # disaggregation: prefill-role replicas become pump-owned prefill
        # workers and are excluded from SUBMIT dispatch
        self.prefill_workers = [
            PrefillWorker(r)
            for r in self.replicas
            if getattr(r.spec, "role", "any") == "prefill"
        ]
        if self.prefill_workers and not any(
            getattr(r.spec, "role", "any") != "prefill" for r in self.replicas
        ):
            raise ValueError(
                "a disaggregated fleet needs at least one decode-capable "
                "replica (role 'decode' or 'any')"
            )
        self._pw_rr = 0  # prefill-worker round-robin cursor
        self._rpc = rpc.Server(num_executors=0, secret=secret)
        self._rpc.telemetry = self.telemetry
        self.quarantine = QuarantineTracker(
            threshold=self.config.quarantine_threshold,
            cooldown=self.config.quarantine_cooldown_s,
        )
        self._lock = lockdebug.rlock("router._lock")
        self._entries: Dict[str, RouteEntry] = {}
        self._pending: deque = deque()  # rids; requeues go left, fresh right
        self._stats_cache: Dict[int, Dict[str, Any]] = {}
        self._down_handled: set = set()  # replica idx whose death was requeued
        self._restarts_used = 0
        self._rr = 0  # round-robin tie-break cursor
        self.counters: Dict[str, int] = {
            "routed": 0,
            "requeued": 0,
            "shed": 0,
            "completed": 0,
            "failed": 0,
            "expired": 0,
            "cancelled": 0,
            "respawned": 0,
            # disaggregation: prompts run on a prefill replica, and KV
            # packs handed to a decode replica (docs/fleet.md)
            "prefilled": 0,
            "handoffs": 0,
        }
        # exact SLO attainment at the fleet edge: counted per completed
        # request against the configured TTFT budget (histogram-derived
        # attainment in SSTATS is the bucket-resolution view of the same)
        self.slo_ok = 0
        self.slo_miss = 0
        self._log: deque = deque(maxlen=500)
        self._closing = False
        self._stop = threading.Event()
        self._pump: Optional[threading.Thread] = None
        self._started_ts = time.time()
        # fleet observability (docs/observability.md "Time series"): one
        # store per replica fed from the SSTATS probe cache, plus a
        # fleet-aggregate store fed at the *same* tick with the bucket-wise
        # merge — the alignment that lets tools/metrics_query.py reproduce
        # fleet windowed percentiles from per-replica snapshots. Alert
        # rules run at fleet scope over the aggregate store.
        self.metrics = timeseries.SeriesStore()
        self.replica_metrics: Dict[int, timeseries.SeriesStore] = {}
        self.alerts = AlertEvaluator(self.metrics, self.telemetry, scope="fleet")
        self._last_metrics_tick = 0.0
        for verb, handler in (
            ("SUBMIT", self._on_submit),
            ("POLL", self._on_poll),
            ("CANCEL", self._on_cancel),
            ("SSTATS", self._on_stats),
            ("STATUS", self._on_status),
            ("LOG", self._on_log),
        ):
            self._rpc.register_callback(verb, handler)
        self._rpc.register_metrics(self._metrics_body)

    @property
    def secret(self) -> str:
        return self._rpc.secret

    # -------------------------------------------------------------- lifecycle

    def start(self, host: str = "0.0.0.0", port: int = 0) -> Tuple[str, int]:
        for replica in self.replicas:
            if replica.state != UP:
                replica.secret = self.secret
                replica.start()
                self.log(
                    f"replica {replica.index} up at "
                    f"{replica.addr[0]}:{replica.addr[1]}"
                )
        addr = self._rpc.start(host=host, port=port)
        self._stop.clear()
        self._pump = threading.Thread(
            target=self._pump_loop, name="maggy-fleet-pump", daemon=True
        )
        self._pump.start()
        self.log(
            f"router on {addr[0]}:{addr[1]} ({len(self.replicas)} replicas, "
            f"slo_ttft_ms={self.config.slo_ttft_ms}, "
            f"admission={self.config.admission})"
        )
        return addr

    def stop(self, drain_timeout: float = 30.0) -> None:
        """Clean shutdown: stop admitting, let replicas finish resident
        work, then close sockets — in that order, so no accepted request is
        dropped by the shutdown itself."""
        with self._lock:
            self._closing = True
        deadline = time.time() + drain_timeout
        while time.time() < deadline:
            with self._lock:
                live = any(
                    not e.done()
                    for e in self._entries.values()
                )
            if not live:
                break
            time.sleep(0.02)
        self._stop.set()
        if self._pump is not None:
            self._pump.join(timeout=5.0)
            self._pump = None
        for replica in self.replicas:
            # replica drain is second-layer insurance (their own queues)
            replica.stop(drain=replica.state == UP, timeout=drain_timeout)
        self._rpc.stop()

    def log(self, line: str) -> None:
        self._log.append(f"[{time.strftime('%H:%M:%S')}] {line}")

    # ------------------------------------------------------------ projections

    def _healthy(self) -> List[Replica]:
        """Dispatch targets: healthy decode-capable replicas (prefill-only
        replicas are PrefillWorkers, never SUBMIT targets)."""
        now = time.time()
        return [
            r
            for r in self.replicas
            if r.state == UP
            and getattr(r.spec, "role", "any") != "prefill"
            and not self.quarantine.is_quarantined(r.index, now)
        ]

    def _pick_replica(  # guarded-by: _lock
        self, healthy: List[Replica]
    ) -> Tuple[Replica, float]:
        """Least projected TTFT; round-robin cursor breaks ties so equal
        replicas share load instead of all traffic piling on index 0."""
        cfg = self.config
        scored = []
        for offset in range(len(healthy)):
            r = healthy[(self._rr + offset) % len(healthy)]
            stats = self._stats_cache.get(r.index, {})
            scored.append((projected_ttft_ms(stats, cfg.default_service_ms), r))
        proj, best = min(scored, key=lambda pr: pr[0])
        self._rr += 1
        return best, proj

    # ----------------------------------------------------------------- verbs
    # (event-loop thread: lock-guarded host state only, no sockets)

    def _busy(
        self,
        why: str,
        projected: Optional[float] = None,
        trace: Optional[str] = None,
    ) -> Dict[str, Any]:
        with self._lock:
            self.counters["shed"] += 1
        self.telemetry.count("fleet.shed")
        self.telemetry.event("req.shed", trace=trace, reason=why)
        reply: Dict[str, Any] = {"type": "BUSY", "error": why}
        if projected is not None:
            reply["projected_ttft_ms"] = round(projected, 1)
        reply["retry_after_s"] = 0.25
        return reply

    def _on_submit(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        prompt = msg.get("prompt")
        if not isinstance(prompt, list) or not all(
            isinstance(t, int) for t in prompt
        ):
            raise ValueError("prompt must be a list of token ids")
        with self._lock:
            if self._closing:
                return self._busy("router shutting down")
            healthy = self._healthy()
            if not healthy:
                return self._busy("no healthy replica")
            pending_depth = len(self._pending)
            if pending_depth >= self.config.max_queue:
                return self._busy(
                    f"router queue full ({self.config.max_queue})"
                )
            cfg = self.config
            if cfg.slo_ttft_ms is not None:
                # admission control: project TTFT on the best replica, plus
                # one wave per router-queued request ahead of this one
                stats_best = min(
                    (
                        projected_ttft_ms(
                            self._stats_cache.get(r.index, {}),
                            cfg.default_service_ms,
                        )
                        for r in healthy
                    ),
                )
                backlog_ms = (
                    pending_depth
                    * cfg.default_service_ms
                    / max(1, sum(r.spec.num_slots for r in healthy))
                )
                projected = stats_best + backlog_ms
                if projected > cfg.slo_ttft_ms and cfg.admission == "shed":
                    return self._busy(
                        f"projected TTFT {projected:.0f}ms exceeds SLO "
                        f"{cfg.slo_ttft_ms:.0f}ms",
                        projected,
                    )
            rid = secrets_mod.token_hex(8)
            # adopt the client's trace id (or mint one for traceless
            # clients); it is forwarded on every downstream dispatch, so
            # the request keeps ONE trace across router, replica, and any
            # requeue-to-survivor hop
            trace = msg.get("trace") or tracing.new_trace_id()
            payload = {
                "prompt": [int(t) for t in prompt],
                "temperature": float(msg.get("temperature", 0.0)),
                "top_k": int(msg.get("top_k", 0)),
                "max_new": int(msg.get("max_new", 16)),
                "eos_id": int(msg.get("eos_id", -1)),
                "seed": int(msg.get("seed", 0)),
                "trace": trace,
            }
            entry = RouteEntry(rid=rid, payload=payload, trace=trace)
            deadline_s = msg.get("deadline_s")
            if deadline_s:
                entry.deadline_ts = time.time() + float(deadline_s)
                entry.payload["deadline_s"] = float(deadline_s)
            self._entries[rid] = entry
            self._pending.append(rid)
        self.telemetry.event(
            "req.accepted", trace=trace, rid=rid, plen=len(prompt)
        )
        return {"type": "SUBMIT", "id": rid}

    def _on_poll(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            entry = self._entries.get(str(msg.get("id")))
            if entry is None:
                raise ValueError(f"unknown request {msg.get('id')!r}")
            return {"type": "POLL", **entry.wire()}

    def _on_cancel(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            entry = self._entries.get(str(msg.get("id")))
            if entry is None or entry.done():
                return {"type": "CANCEL", "cancelled": False}
            entry.cancel_requested = True
            if entry.state in (PENDING, REQUEUED):
                self._finish_local(entry, "cancelled")
        return {"type": "CANCEL", "cancelled": True}

    def _finish_local(  # guarded-by: _lock
        self, entry: RouteEntry, state: str, error=None
    ) -> None:
        """Terminal without a downstream snapshot (lock held)."""
        entry.final = {
            "state": state,
            "tokens": [],
            "n_tokens": 0,
            "prompt_len": len(entry.payload.get("prompt", [])),
            "error": error,
            "ttft_ms": None,
            "done": True,
        }
        try:
            self._pending.remove(entry.rid)
        except ValueError:
            pass
        key = {"cancelled": "cancelled", "expired": "expired", "failed": "failed"}[
            state
        ]
        self.counters[key] += 1
        entry.counted_done = True

    def _fleet_stats(self) -> Dict[str, Any]:  # guarded-by: _lock
        """Aggregate + per-replica table (lock held).

        Latency is merged honestly: every replica's SSTATS carries its raw
        fixed-log-bucket histograms under ``latency``; those are added
        bucket-wise per signal (TTFT/TPOT/queue-wait/e2e), so the fleet's
        ``ttft_ms_p50/p90/p95/p99`` are true percentiles over ALL requests
        — not the slowest replica's, not a mean of means. The merged
        encodings ride out under ``latency`` for further aggregation
        (docs/observability.md)."""
        now = time.time()
        table = []
        agg = {
            "queue_depth": len(self._pending),
            "active_slots": 0,
            "num_slots": 0,
            "tokens_out": 0,
            "requests_done": 0,
            "requests_failed": 0,
            "prefix_hits": 0,
            "prefix_tokens_saved": 0,
            "prefill_calls": 0,
            # paged KV cache, summed over paged replicas (docs/serving.md)
            "pages_total": 0,
            "pages_free": 0,
            "pages_shared": 0,
            "preemptions": 0,
        }
        latency_dicts: Dict[str, List[Dict[str, Any]]] = {
            name: [] for name in LATENCY_SIGNALS
        }
        for r in self.replicas:
            # in-process replicas answer fresh (lock-only, no sockets);
            # remote/dead ones fall back to the probe cache
            local = getattr(r, "local_stats", lambda: None)()
            stats = local or self._stats_cache.get(r.index, {})
            quarantined = self.quarantine.is_quarantined(r.index, now)
            row = {
                **r.describe(),
                "quarantined": quarantined,
                "queue_depth": stats.get("queue_depth", 0),
                "active_slots": stats.get("active_slots", 0),
                "num_slots": stats.get("num_slots", r.spec.num_slots),
                "requests_done": stats.get("requests_done", 0),
                "tokens_per_sec": stats.get("tokens_per_sec", 0.0),
                "prefix_hits": stats.get("prefix_hits", 0),
                "prefix_tokens_saved": stats.get("prefix_tokens_saved", 0),
                "ttft_ms_p50": stats.get("ttft_ms_p50"),
                "ttft_ms_p95": stats.get("ttft_ms_p95"),
            }
            if quarantined:
                row["state"] = "quarantined"
            table.append(row)
            if r.state == UP and not quarantined:
                agg["queue_depth"] += stats.get("queue_depth", 0)
            for k in (
                "active_slots",
                "num_slots",
                "tokens_out",
                "requests_done",
                "requests_failed",
                "prefix_hits",
                "prefix_tokens_saved",
                "prefill_calls",
                "preemptions",
            ):
                agg[k] += stats.get(k, 0)
            paging = stats.get("paging") or {}
            if paging.get("paged"):
                for k in ("pages_total", "pages_free", "pages_shared"):
                    agg[k] += paging.get(k, 0)
                row["pages_free"] = paging.get("pages_free")
            for name, d in (stats.get("latency") or {}).items():
                latency_dicts.setdefault(name, []).append(d)
        merged = {
            name: merge_dicts(ds) for name, ds in latency_dicts.items()
        }
        ttft = merged.get("ttft_ms")
        for q, key in ((0.50, "p50"), (0.90, "p90"), (0.95, "p95"), (0.99, "p99")):
            agg[f"ttft_ms_{key}"] = ttft.percentile(q) if ttft else None
        tpot = merged.get("tpot_ms")
        agg["tpot_ms_p50"] = tpot.percentile(0.50) if tpot else None
        agg["tpot_ms_p95"] = tpot.percentile(0.95) if tpot else None
        qw = merged.get("queue_wait_ms")
        agg["queue_wait_ms_p50"] = qw.percentile(0.50) if qw else None
        e2e = merged.get("e2e_ms")
        agg["e2e_ms_p50"] = e2e.percentile(0.50) if e2e else None
        agg["e2e_ms_p95"] = e2e.percentile(0.95) if e2e else None
        agg["latency"] = {
            name: h.to_dict() for name, h in merged.items() if h is not None
        }
        if self.config.slo_ttft_ms is not None:
            agg["slo_ttft_ms"] = self.config.slo_ttft_ms
            agg["slo_ok"] = self.slo_ok
            agg["slo_miss"] = self.slo_miss
            judged = self.slo_ok + self.slo_miss
            # exact edge counters when available; the merged histogram's
            # bucket-interpolated view stands in before any completion
            agg["slo_attainment"] = (
                self.slo_ok / judged
                if judged
                else (ttft.attainment(self.config.slo_ttft_ms) if ttft else None)
            )
        if self.autopilot is not None:
            agg["autopilot"] = self.autopilot.status()
        # ALERTS surface: fleet-scope rules plus whatever each replica's
        # worker-scope evaluator reports in its SSTATS
        alerts = list(self.alerts.firing())
        for r in self.replicas:
            stats = self._stats_cache.get(r.index) or {}
            for a in stats.get("alerts") or []:
                alerts.append(dict(a, replica=r.index))
        agg["alerts"] = alerts
        agg["trends"] = self.metrics.trends(TREND_SIGNALS)
        return {
            **agg,
            "replicas": table,
            "routing": dict(self.counters),
            "in_flight": sum(
                1 for e in self._entries.values() if not e.done()
            ),
            "uptime_s": round(time.time() - self._started_ts, 3),
        }

    def _on_stats(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            return {"type": "SSTATS", "fleet": True, **self._fleet_stats()}

    def _metrics_body(self) -> Dict[str, Any]:
        """METRICS verb: aligned per-replica + fleet-aggregate series."""
        with self._lock:
            replicas = {
                str(idx): store.snapshot()
                for idx, store in self.replica_metrics.items()
            }
        return {
            "scope": "fleet",
            "metrics": self.metrics.snapshot(),
            "replicas": replicas,
            "alerts": self.alerts.firing(),
        }

    def _sample_metrics(self, now: float) -> None:
        """One aligned fleet observability tick (pump thread, ~1 Hz).

        Appends each replica's cached cumulative stats to its per-replica
        store AND the bucket-wise merge of the same snapshots to the fleet
        store at the same timestamp, then evaluates the fleet-scope alert
        rules. Using one ``now`` for every append is what makes windowed
        fleet queries equal the merge of windowed per-replica queries."""
        if now - self._last_metrics_tick < self.metrics.interval_s:
            return
        self._last_metrics_tick = now
        with self._lock:
            cache = {
                r.index: self._stats_cache.get(r.index)
                for r in self.replicas
            }
            pending = len(self._pending)
        latency_all: Dict[str, List[Dict[str, Any]]] = {}
        slo_ok_sum = 0
        slo_miss_sum = 0
        have_replica_slo = False
        fleet_gauges = {
            "serve.queue_depth": float(pending),
            "fleet.healthy_replicas": float(len(self._healthy())),
        }
        tokens_per_sec = 0.0
        for idx, stats in cache.items():
            if not stats:
                continue
            with self._lock:
                store = self.replica_metrics.get(idx)
                if store is None:
                    store = timeseries.SeriesStore(self.metrics.interval_s)
                    self.replica_metrics[idx] = store
            hists = {
                f"serve.{name}": d
                for name, d in (stats.get("latency") or {}).items()
            }
            counters = {"serve.requests_done": stats.get("requests_done", 0)}
            if stats.get("slo_ok") is not None:
                have_replica_slo = True
                slo_ok_sum += int(stats.get("slo_ok") or 0)
                slo_miss_sum += int(stats.get("slo_miss") or 0)
                counters["serve.slo_ok"] = stats.get("slo_ok")
                counters["serve.slo_miss"] = stats.get("slo_miss")
            store.ingest(
                now,
                gauges={
                    "serve.queue_depth": stats.get("queue_depth"),
                    "serve.active_slots": stats.get("active_slots"),
                    "serve.tokens_per_sec": stats.get("tokens_per_sec"),
                    "serve.ttft_ms": stats.get("ttft_ms_p95"),
                    "serve.pages_free": (stats.get("paging") or {}).get("pages_free"),
                },
                counters=counters,
                hists=hists,
            )
            tokens_per_sec += float(stats.get("tokens_per_sec") or 0.0)
            for name, d in (stats.get("latency") or {}).items():
                latency_all.setdefault(name, []).append(d)
        fleet_gauges["serve.tokens_per_sec"] = round(tokens_per_sec, 2)
        merged_hists: Dict[str, Dict[str, Any]] = {}
        for name, ds in latency_all.items():
            h = merge_dicts(ds)
            if h is not None:
                merged_hists[f"serve.{name}"] = h.to_dict()
        if merged_hists.get("serve.ttft_ms"):
            p95 = timeseries.hist_delta(merged_hists["serve.ttft_ms"], None)
            fleet_gauges["serve.ttft_ms"] = p95.percentile(0.95) if p95 else None
        # exact fleet-edge SLO counters when the router judges TTFT itself;
        # the sum of replica-side counters stands in otherwise
        counters = {}
        if self.config.slo_ttft_ms is not None:
            with self._lock:
                counters = {
                    "serve.slo_ok": self.slo_ok,
                    "serve.slo_miss": self.slo_miss,
                }
        elif have_replica_slo:
            counters = {"serve.slo_ok": slo_ok_sum, "serve.slo_miss": slo_miss_sum}
        self.metrics.ingest(now, gauges=fleet_gauges, counters=counters, hists=merged_hists)
        self.alerts.evaluate(now)
        self.telemetry.gauge("alerts.firing", float(len(self.alerts.firing())))

    def _on_status(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            stats = self._fleet_stats()
        status: Dict[str, Any] = {
            "type": "STATUS",
            "name": self.name,
            "kind": "serve-fleet",
            "state": "closing" if self._closing else "serving",
            "app_id": self.name,
            "run_id": 0,
            "elapsed_s": time.time() - self._started_ts,
            "serve": stats,
            "fleet": {
                "replicas": stats["replicas"],
                "routing": stats["routing"],
            },
        }
        tel = self.telemetry
        if getattr(tel, "active", False):
            snap = tel.snapshot()
            if snap:
                status["telemetry"] = {"router": snap}
        return status

    def _on_log(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            lines = list(self._log)
            self._log.clear()
            stats = self._fleet_stats()
        progress = (
            f"replicas {sum(1 for r in stats['replicas'] if r['state'] == UP)}"
            f"/{len(self.replicas)}  queue {stats['queue_depth']}  "
            f"done {stats['requests_done']}  "
            f"requeued {stats['routing']['requeued']}"
        )
        return {"type": "LOG", "logs": lines, "progress": progress}

    # ------------------------------------------------------------------ pump
    # (single background thread: all downstream sockets live here)

    # terminal entries stay pollable this long (mirrors scheduler retention)
    RETENTION_S = 300.0

    def _retire_old(self, now: float) -> None:
        with self._lock:
            dead = [
                rid
                for rid, e in self._entries.items()
                if e.done() and now - e.submitted_ts > self.RETENTION_S
            ]
            for rid in dead:
                del self._entries[rid]

    def _pump_loop(self) -> None:
        last_probe = 0.0
        while not self._stop.is_set():
            now = time.time()
            try:
                if now - last_probe >= self.config.probe_interval_s:
                    self._probe_replicas()
                    self._sample_metrics(now)
                    self._retire_old(now)
                    last_probe = now
                self._chaos_tick()
                self._sweep_down_replicas()
                self._dispatch_pending(time.time())
                self._poll_routed()
                if self.autopilot is not None:
                    self.autopilot.maybe_sample(time.time())
            except Exception as e:  # noqa: BLE001 - pump must survive anything
                self.log(f"pump error: {type(e).__name__}: {e}")
            self._stop.wait(self.config.pump_interval_s)

    def _probe_replicas(self) -> None:
        for replica in self.replicas:
            if replica.state != UP:
                self._note_failure(replica, "down")
                continue
            try:
                stats = replica.client.stats()
            except (RpcError, OSError) as e:
                self._note_failure(replica, f"{type(e).__name__}: {e}")
                continue
            with self._lock:
                self._stats_cache[replica.index] = stats
            self.quarantine.record_success(replica.index)
            with self._lock:
                self._down_handled.discard(replica.index)
        self.telemetry.gauge(
            "fleet.healthy_replicas", float(len(self._healthy()))
        )

    def _note_failure(self, replica: Replica, why: str) -> None:
        tripped = self.quarantine.record_failure(replica.index)
        if tripped:
            self.log(f"replica {replica.index} quarantined ({why})")
            self.telemetry.count("fleet.quarantined")
        # a closed port IS death — don't wait out the probe threshold
        if replica.state == DEAD or self.quarantine.is_quarantined(replica.index):
            self._handle_replica_down(replica)

    def _handle_replica_down(self, replica: Replica) -> None:
        """Requeue the dead/quarantined replica's in-flight requests ahead
        of fresh arrivals, then respawn it if budget remains."""
        with self._lock:
            if replica.index in self._down_handled:
                return
            self._down_handled.add(replica.index)
            moved = 0
            requeued_entries = []
            for entry in self._entries.values():
                if entry.replica == replica.index and not entry.done():
                    entry.state = REQUEUED
                    entry.replica = None
                    entry.remote_id = None
                    entry.snapshot = None
                    entry.resubmits += 1
                    self._pending.appendleft(entry.rid)
                    requeued_entries.append(entry)
                    moved += 1
            self.counters["requeued"] += moved
        for entry in requeued_entries:
            # explicit hop milestone: the SAME trace id continues on the
            # survivor, so the exported lane shows the loss + re-run inline
            self.telemetry.event(
                "req.requeued", trace=entry.trace, rid=entry.rid,
                replica=replica.index, resubmits=entry.resubmits,
            )
        with self._lock:
            self._stats_cache.pop(replica.index, None)
            respawn = (
                replica.state == DEAD
                and self._restarts_used < self.config.max_restarts
            )
            if respawn:
                self._restarts_used += 1
        if moved:
            self.log(
                f"replica {replica.index} down: requeued {moved} request(s) "
                "to survivors"
            )
            self.telemetry.count("fleet.requeued", moved)
        if respawn:
            try:
                addr = replica.respawn()
            except Exception as e:  # noqa: BLE001 - respawn is best-effort within budget
                self.log(
                    f"replica {replica.index} respawn failed: "
                    f"{type(e).__name__}: {e}"
                )
                return
            self.quarantine.record_success(replica.index)
            with self._lock:
                self._down_handled.discard(replica.index)
                self.counters["respawned"] += 1
            self.log(
                f"replica {replica.index} respawned at {addr[0]}:{addr[1]} "
                f"({self.config.max_restarts - self._restarts_used} restarts left)"
            )

    def _sweep_down_replicas(self) -> None:
        """Catch deaths between probes (chaos kill closes the port at once)."""
        for replica in self.replicas:
            if replica.state == DEAD:
                with self._lock:
                    handled = replica.index in self._down_handled
                if not handled:
                    self._handle_replica_down(replica)

    def _chaos_tick(self) -> None:
        """`replica_kill:replica=N` fires once the target is actually
        decoding (mid-stream by construction, so requeue is exercised)."""
        ch = chaos_mod.get()
        if ch is None:
            return
        for replica in self.replicas:
            if replica.state != UP:
                continue
            with self._lock:
                busy = any(
                    e.replica == replica.index and not e.done()
                    and e.snapshot is not None
                    and e.snapshot.get("n_tokens", 0) > 0
                    for e in self._entries.values()
                )
            if busy and ch.replica_kill(replica.index):
                self.log(f"chaos: killing replica {replica.index}")
                replica.kill()

    def _dispatch_pending(self, now: float) -> None:
        while True:
            with self._lock:
                if not self._pending:
                    return
                rid = self._pending[0]
                entry = self._entries.get(rid)
                if entry is None or entry.done():
                    self._pending.popleft()
                    continue
                if entry.deadline_ts is not None and now > entry.deadline_ts:
                    self._pending.popleft()
                    self._finish_local(
                        entry, "expired", "deadline exceeded in router queue"
                    )
                    continue
                healthy = self._healthy()
                if not healthy:
                    return
                best, proj = self._pick_replica(healthy)
                cfg = self.config
                if (
                    cfg.slo_ttft_ms is not None
                    and cfg.admission == "queue"
                    and entry.state == PENDING
                    and proj > cfg.slo_ttft_ms
                ):
                    return  # hold fresh work until capacity projects in-SLO
                self._pending.popleft()
            # milestone BEFORE the downstream round-trip: the replica's own
            # req.queued lands mid-flight, so stamping after the reply
            # would scramble the lane's dispatched→queued ordering
            self.telemetry.event(
                "req.dispatched", trace=entry.trace, rid=entry.rid,
                replica=best.index, resubmits=entry.resubmits,
            )
            remote_id = None
            if self.prefill_workers:
                remote_id = self._dispatch_disaggregated(entry, best)
            if remote_id is None:
                try:
                    remote_id = best.client.submit(**entry.payload)
                except RpcRejectedError as e:
                    with self._lock:
                        self._finish_local(entry, "failed", str(e))
                    continue
                except (RpcError, OSError) as e:
                    with self._lock:
                        entry.state = REQUEUED
                        self._pending.appendleft(rid)
                    self._note_failure(best, f"submit: {type(e).__name__}")
                    return
            with self._lock:
                entry.state = ROUTED
                entry.replica = best.index
                entry.remote_id = remote_id
                self.counters["routed"] += 1
                # book the new load locally so picks between probes see it
                cached = self._stats_cache.setdefault(best.index, {})
                cached["queue_depth"] = cached.get("queue_depth", 0) + 1
            self.telemetry.count("fleet.routed")

    def _dispatch_disaggregated(self, entry: RouteEntry, best: Replica):
        """Disaggregated dispatch (pump thread): run the prompt on a
        prefill replica, hand the KV pack to the chosen decode replica.
        Returns the downstream request id, or None to fall back to plain
        dispatch (prefill fleet down / handoff unsupported) — the decode
        replica's full engine then prefills for itself, so disaggregation
        degrades, never outages."""
        worker = pick_worker(self.prefill_workers, self._pw_rr)
        self._pw_rr += 1
        if worker is None:
            return None
        t0 = time.perf_counter()
        try:
            pack = worker.prefill(entry.payload)
        except PrefillWorkerError as e:
            self.log(f"prefill fallback: {e}")
            return None
        with self._lock:
            self.counters["prefilled"] += 1
        self.telemetry.event(
            "req.prefilled", trace=entry.trace, rid=entry.rid,
            replica=worker.index,
            plen=len(entry.payload.get("prompt", [])),
        )
        try:
            remote_id = best.submit_prefilled(entry.payload, pack)
        except Exception as e:  # noqa: BLE001 - dead/remote decode replica: plain dispatch retries
            self.log(f"handoff fallback: {type(e).__name__}: {e}")
            return None
        # handoff latency: prefill dispatch -> KV pack accepted by the
        # decode replica (covers the device_get serialization; the decode
        # side's device put shows up in its serve.kv_admit span)
        handoff_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self.counters["handoffs"] += 1
        self.telemetry.gauge("serve.handoff_ms", handoff_ms)
        self.telemetry.histogram("serve.handoff_ms", handoff_ms)
        self.telemetry.event(
            "req.handoff", trace=entry.trace, rid=entry.rid,
            prefill_replica=worker.index, decode_replica=best.index,
            handoff_ms=round(handoff_ms, 3),
        )
        return remote_id

    def _poll_routed(self) -> None:
        with self._lock:
            live = [
                (e.rid, e.replica, e.remote_id, e.cancel_requested, e.cancel_sent)
                for e in self._entries.values()
                if e.state == ROUTED and not e.done()
            ]
        for rid, idx, remote_id, want_cancel, cancel_sent in live:
            replica = self.replicas[idx]
            if replica.state != UP:
                continue  # the down-sweep requeues; don't poke a closed port
            try:
                if want_cancel and not cancel_sent:
                    replica.client.cancel(remote_id)
                    with self._lock:
                        entry = self._entries.get(rid)
                        if entry is not None:
                            entry.cancel_sent = True
                snap = replica.client.poll(remote_id)
            except RpcRejectedError:
                # replica forgot the id (restart/retention): replay it
                requeued_entry = None
                with self._lock:
                    entry = self._entries.get(rid)
                    if entry is not None and not entry.done():
                        entry.state = REQUEUED
                        entry.replica = None
                        entry.remote_id = None
                        entry.snapshot = None
                        entry.resubmits += 1
                        self.counters["requeued"] += 1
                        self._pending.appendleft(rid)
                        requeued_entry = entry
                if requeued_entry is not None:
                    self.telemetry.event(
                        "req.requeued", trace=requeued_entry.trace, rid=rid,
                        replica=idx, resubmits=requeued_entry.resubmits,
                    )
                continue
            except (RpcError, OSError) as e:
                self._note_failure(replica, f"poll: {type(e).__name__}")
                return
            completed = None
            with self._lock:
                entry = self._entries.get(rid)
                if entry is None or entry.state != ROUTED:
                    continue
                entry.snapshot = snap
                if snap.get("done") and not entry.counted_done:
                    entry.counted_done = True
                    key = {
                        "done": "completed",
                        "cancelled": "cancelled",
                        "expired": "expired",
                        "failed": "failed",
                    }.get(snap.get("state"), "completed")
                    self.counters[key] += 1
                    completed = entry
                    # exact fleet-edge SLO attainment, judged on the TTFT
                    # the serving replica measured for this request
                    if (
                        self.config.slo_ttft_ms is not None
                        and snap.get("ttft_ms") is not None
                    ):
                        if snap["ttft_ms"] <= self.config.slo_ttft_ms:
                            self.slo_ok += 1
                        else:
                            self.slo_miss += 1
            if completed is not None:
                self.telemetry.event(
                    "req.completed", trace=completed.trace, rid=rid,
                    state=snap.get("state"), replica=idx,
                    resubmits=completed.resubmits,
                )
