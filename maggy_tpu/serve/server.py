"""RPC front-end for the serving engine.

Rides the existing control-plane transport (:mod:`maggy_tpu.core.rpc` —
length-framed JSON over TCP, secret-authenticated) with a serving verb set:

* ``SUBMIT``  — ``{prompt: [int], temperature, top_k, max_new, eos_id,
  seed, deadline_s}`` -> ``{id}``
* ``POLL``    — ``{id}`` -> request snapshot (``state``, ``tokens``,
  ``ttft_ms``, ``done``)
* ``CANCEL``  — ``{id}`` -> ``{cancelled: bool}``
* ``SSTATS``  — scheduler/engine stats (queue depth, slot occupancy,
  tokens/sec, TTFT percentiles, compile counts)
* ``METRICS`` — the scheduler's time-series store as a versioned snapshot
  (``telemetry/timeseries.py``), for the router's fleet merge and
  ``tools/metrics_query.py``
* ``STATUS`` / ``LOG`` — the monitor's dashboard verbs, so
  ``python -m maggy_tpu.monitor <host:port> <secret> --dashboard`` renders a
  live serving panel with zero monitor-side configuration.

Handlers only touch the scheduler's lock-guarded host state — never device
work — so the socket loop stays responsive under load (the same contract the
experiment driver's handlers follow).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from maggy_tpu import telemetry
from maggy_tpu.core import rpc
from maggy_tpu.serve.request import SamplingParams
from maggy_tpu.serve.scheduler import Scheduler


class ServeServer:
    """Owns the RPC server + scheduler pair for one serving process."""

    def __init__(
        self,
        scheduler: Scheduler,
        secret: Optional[str] = None,
        name: str = "maggy-serve",
        telemetry_recorder=None,
    ):
        self.scheduler = scheduler
        self.name = name
        self.telemetry = telemetry_recorder or scheduler.telemetry or telemetry.get()
        self._rpc = rpc.Server(num_executors=0, secret=secret)
        self._rpc.telemetry = self.telemetry
        self._log: deque = deque(maxlen=500)
        self._started_ts = time.time()
        for verb, handler in (
            ("SUBMIT", self._on_submit),
            ("POLL", self._on_poll),
            ("CANCEL", self._on_cancel),
            ("SSTATS", self._on_stats),
            ("STATUS", self._on_status),
            ("LOG", self._on_log),
        ):
            self._rpc.register_callback(verb, handler)
        self._rpc.register_metrics(self._metrics_body)

    @property
    def secret(self) -> str:
        return self._rpc.secret

    # -------------------------------------------------------------- lifecycle

    def start(self, host: str = "0.0.0.0", port: int = 0) -> Tuple[str, int]:
        addr = self._rpc.start(host=host, port=port)
        self.scheduler.start()
        self.log(f"serving on {addr[0]}:{addr[1]} "
                 f"({self.scheduler.engine.slots.num_slots} slots)")
        return addr

    def stop(self) -> None:
        self.scheduler.stop()
        self._rpc.stop()

    def log(self, line: str) -> None:
        self._log.append(f"[{time.strftime('%H:%M:%S')}] {line}")

    # ----------------------------------------------------------------- verbs

    def _on_submit(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        prompt = msg.get("prompt")
        if not isinstance(prompt, list) or not all(
            isinstance(t, int) for t in prompt
        ):
            raise ValueError("prompt must be a list of token ids")
        params = SamplingParams(
            temperature=float(msg.get("temperature", 0.0)),
            top_k=int(msg.get("top_k", 0)),
            max_new=int(msg.get("max_new", 16)),
            eos_id=int(msg.get("eos_id", -1)),
            seed=int(msg.get("seed", 0)),
        )
        deadline_s = msg.get("deadline_s")
        req = self.scheduler.submit(
            prompt,
            params,
            deadline_s=float(deadline_s) if deadline_s else None,
            # the frame's trace id (client- or router-minted) keeps this
            # request's lifecycle correlated end to end
            trace=msg.get("trace"),
            tenant=msg.get("tenant"),
            qos=msg.get("qos"),
        )
        self.log(f"submit {req.id} len={len(prompt)} max_new={params.max_new}")
        return {"type": "SUBMIT", "id": req.id}

    def _on_poll(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"type": "POLL", **self.scheduler.poll(str(msg.get("id")))}

    def _on_cancel(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        cancelled = self.scheduler.cancel(str(msg.get("id")))
        if cancelled:
            self.log(f"cancel {msg.get('id')}")
        return {"type": "CANCEL", "cancelled": cancelled}

    def _on_stats(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"type": "SSTATS", **self.scheduler.stats()}

    def _metrics_body(self) -> Dict[str, Any]:
        sched = self.scheduler
        return {
            "scope": "worker",
            "metrics": sched.metrics.snapshot(),
            "alerts": sched.alerts.firing() + sched.sentinel.firing(),
        }

    def _on_status(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """The monitor dashboard's STATUS shape, serving flavour."""
        stats = self.scheduler.stats()
        status: Dict[str, Any] = {
            "type": "STATUS",
            "name": self.name,
            "kind": "serve",
            "state": "serving",
            "app_id": self.name,
            "run_id": 0,
            "elapsed_s": time.time() - self._started_ts,
            "serve": stats,
        }
        tel = self.telemetry
        if getattr(tel, "active", False):
            snap = tel.snapshot()
            if snap:
                status["telemetry"] = {"serve": snap}
        return status

    def _on_log(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        lines = list(self._log)
        self._log.clear()
        s = self.scheduler.stats()
        progress = (
            f"slots {s['active_slots']}/{s['num_slots']}  "
            f"queue {s['queue_depth']}  done {s['requests_done']}"
        )
        return {"type": "LOG", "logs": lines, "progress": progress}
