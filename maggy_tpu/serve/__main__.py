"""Serving CLI: load a checkpoint onto a mesh and serve it over RPC.

    python -m maggy_tpu.serve --config tiny --slots 8
    python -m maggy_tpu.serve --config llama3_8b --checkpoint /ckpts/run7 \
        --mesh fsdp --slots 16 --port 7777
    # fleet mode: router + N engine replicas behind one address
    python -m maggy_tpu.serve --config tiny --replicas 2 --slo-ttft-ms 2000

Without ``--checkpoint`` the model is randomly initialized (``--seed``) — the
demo/smoke path. The process prints the address and experiment secret on
stderr; point clients (:class:`maggy_tpu.serve.ServeClient`) or the live
monitor (``python -m maggy_tpu.monitor <host:port> <secret> --dashboard``)
at it. With ``--exp-dir`` the engine's telemetry lands in
``<exp_dir>/telemetry/worker_serve.jsonl`` for the Chrome-trace /
TensorBoard exporters.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time


def build_config(name: str, max_seq_len=None):
    """A ``DecoderConfig`` from a preset name or a JSON file of overrides."""
    from maggy_tpu.models import DecoderConfig

    presets = {"tiny": DecoderConfig.tiny, "llama3_8b": DecoderConfig.llama3_8b}
    if name.endswith(".json"):
        with open(name) as f:
            cfg = DecoderConfig(**json.load(f))
    elif name in presets:
        cfg = presets[name]()
    else:
        raise SystemExit(
            f"unknown --config {name!r}: use {sorted(presets)} or a "
            ".json file of DecoderConfig fields"
        )
    if max_seq_len:
        import dataclasses

        cfg = dataclasses.replace(cfg, max_seq_len=max_seq_len)
    return cfg


def load_or_init_params(model, cfg, checkpoint=None, step=None, seed=0):
    """Checkpoint params (train/checkpoint.py, params-only restore) or a
    seeded random init for checkpoint-free demo serving."""
    import jax
    import jax.numpy as jnp

    if checkpoint:
        from maggy_tpu.train.checkpoint import Checkpointer

        return Checkpointer(checkpoint, async_save=False).restore_params(step)
    dummy = jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)
    variables = model.init(jax.random.key(seed), dummy)
    from maggy_tpu.parallel.sharding import unbox

    return unbox(variables["params"])


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m maggy_tpu.serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--config", default="tiny",
                        help="DecoderConfig preset name or .json file")
    parser.add_argument("--checkpoint", help="Checkpointer directory to restore")
    parser.add_argument("--step", type=int, help="checkpoint step (default latest)")
    parser.add_argument("--slots", type=int, default=4,
                        help="KV-cache slots = max concurrent requests")
    parser.add_argument("--mesh", default="none",
                        help="'none', a mesh preset (dp/fsdp/tp/...), or "
                             "'auto' to consult the autotuner cache "
                             "(maggy_tpu.tune) for this model+topology and "
                             "fall back to 'none' on a miss")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--secret", help="RPC secret (default: random)")
    parser.add_argument("--seed", type=int, default=0,
                        help="param init seed when serving without a checkpoint")
    parser.add_argument("--max-seq-len", type=int,
                        help="override the config's max_seq_len (cache size)")
    parser.add_argument("--exp-dir",
                        help="directory for telemetry JSONL export")
    parser.add_argument("--name", default="maggy-serve")
    parser.add_argument("--replicas", type=int, default=1,
                        help=">1 serves a fleet: a router front-end over N "
                             "engine replicas (docs/fleet.md)")
    parser.add_argument("--slo-ttft-ms", type=float,
                        help="TTFT budget: fleet admission sheds/queues "
                             "requests whose projected TTFT exceeds it; "
                             "single-engine mode counts slo_ok/slo_miss "
                             "attainment in SSTATS")
    parser.add_argument("--admission", choices=("queue", "shed"),
                        default="queue",
                        help="fleet behavior when projection exceeds the SLO")
    parser.add_argument("--max-restarts", type=int, default=1,
                        help="fleet-wide replica respawn budget")
    parser.add_argument("--paged", dest="paged", action="store_true",
                        default=None,
                        help="paged KV cache (default on; docs/serving.md)")
    parser.add_argument("--no-paged", dest="paged", action="store_false",
                        help="dense row-per-slot KV cache fallback")
    parser.add_argument("--page-size", type=int,
                        help="KV page size in tokens (power of two dividing "
                             "max_seq_len; default 16)")
    parser.add_argument("--num-pages", type=int,
                        help="KV page pool size; default reserves the dense "
                             "equivalent (slots x max_seq_len/page_size + 1)")
    parser.add_argument("--prefill-replicas", type=int, default=0,
                        help="disaggregated fleet: N extra prefill-only "
                             "replicas; prompts prefill there and the KV "
                             "pages hand off to decode replicas "
                             "(docs/fleet.md)")
    args = parser.parse_args(argv)
    if args.prefill_replicas and args.replicas < 1:
        raise SystemExit("--prefill-replicas needs at least one decode replica")

    from maggy_tpu.models import Decoder
    from maggy_tpu.serve import Engine, Scheduler, ServeServer
    from maggy_tpu.telemetry import worker_telemetry

    cfg = build_config(args.config, args.max_seq_len)
    model = Decoder(cfg)

    mesh = None
    if args.mesh == "auto":
        # tuned-winner lookup (grid-independent alias on the env seam);
        # cache-only — never compiles — so startup cost is one JSON read
        from maggy_tpu.tune import cached_best

        tuned = cached_best(model)
        if tuned is not None:
            tuned.apply_env()
            mesh = tuned.mesh()
            print(
                f"[serve] mesh auto: tuning cache hit -> {dict(mesh.shape)} "
                f"(source={tuned.source})",
                file=sys.stderr,
            )
        else:
            print(
                "[serve] mesh auto: no tuning-cache record for this "
                "model/topology (run python -m maggy_tpu.tune); serving "
                "unsharded",
                file=sys.stderr,
            )
    elif args.mesh and args.mesh != "none":
        from maggy_tpu.parallel.mesh import mesh_for

        mesh, _ = mesh_for(sharding=args.mesh)
        print(f"[serve] mesh {args.mesh}: {dict(mesh.shape)}", file=sys.stderr)

    t0 = time.time()
    params = load_or_init_params(
        model, cfg, checkpoint=args.checkpoint, step=args.step, seed=args.seed
    )
    src = args.checkpoint or f"random init (seed {args.seed})"
    print(f"[serve] params from {src} in {time.time() - t0:.1f}s", file=sys.stderr)

    tel = None
    if args.exp_dir:
        tel = worker_telemetry("serve", args.exp_dir, role="serve")
    if args.replicas > 1 or args.prefill_replicas > 0:
        from maggy_tpu.serve.fleet import ReplicaSpec, launch_fleet

        tel_factory = None
        if args.exp_dir:
            tel_factory = lambda i: worker_telemetry(  # noqa: E731
                f"replica{i}", args.exp_dir, role="serve"
            )
        spec = ReplicaSpec(
            cfg, params, num_slots=args.slots, mesh=mesh,
            telemetry_factory=tel_factory,
            paged=args.paged, page_size=args.page_size,
            num_pages=args.num_pages,
        )
        server = launch_fleet(
            spec,
            replicas=args.replicas,
            secret=args.secret,
            name=args.name,
            slo_ttft_ms=args.slo_ttft_ms,
            admission=args.admission,
            max_restarts=args.max_restarts,
            telemetry_recorder=tel,
            prefill_replicas=args.prefill_replicas,
        )
        host, port = server.start(host=args.host, port=args.port)
        what = f"fleet router ({args.replicas} replicas"
        if args.prefill_replicas:
            what += f" + {args.prefill_replicas} prefill"
        what += ")"
    else:
        engine = Engine(
            cfg, params, num_slots=args.slots, mesh=mesh,
            telemetry_recorder=tel, paged=args.paged,
            page_size=args.page_size, num_pages=args.num_pages,
        )
        scheduler = Scheduler(engine, slo_ttft_ms=args.slo_ttft_ms)
        server = ServeServer(scheduler, secret=args.secret, name=args.name)
        host, port = server.start(host=args.host, port=args.port)
        what = "engine"
    print(
        f"[serve] {what} listening on {host}:{port}\n"
        f"[serve] secret: {server.secret}\n"
        f"[serve] monitor: python -m maggy_tpu.monitor {host}:{port} "
        f"{server.secret} --dashboard",
        file=sys.stderr,
    )

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    print("[serve] shutting down", file=sys.stderr)
    server.stop()
    if tel is not None:
        tel.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
