// Host-side data-loading primitives for the TPU input pipeline.
//
// The reference delegates its data plane to petastorm / torch DataLoader
// (core/patching/dataloader.py:33-144) — external native code. This is the
// first-party equivalent: a seeded permutation generator and a multithreaded
// row-gather that assembles minibatches outside the GIL, so a Python prefetch
// thread overlaps host batching with TPU step time.
//
// C ABI (consumed via ctypes from maggy_tpu/train/native_loader.py):
//   mtl_perm(n, seed, out)                - seeded Fisher-Yates permutation
//   mtl_gather(src, row_bytes, idx, m, dst, threads)
//                                         - dst[i] = src[idx[i]] row copy
//   mtl_version()                         - ABI version for sanity checks

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

int64_t mtl_version() { return 1; }

// xoshiro256** — fast, seedable, good enough for shuffling
struct Rng {
  uint64_t s[4];
  explicit Rng(uint64_t seed) {
    // splitmix64 seeding
    for (int i = 0; i < 4; ++i) {
      seed += 0x9E3779B97f4A7C15ULL;
      uint64_t z = seed;
      z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
      z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
      s[i] = z ^ (z >> 31);
    }
  }
  static inline uint64_t rotl(uint64_t x, int k) {
    return (x << k) | (x >> (64 - k));
  }
  uint64_t next() {
    const uint64_t result = rotl(s[1] * 5, 7) * 9;
    const uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
  // unbiased bounded draw (Lemire)
  uint64_t bounded(uint64_t n) {
    uint64_t x = next();
    __uint128_t m = (__uint128_t)x * (__uint128_t)n;
    uint64_t l = (uint64_t)m;
    if (l < n) {
      uint64_t t = (0 - n) % n;
      while (l < t) {
        x = next();
        m = (__uint128_t)x * (__uint128_t)n;
        l = (uint64_t)m;
      }
    }
    return (uint64_t)(m >> 64);
  }
};

void mtl_perm(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  Rng rng(seed);
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)rng.bounded((uint64_t)(i + 1));
    int64_t tmp = out[i];
    out[i] = out[j];
    out[j] = tmp;
  }
}

void mtl_gather(const uint8_t* src, int64_t row_bytes, const int64_t* idx,
                int64_t m, uint8_t* dst, int32_t threads) {
  if (threads < 1) threads = 1;
  if (threads == 1 || m < threads * 4) {
    for (int64_t i = 0; i < m; ++i)
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                  (size_t)row_bytes);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve((size_t)threads);
  int64_t chunk = (m + threads - 1) / threads;
  for (int32_t t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < m ? lo + chunk : m;
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                    (size_t)row_bytes);
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
