from maggy_tpu.pruner.abstractpruner import AbstractPruner
from maggy_tpu.pruner.hyperband import Hyperband

__all__ = ["AbstractPruner", "Hyperband"]
