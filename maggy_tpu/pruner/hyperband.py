"""Parallel Hyperband pruner (BOHB-style).

Capability parity with the reference ``maggy/pruner/hyperband.py:29-594``:
geometric budget brackets, per-bracket successive-halving rungs, promotion of
the top 1/eta finishers, and an async ``pruning_routine`` that hands the
optimizer one decision at a time — fresh config at the base rung, promotion
into a higher rung, IDLE while promotions wait on stragglers, or None when the
whole schedule has been consumed. Unlike the reference's ``_top`` (which, like
ASHA's ``_top_k``, ignores direction), ranking here respects ``direction``.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Union

from maggy_tpu.pruner.abstractpruner import AbstractPruner


class _Rung:
    def __init__(self, budget: float, capacity: int):
        self.budget = budget
        self.capacity = capacity
        self.trials: List[str] = []  # new_trial_ids occupying this rung
        self.promoted_from: set = set()  # source trial ids already promoted here


class _Bracket:
    def __init__(self, s: int, s_max: int, eta: int, resource_max: float):
        self.rungs: List[_Rung] = []
        n0 = int(math.ceil((s_max + 1) / (s + 1) * eta**s))
        for k in range(s + 1):
            n_k = max(1, int(n0 // eta**k))
            budget = resource_max * float(eta) ** (k - s)
            self.rungs.append(_Rung(budget, n_k))


class Hyperband(AbstractPruner):
    def __init__(
        self,
        trial_metric_getter,
        eta: int = 3,
        resource_min: float = 1,
        resource_max: float = 9,
        direction: str = "max",
        iterations: int = 1,
    ):
        """:param iterations: how many full Hyperband cycles to schedule
        (hpbandster's ``n_iterations``; the reference runs SH iterations
        concurrently, hyperband.py:137-195). With one cycle, a fleet larger
        than the base rungs can sit IDLE behind straggler-gated promotions;
        extra cycles keep every executor busy — later cycles' base rungs
        stay eligible while earlier cycles wait on stragglers."""
        super().__init__(trial_metric_getter, direction)
        if eta < 2:
            raise ValueError("eta must be >= 2")
        if resource_min <= 0 or resource_max < resource_min:
            raise ValueError("need 0 < resource_min <= resource_max")
        if iterations < 1:
            raise ValueError("iterations must be >= 1")
        self.eta = int(eta)
        s_max = int(math.floor(math.log(resource_max / resource_min, eta) + 1e-9))
        self.brackets = [
            _Bracket(s, s_max, self.eta, resource_max)
            for _ in range(int(iterations))
            for s in range(s_max, -1, -1)
        ]
        self._pending = None  # (rung, source_trial_id) awaiting report_trial

    # ------------------------------------------------------------------ interface

    def num_trials(self) -> int:
        return sum(r.capacity for b in self.brackets for r in b.rungs)

    def pruning_routine(self) -> Union[Dict, str, None]:
        if self._pending is not None:
            # optimizer must report the previous decision before asking again
            return "IDLE"
        any_incomplete = False
        for bracket in self.brackets:
            for k, rung in enumerate(bracket.rungs):
                if len(rung.trials) >= rung.capacity:
                    continue
                any_incomplete = True
                if k == 0:
                    self._pending = (rung, None)
                    return {"trial_id": None, "budget": rung.budget}
                prev = bracket.rungs[k - 1]
                if len(prev.trials) < prev.capacity:
                    continue  # lower rung not fully scheduled yet
                # presence in the getter result == finalized; a None metric
                # (errored trial) still counts as finished, ranked worst, so a
                # failed trial can never deadlock the bracket
                worst = float("-inf") if self.direction == "max" else float("inf")
                finished = {
                    t: (m if m is not None else worst)
                    for t, m in self.trial_metric_getter(prev.trials).items()
                }
                if len(finished) < prev.capacity:
                    continue  # stragglers still running
                candidate = self._best_unpromoted(finished, rung)
                if candidate is None:
                    continue  # everything promotable already promoted
                self._pending = (rung, candidate)
                return {"trial_id": candidate, "budget": rung.budget}
        return "IDLE" if any_incomplete else None

    def report_trial(self, original_trial_id: Optional[str], new_trial_id: str) -> None:
        if self._pending is None:
            return
        rung, source = self._pending
        rung.trials.append(new_trial_id)
        if source is not None:
            rung.promoted_from.add(source)
        self._pending = None

    # ------------------------------------------------------------------ internals

    def _best_unpromoted(self, finished: Dict[str, float], rung: _Rung) -> Optional[str]:
        ranked = sorted(
            finished.items(), key=lambda kv: kv[1], reverse=self.direction == "max"
        )
        for trial_id, _ in ranked:
            if trial_id not in rung.promoted_from:
                return trial_id
        return None
