"""Pruner interface (reference pruner/abstractpruner.py:23-95).

A pruner owns the budget schedule; the optimizer only chooses *which* config
fills each slot. Contract with the optimizer (reference randomsearch.py:47-90,
bayes/base.py get_suggestion):

* ``pruning_routine()`` → ``{"trial_id": <id-or-None>, "budget": b}`` to start
  a trial (None = fresh config, id = promote that config), ``"IDLE"`` to wait,
  or ``None`` when the schedule is exhausted.
* ``report_trial(original_trial_id, new_trial_id)`` records the Trial created
  for the last decision.
* ``num_trials()`` → total slots across all rungs (the driver's trial budget).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Callable, Dict, Optional, Union


class AbstractPruner(ABC):
    def __init__(self, trial_metric_getter: Callable, direction: str = "max"):
        self.trial_metric_getter = trial_metric_getter
        self.direction = direction

    @abstractmethod
    def pruning_routine(self) -> Union[Dict, str, None]:
        ...

    @abstractmethod
    def report_trial(self, original_trial_id: Optional[str], new_trial_id: str) -> None:
        ...

    @abstractmethod
    def num_trials(self) -> int:
        ...
