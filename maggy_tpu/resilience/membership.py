"""Elastic membership: epoch-numbered views of the active slice set.

The distributed tier's bounded elastic restart (PR 4) relaunches a lost
partition at the *same* world size — if a slice is preempted and never comes
back, the run dies once ``max_restarts`` is exhausted. This module holds the
state machine that turns "restartable" into "degrades and recovers"
(ROADMAP item 5, the TPU-concurrency-limits posture): the data mesh
*reshapes* when a slice leaves or rejoins, checkpoint-consistently.

Concepts:

* :class:`MembershipView` — an immutable, **epoch-numbered** snapshot of
  which slices are in the data mesh. Every transition (``drop`` /
  ``rejoin``) returns a new view with ``epoch + 1``; shrinking below
  ``min_slices`` raises :class:`MembershipViolation` instead (a clean
  deterministic failure, never a hang).
* :class:`MembershipMonitor` — the worker-side handle: holds the view the
  worker is currently *running under*, receives reshape signals (the
  driver's RESHAPE heartbeat reply, or a locally observed slice event) and
  surfaces them to ``Trainer.fit`` as a pending epoch checked at step
  boundaries.
* Control-flow exceptions — ``Trainer.fit`` raises one of these at a step
  boundary and the distributed executor's elastic loop catches it,
  negotiates the new view with the driver (the *reshape barrier*), rebuilds
  the mesh over the surviving slices, and re-enters the train_fn, which
  resumes from the latest complete checkpoint via ``fit(resume="auto")``:

  - :class:`SliceLost` (a :class:`~maggy_tpu.exceptions.WorkerLost`) — a
    slice died under us; its device state is gone, so the run falls back
    to the last *retained* checkpoint.
  - :class:`SliceRejoin` — a previously lost slice came back; graceful, so
    fit checkpoints the current step first and no step re-runs.
  - :class:`MembershipChanged` — another member's membership event reached
    us (heartbeat RESHAPE); graceful like a rejoin.

A "slice" is one ICI-connected failure domain. On a real fleet that is a
TPU slice (one worker process per slice, cross-slice traffic on DCN); on a
single host the driver *simulates* slices as contiguous partitions of the
``xla_force_host_platform_device_count`` CPU mesh (see
``parallel.mesh.slice_device_groups``), so n=16+ elastic geometries are
testable without hardware — the same generalization the dryrun machinery
uses. Docs: docs/resilience.md "Elastic membership".
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional, Tuple

from maggy_tpu.exceptions import MaggyError, WorkerLost


class MembershipViolation(MaggyError):
    """A membership transition would shrink the mesh below ``min_slices``.
    Deterministic by design: the run aborts with this error instead of
    degrading past the configured floor (or hanging on a barrier that can
    never complete)."""

    def __init__(self, slice_id: Any, n_active: int, min_slices: int):
        super().__init__(
            f"dropping slice {slice_id} would leave {n_active - 1} active "
            f"slice(s), below min_slices={min_slices}; aborting instead of "
            "degrading further"
        )
        self.slice_id = slice_id


class SliceLost(WorkerLost):
    """A slice left the mesh out from under the step loop (preemption, host
    loss, chaos ``slice_drop``). Transient: the elastic membership protocol
    reshapes around it instead of failing the run."""

    def __init__(self, slice_id: Any, step: Optional[int] = None):
        super().__init__(
            f"slice {slice_id} lost"
            + (f" at step {step}" if step is not None else "")
        )
        self.slice_id = slice_id
        self.step = step


class SliceRejoin(MaggyError):
    """Control flow, not an error: a previously lost slice is back and the
    mesh should reshape to re-admit it (chaos ``slice_rejoin``, or a dead
    partition re-registering). Raised by ``Trainer.fit`` at a step boundary
    AFTER checkpointing the current step, caught by the executor loop."""

    def __init__(self, slice_id: Any, step: Optional[int] = None):
        super().__init__(
            f"slice {slice_id} rejoining"
            + (f" at step {step}" if step is not None else "")
        )
        self.slice_id = slice_id
        self.step = step


class MembershipChanged(MaggyError):
    """Control flow: the driver announced a newer membership epoch (another
    slice left or rejoined). Raised by ``Trainer.fit`` at a step boundary
    after checkpointing, caught by the executor loop, which re-runs the
    EXEC_CONFIG exchange and rebuilds the mesh for the new view."""

    def __init__(self, epoch: int):
        super().__init__(f"membership moved to epoch {epoch}; reshape required")
        self.epoch = epoch


@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One epoch of the membership state machine.

    ``total_slices`` is the full-width slice count the run was launched
    with; ``active`` the (sorted) slice ids currently in the mesh. The view
    is immutable — transitions return the successor epoch's view, so a
    reader can never observe a half-applied reshape.
    """

    epoch: int = 0
    total_slices: int = 1
    active: Tuple[int, ...] = (0,)
    min_slices: int = 1
    # "sim" = slices are simulated device-partitions inside one worker
    # process; "workers" = one worker process per slice (pods)
    mode: str = "workers"

    def __post_init__(self):
        if self.min_slices < 1:
            raise ValueError("min_slices must be >= 1")
        if not self.active:
            raise ValueError("a MembershipView needs at least one active slice")
        object.__setattr__(self, "active", tuple(sorted(self.active)))

    @property
    def n_active(self) -> int:
        return len(self.active)

    @property
    def inactive(self) -> Tuple[int, ...]:
        return tuple(s for s in range(self.total_slices) if s not in self.active)

    @classmethod
    def full(
        cls, total_slices: int, min_slices: int = 1, mode: str = "workers"
    ) -> "MembershipView":
        return cls(
            epoch=0,
            total_slices=total_slices,
            active=tuple(range(total_slices)),
            min_slices=min_slices,
            mode=mode,
        )

    def drop(self, slice_id: int) -> "MembershipView":
        """The successor view with ``slice_id`` removed (epoch + 1).
        Raises :class:`MembershipViolation` below the floor; dropping an
        already-inactive slice is idempotent noise from a duplicate fault
        report and returns ``self`` unchanged (no epoch burn)."""
        if slice_id not in self.active:
            return self
        if self.n_active - 1 < self.min_slices:
            raise MembershipViolation(slice_id, self.n_active, self.min_slices)
        return dataclasses.replace(
            self,
            epoch=self.epoch + 1,
            active=tuple(s for s in self.active if s != slice_id),
        )

    def rejoin(self, slice_id: int) -> "MembershipView":
        """The successor view with ``slice_id`` re-admitted (epoch + 1);
        idempotent for an already-active slice."""
        if slice_id in self.active:
            return self
        if not 0 <= int(slice_id) < self.total_slices:
            raise ValueError(
                f"slice {slice_id} is outside the launch topology "
                f"(total_slices={self.total_slices})"
            )
        return dataclasses.replace(
            self,
            epoch=self.epoch + 1,
            active=tuple(sorted(self.active + (int(slice_id),))),
        )

    def as_dict(self) -> Dict[str, Any]:
        """Wire form (EXEC_CONFIG / MEMBERSHIP payload)."""
        return {
            "epoch": self.epoch,
            "total_slices": self.total_slices,
            "active": list(self.active),
            "min_slices": self.min_slices,
            "mode": self.mode,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MembershipView":
        return cls(
            epoch=int(d["epoch"]),
            total_slices=int(d["total_slices"]),
            active=tuple(int(s) for s in d["active"]),
            min_slices=int(d.get("min_slices", 1)),
            mode=str(d.get("mode", "workers")),
        )


class MembershipMonitor:
    """Worker-side membership handle.

    Holds the view this worker's mesh was built for, plus an optional
    *pending* epoch set asynchronously (the rpc heartbeat thread on a
    RESHAPE reply). ``Trainer.fit`` polls :meth:`pending_epoch` at step
    boundaries; the executor's elastic loop calls :meth:`adopt` once the
    reshape barrier delivered the new view.
    """

    def __init__(self, view: MembershipView, self_slice: Optional[int] = None):
        self._lock = threading.Lock()
        self._view = view
        self._pending: Optional[int] = None
        # worker-mode runs: the one slice THIS worker embodies — chaos
        # slice_drop then only targets it (a drop of another slice reaches
        # us as that worker's death + a RESHAPE signal, never locally), and
        # sim-mode-only seams (local rejoin) stay off
        self.self_slice = self_slice

    @property
    def view(self) -> MembershipView:
        with self._lock:
            return self._view

    @property
    def epoch(self) -> int:
        return self.view.epoch

    @property
    def active(self) -> Tuple[int, ...]:
        return self.view.active

    @property
    def inactive(self) -> Tuple[int, ...]:
        return self.view.inactive

    def signal(self, epoch: Any) -> None:  # thread-entry — the rpc heartbeat thread signals RESHAPE replies
        """Note that the driver is at a newer epoch (heartbeat thread)."""
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            return
        with self._lock:
            if epoch > self._view.epoch:
                self._pending = max(self._pending or 0, epoch)

    def pending_epoch(self) -> Optional[int]:
        """The newer epoch a reshape is pending for, or None."""
        with self._lock:
            return self._pending

    def adopt(self, view: MembershipView) -> None:
        """Install the view the mesh is being rebuilt for; clears a pending
        signal the view satisfies."""
        with self._lock:
            self._view = view
            if self._pending is not None and view.epoch >= self._pending:
                self._pending = None
