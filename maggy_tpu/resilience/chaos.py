"""Deterministic chaos harness: scripted faults on an env/config seam.

Every recovery path in the runtime (trial requeue, worker quarantine,
elastic restart, auto-resume, checkpoint fallback, heartbeat liveness) must
be testable on a CPU dev box where nothing ever actually gets preempted.
This module injects the failures deterministically: a :class:`Chaos` plan is
a list of :class:`Fault` rules, each firing a bounded number of times when
its match keys line up — same plan, same execution, same faults.

Seams (all zero-cost when no plan is installed):

* ``Trainer.fit`` calls :meth:`Chaos.kill` each step — a matching ``kill``
  rule raises :class:`WorkerKilled` (a :class:`~maggy_tpu.exceptions.WorkerLost`),
  which executors treat as worker death, not a trial error.
* ``rpc.Client._send_beat`` consults ``hb_drop`` — the beat is silently
  skipped, simulating a silent/preempted worker to the driver's liveness
  sweep.
* ``rpc.Server._dispatch`` consults ``rpc_stall`` — the matching verb's
  reply is delayed by ``secs`` (this deliberately blocks the server loop;
  chaos is a test harness, never production instrumentation).
* :func:`truncate_checkpoint` corrupts a saved step in place so the
  ``Checkpointer.restore`` fallback path can be exercised.
* The serving fleet router consults ``replica_kill`` — the matching
  replica's RPC port closes and its scheduler is abandoned mid-decode,
  simulating a preempted serving host (the router must requeue its
  in-flight requests to survivors; docs/fleet.md).
* The serve scheduler consults ``replica_slow`` per admission — a gray
  (slow-but-alive) replica whose own TTFT telemetry absorbs the injected
  latency, which is what the router's circuit breaker scores
  (docs/resilience.md "Gray failure & circuit breakers").
* The traffic generator (``serve/loadgen.py``) consults ``tenant_burst``
  while building a schedule — one tenant's offered load is multiplied,
  driving the brownout ladder without a bespoke traffic spec.
* The host-DRAM KV tier (``serve/tier/host_pool.py``) consults
  ``host_pool_slow`` per pack fill — swap-in latency lands in admission
  TTFT, exercising the tier's degraded-but-correct path (docs/serving.md
  "Host-DRAM page tier").
* The fleet autoscaler (``serve/fleet/autoscale.py``) consults
  ``replica_spawn_slow`` before warming a spawned replica (slow host
  acquisition / cold compile cache — the probation gate must hold) and
  ``replica_kill_mid_drain`` each drain tick (a scale-in victim dying
  mid-drain must fall back to the requeue-on-death path; docs/fleet.md
  "Autoscaling").
* ``Trainer.fit`` consults ``slice_drop`` / ``slice_rejoin`` each step when
  running under an elastic membership monitor — a matching ``slice_drop``
  raises :class:`~maggy_tpu.resilience.membership.SliceLost` (the slice's
  devices are gone: fall back to the last retained checkpoint), a matching
  ``slice_rejoin`` re-admits a previously dropped slice gracefully (fit
  checkpoints first). Both drive the mesh-reshape protocol end to end
  (docs/resilience.md "Elastic membership").

Activation: install programmatically (``chaos.install(Chaos.parse(spec))``)
or via ``MAGGY_TPU_CHAOS=<spec>`` in the environment — the env seam reaches
subprocess workers the same way the telemetry flag does. Spec grammar::

    MAGGY_TPU_CHAOS="kill:worker=1,step=3;hb_drop:worker=0,times=5;rpc_stall:verb=GET,secs=0.2"
    MAGGY_TPU_CHAOS="slice_drop:slice=1,step=4;slice_rejoin:slice=1,step=8"

Rules are ``kind:key=value,...`` joined by ``;``. ``times`` bounds firings
(default 1); omitted match keys match anything. Every kind must be declared
in :data:`KINDS` — ``tools/check_chaos_kinds.py`` (tier-1) closes the kind
set the same way the telemetry-name lint closes the metric set, so a typo'd
kind (``slice_dorp``) fails the lint instead of silently never firing.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from maggy_tpu.exceptions import WorkerLost

ENV_VAR = "MAGGY_TPU_CHAOS"

# The closed set of chaos kinds. Every rule a spec names and every
# ``Chaos.fire(kind, ...)`` seam in maggy_tpu/ and tests/ must use a kind
# declared here — tools/check_chaos_kinds.py lints both sides (tier-1).
# Adding a fault kind = declare it here + add its seam method below.
KINDS = frozenset(
    {
        "kill",  # raise WorkerKilled in Trainer.fit (worker N at step K)
        "hb_drop",  # swallow a worker's next heartbeat (silent worker)
        "rpc_stall",  # delay one verb's reply (wedged driver host)
        "replica_kill",  # kill a serving fleet replica mid-stream
        "slice_drop",  # a slice leaves the elastic data mesh at step K
        "slice_rejoin",  # a dropped slice comes back at step K
        "replica_slow",  # gray failure: delay replica N's admissions by ms=K
        "tenant_burst",  # multiply tenant T's offered load by mult=M (loadgen)
        "host_pool_slow",  # delay host-DRAM KV tier swap-ins by ms=K
        "replica_spawn_slow",  # delay an autoscaler spawn's warm-up by secs=K
        "replica_kill_mid_drain",  # kill replica N while its drain is in progress
    }
)


class WorkerKilled(WorkerLost):
    """Chaos-injected worker death (stands in for preemption/host loss)."""


@dataclasses.dataclass
class Fault:
    """One scripted fault: fire ``kind`` up to ``times`` times whenever every
    entry of ``match`` equals the observed attribute (string-compared)."""

    kind: str
    match: Dict[str, str] = dataclasses.field(default_factory=dict)
    times: int = 1
    arg: float = 0.0  # rule payload (e.g. rpc_stall seconds)


class Chaos:
    """A deterministic fault plan; thread-safe, fires each rule exactly its
    budgeted number of times."""

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self._lock = threading.Lock()
        self.fired: List[Tuple[str, Dict[str, Any]]] = []

    @classmethod
    def parse(cls, spec: str) -> "Chaos":
        faults = []
        for rule in spec.split(";"):
            rule = rule.strip()
            if not rule:
                continue
            kind, _, rest = rule.partition(":")
            match: Dict[str, str] = {}
            times, arg = 1, 0.0
            for pair in filter(None, (p.strip() for p in rest.split(","))):
                key, sep, value = pair.partition("=")
                if not sep:
                    raise ValueError(
                        f"chaos rule {rule!r}: expected key=value, got {pair!r}"
                    )
                if key == "times":
                    times = int(value)
                elif key == "secs":
                    arg = float(value)
                elif key == "ms":
                    # latency payloads (replica_slow) are spelled in ms on
                    # the wire but carried in seconds like secs
                    arg = float(value) / 1e3
                elif key == "mult":
                    # rate-multiplier payload (tenant_burst)
                    arg = float(value)
                else:
                    match[key.strip()] = value.strip()
            kind = kind.strip()
            if kind not in KINDS:
                raise ValueError(
                    f"chaos rule {rule!r}: unknown kind {kind!r} "
                    f"(declared kinds: {sorted(KINDS)})"
                )
            faults.append(Fault(kind, match, times=times, arg=arg))
        return cls(faults)

    def fire(self, kind: str, **attrs: Any) -> Optional[Fault]:
        """Consume one firing of the first live rule of ``kind`` matching
        ``attrs``; None when no rule applies."""
        with self._lock:
            for fault in self.faults:
                if fault.kind != kind or fault.times <= 0:
                    continue
                if all(
                    str(attrs.get(key)) == value
                    for key, value in fault.match.items()
                ):
                    fault.times -= 1
                    self.fired.append((kind, dict(attrs)))
                    return fault
        return None

    # ------------------------------------------------------------- seam API

    def kill(self, worker: Any, step: Optional[int] = None) -> None:
        """Raise :class:`WorkerKilled` when a ``kill`` rule matches."""
        if self.fire("kill", worker=worker, step=step) is not None:
            raise WorkerKilled(
                f"chaos: killed worker {worker}"
                + (f" at step {step}" if step is not None else "")
            )

    def drop_heartbeat(self, worker: Any) -> bool:
        """True when this worker's next heartbeat should be swallowed."""
        return self.fire("hb_drop", worker=worker) is not None

    def rpc_stall(self, verb: str) -> float:
        """Seconds to stall the reply to ``verb`` (0.0 = no stall)."""
        fault = self.fire("rpc_stall", verb=verb)
        return fault.arg if fault is not None else 0.0

    def replica_kill(self, replica: Any) -> bool:
        """True when this serving replica should drop dead (the fleet
        router's pump consults it only while the replica is mid-stream, so
        a matching rule always exercises requeue-to-survivors)."""
        return self.fire("replica_kill", replica=replica) is not None

    def replica_spawn_slow(self, replica: Any) -> float:
        """Seconds to delay a freshly spawned replica's warm-up (0.0 =
        none). The autoscaler's warm worker consults it before building
        the new engine, standing in for a slow host acquisition or a cold
        compile cache — the probation gate and warm timeout must hold the
        replica out of dispatch the whole time:
        ``replica_spawn_slow:replica=2,secs=1``."""
        fault = self.fire("replica_spawn_slow", replica=replica)
        return fault.arg if fault is not None else 0.0

    def replica_kill_mid_drain(self, replica: Any) -> bool:
        """True when this replica should drop dead mid-drain. The
        autoscaler's drain loop consults it each tick while the victim
        still holds in-flight streams, so a matching rule always lands
        between dispatch-stop and retire — exercising the fallback from
        graceful drain to the router's requeue-on-death path:
        ``replica_kill_mid_drain:replica=1``."""
        return self.fire("replica_kill_mid_drain", replica=replica) is not None

    def replica_slow(self, replica: Any) -> float:
        """Seconds of gray-failure latency to inject into this replica's
        next admission (0.0 = none). The scheduler consults it per admitted
        request, so the slow replica's own TTFT histograms absorb the
        delay — exactly the signal the router's circuit breaker scores
        (docs/resilience.md "Gray failure"). Spell sustained slowness with
        ``times=N``: ``replica_slow:replica=1,ms=300,times=50``."""
        fault = self.fire("replica_slow", replica=replica)
        return fault.arg if fault is not None else 0.0

    def host_pool_slow(self) -> float:
        """Seconds of swap-in latency to inject into the next host-DRAM KV
        tier fill (0.0 = none). ``HostPagePool.get`` consults it per pack
        fill — outside its lock — so a slow host-memory path surfaces as
        admission TTFT, the same signal a genuinely DMA-bound swap-in
        would produce: ``host_pool_slow:ms=50,times=10``."""
        fault = self.fire("host_pool_slow")
        return fault.arg if fault is not None else 0.0

    def tenant_burst(self, tenant: Any) -> float:
        """Offered-load multiplier for this tenant (1.0 = no burst). The
        traffic generator consults it while building a schedule, so a burst
        scenario is spelled as chaos instead of a bespoke spec:
        ``tenant_burst:tenant=bulk,mult=5``."""
        fault = self.fire("tenant_burst", tenant=tenant)
        if fault is None or fault.arg <= 0:
            return 1.0
        return fault.arg

    def slice_drop(self, slices, step: Optional[int] = None) -> Optional[Any]:
        """The id of the ACTIVE slice a ``slice_drop`` rule kills at this
        step (None = no rule fired). At most one slice drops per call — a
        multi-slice outage is spelled as multiple rules firing on
        consecutive steps, which exercises the reshape path once per loss
        the way real preemptions arrive."""
        for s in slices:
            if self.fire("slice_drop", slice=s, step=step) is not None:
                return s
        return None

    def slice_rejoin(self, slices, step: Optional[int] = None) -> Optional[Any]:
        """The id of the INACTIVE slice a ``slice_rejoin`` rule re-admits
        at this step (None = no rule fired)."""
        for s in slices:
            if self.fire("slice_rejoin", slice=s, step=step) is not None:
                return s
        return None


def truncate_checkpoint(directory: str, step: Optional[int] = None) -> int:
    """Corrupt a saved checkpoint step in place (default: the latest) by
    truncating every payload file under it to half size — the on-disk shape
    of a save interrupted mid-write. Returns the corrupted step."""
    steps = sorted(int(name) for name in os.listdir(directory) if name.isdigit())
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {directory}")
    step = int(step) if step is not None else steps[-1]
    root = os.path.join(directory, str(step))
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(size // 2)
    return step


# ------------------------------------------------------------------ registry

_lock = threading.Lock()
_active: Optional[Chaos] = None
_env_resolved = False


def install(chaos: Optional[Chaos]) -> None:
    """Install (or clear, with None) the process-wide fault plan."""
    global _active, _env_resolved
    with _lock:
        _active = chaos
        _env_resolved = True  # explicit install wins over the env seam


def get() -> Optional[Chaos]:
    """The active fault plan, lazily parsed from ``MAGGY_TPU_CHAOS`` once.
    None (the overwhelmingly common case) costs one attribute read."""
    global _active, _env_resolved
    if _env_resolved:
        return _active
    with _lock:
        if not _env_resolved:
            spec = os.environ.get(ENV_VAR, "")
            _active = Chaos.parse(spec) if spec else None
            _env_resolved = True
    return _active


def reset() -> None:
    """Clear the plan AND re-arm the env seam (test isolation)."""
    global _active, _env_resolved
    with _lock:
        _active = None
        _env_resolved = False
