"""Preemption notice handling: one final synchronous save before eviction.

Preemptible TPU pods deliver SIGTERM with a grace window before reclaiming
the host. ``Trainer.fit`` installs this hook when it holds a checkpointer:
the handler only sets an event (signal-safe), and the training loop checks
it at step boundaries — on notice it performs one final *synchronous*
checkpoint save and returns early, so ``fit(resume="auto")`` on the
replacement host loses zero completed steps.

Signal handlers can only be installed from the main thread; executor threads
(HPO trial workers) call :func:`install` too, where it degrades to the
shared event — which tests and launchers can set directly via
:func:`request`. The hook is a process-wide singleton: a pod host gets one
SIGTERM regardless of how many trainer loops it runs.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional


class PreemptionHook:
    def __init__(self) -> None:
        self._event = threading.Event()
        self._installed = False
        self._prev = None

    def install(self) -> "PreemptionHook":
        """Idempotently install the SIGTERM handler (main thread only;
        elsewhere the event alone is armed)."""
        if not self._installed and threading.current_thread() is threading.main_thread():
            try:
                self._prev = signal.getsignal(signal.SIGTERM)
                signal.signal(signal.SIGTERM, self._handler)
                self._installed = True
            except (ValueError, OSError):  # embedded interpreters may refuse
                pass
        return self

    def _handler(self, signum, frame) -> None:
        self._event.set()
        # chain a pre-existing handler (e.g. a launcher's own cleanup)
        if callable(self._prev) and self._prev not in (
            signal.SIG_IGN,
            signal.SIG_DFL,
        ):
            self._prev(signum, frame)

    def request(self) -> None:
        """Raise the preemption flag programmatically (tests, launchers that
        learn about eviction out-of-band, chaos harness)."""
        self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()

    def clear(self) -> None:
        self._event.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


HOOK = PreemptionHook()


def install() -> PreemptionHook:
    return HOOK.install()


def request() -> None:
    HOOK.request()


def requested() -> bool:
    return HOOK.requested()


def clear() -> None:
    HOOK.clear()
