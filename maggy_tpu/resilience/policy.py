"""Failure classification, retry budgets, and worker quarantine.

The scheduling question on a preemptible pod is never "did something fail"
but "is it worth paying for again": a worker death or RPC loss says nothing
about the trial it interrupted (retry it elsewhere), while an exception
raised out of ``train_fn`` will raise again on any worker (fail fast).
These are the policy objects the drivers consult; they hold no driver state
and are independently testable.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"


def classify_failure(exc: BaseException) -> str:
    """Classify a worker-side failure for the retry machinery.

    TRANSIENT — the *substrate* died out from under the work (worker/host
    death, chaos kill, RPC transport loss, OS-level connection trouble):
    rerunning the same work elsewhere can succeed. DETERMINISTIC — the work
    itself raised (a train_fn bug, bad hparams, OOM from the model shape):
    rerunning burns budget to fail identically, so the driver fails fast.
    """
    from maggy_tpu.exceptions import RpcError, WorkerLost

    if isinstance(
        exc, (WorkerLost, RpcError, ConnectionError, TimeoutError, OSError)
    ):
        return TRANSIENT
    return DETERMINISTIC


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Per-trial retry budget with exponential backoff + deterministic jitter.

    ``delay(attempt)`` is a pure function of (policy, attempt): the jitter is
    seeded from them, so a requeue schedule is reproducible run-to-run (the
    chaos tests depend on that) while still de-synchronizing workers that
    share a policy but retry different attempts.
    """

    max_retries: int = 2
    backoff_base: float = 0.5  # seconds before the first retry
    backoff_factor: float = 2.0
    backoff_cap: float = 30.0
    jitter: float = 0.25  # fraction of the delay randomized away
    seed: int = 0

    @classmethod
    def from_config(cls, config: Any) -> "RetryPolicy":
        """Build from experiment-config knobs with env overrides
        (``MAGGY_TPU_TRIAL_RETRIES`` / ``MAGGY_TPU_RETRY_BACKOFF``)."""
        return cls(
            max_retries=_env_int(
                "MAGGY_TPU_TRIAL_RETRIES", int(getattr(config, "trial_retries", 2))
            ),
            backoff_base=_env_float(
                "MAGGY_TPU_RETRY_BACKOFF",
                float(getattr(config, "retry_backoff", 0.5)),
            ),
            seed=int(getattr(config, "seed", None) or 0),
        )

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based): exponential growth,
        capped, with deterministic downward jitter."""
        base = min(
            self.backoff_base * self.backoff_factor ** max(0, attempt),
            self.backoff_cap,
        )
        r = random.Random(self.seed * 1_000_003 + attempt).random()
        return base * (1.0 - self.jitter * r)


class QuarantineTracker:
    """Take a repeatedly-lethal worker out of scheduling.

    A worker whose *consecutive* trials keep dying (flaky host, wedged
    accelerator, bad NIC) is quarantined for ``cooldown`` seconds: the driver
    stops assigning to it and stops respawning it. Any successful trial
    resets the streak. After the cooldown the worker re-enters on probation —
    the streak restarts one below the threshold, so a single further death
    re-quarantines it immediately. Thread-safe.
    """

    def __init__(self, threshold: int = 3, cooldown: float = 300.0):
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self._lock = threading.Lock()
        self._streak: Dict[int, int] = {}
        self._until: Dict[int, float] = {}

    def record_failure(self, pid: int, now: Optional[float] = None) -> bool:
        """Record one lost/dead trial on ``pid``; True when this tips the
        worker into quarantine."""
        now = time.time() if now is None else now
        with self._lock:
            streak = self._streak.get(pid, 0) + 1
            self._streak[pid] = streak
            if streak >= self.threshold and pid not in self._until:
                self._until[pid] = now + self.cooldown
                return True
            return False

    def record_success(self, pid: int) -> None:
        with self._lock:
            self._streak.pop(pid, None)
            self._until.pop(pid, None)

    def is_quarantined(self, pid: int, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        with self._lock:
            until = self._until.get(pid)
            if until is None:
                return False
            if now < until:
                return True
            # cooldown over: release on probation (one more death re-trips)
            self._until.pop(pid, None)
            self._streak[pid] = self.threshold - 1
            return False

    def quarantined(self, now: Optional[float] = None) -> List[int]:
        now = time.time() if now is None else now
        with self._lock:
            return sorted(pid for pid, until in self._until.items() if now < until)

    def snapshot(self) -> Dict[str, Any]:
        """For STATUS: remaining quarantine seconds per worker."""
        now = time.time()
        with self._lock:
            return {
                str(pid): round(until - now, 1)
                for pid, until in self._until.items()
                if until > now
            }
