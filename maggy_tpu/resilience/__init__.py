"""Fault-tolerant experiment runtime.

The reference maggy gets fault tolerance for free from Spark re-running
executor tasks (spark_driver.py:136-145); our TPU-native runtime replaced
Spark with its own RPC drivers, so recovery is a first-class runtime concern
here instead. This package holds the policy and test substrate the three
execution tiers thread through:

* :mod:`maggy_tpu.resilience.policy` — transient-vs-deterministic failure
  classification, :class:`RetryPolicy` (per-trial retry budget + exponential
  backoff with deterministic jitter), and :class:`QuarantineTracker`
  (a worker whose consecutive trials keep dying is taken out of scheduling
  for a cooldown window).
* :mod:`maggy_tpu.resilience.preemption` — SIGTERM/preemption hook installed
  by ``Trainer.fit`` when it holds a checkpointer: one final synchronous save
  before the process dies (preemptible TPU pods send SIGTERM ahead of
  reclaim).
* :mod:`maggy_tpu.resilience.chaos` — deterministic fault injector (kill
  worker N at step K, drop heartbeats, stall an RPC reply, truncate a
  checkpoint, drop/rejoin a data-mesh slice) on a config/env seam, so every
  recovery path is testable on CPU without real preemptions. The kind set
  is closed by a checked-in registry (``chaos.KINDS`` +
  ``tools/check_chaos_kinds.py``).
* :mod:`maggy_tpu.resilience.membership` — epoch-numbered elastic
  membership views: the data mesh reshapes checkpoint-consistently when a
  slice leaves or rejoins (``DistributedConfig(elastic=True,
  min_slices=...)``), instead of dying once ``max_restarts`` is exhausted.

Consumers: ``core/driver/hpo.py`` (trial requeue + quarantine),
``core/driver/distributed.py`` (bounded elastic restart),
``train/trainer.py`` (``fit(resume="auto")`` + preemption save),
``train/checkpoint.py`` (restore fallback), ``core/rpc.py`` (jittered
reconnects, chaos seams). All recovery actions count ``resilience.*``
telemetry so the monitor panel and exported traces show what the runtime
absorbed.
"""

from __future__ import annotations

from maggy_tpu.resilience.membership import (  # noqa: F401
    MembershipChanged,
    MembershipMonitor,
    MembershipView,
    MembershipViolation,
    SliceLost,
    SliceRejoin,
)
from maggy_tpu.resilience.policy import (  # noqa: F401
    DETERMINISTIC,
    TRANSIENT,
    QuarantineTracker,
    RetryPolicy,
    classify_failure,
)

__all__ = [
    "TRANSIENT",
    "DETERMINISTIC",
    "classify_failure",
    "RetryPolicy",
    "QuarantineTracker",
    "MembershipView",
    "MembershipMonitor",
    "MembershipChanged",
    "MembershipViolation",
    "SliceLost",
    "SliceRejoin",
]
