"""TPU-VM pod execution: every host runs the same user script.

The reference ships pickled closures to Spark executors over the JVM
(spark_driver.py:136-145). On a TPU pod that machinery is unnecessary — the
standard JAX SPMD launch already starts one identical Python process per host,
so the train_fn exists everywhere by construction. ``lagom(train_fn,
DistributedConfig(...))`` therefore behaves per role:

* **process 0** (or single-host): full driver + its own worker — unchanged.
* **process k > 0** (detected via ``worker_role()``): skip the driver, connect
  a worker to the process-0 driver over the host network, run the executor,
  and return the local outputs.

The driver address travels out-of-band (it is known before Python starts):
``MAGGY_TPU_DRIVER=host:port`` + ``MAGGY_TPU_SECRET=...`` env vars, or
``DistributedConfig(driver_addr=...)`` with the secret read from env. Port and
secret are printed by the driver at startup for launcher tooling.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Tuple


def worker_role(config) -> Optional[Tuple[str, int, str]]:
    """Return (host, port, secret) if this process should run as a pod worker,
    else None (run the driver)."""
    addr = os.environ.get("MAGGY_TPU_DRIVER") or getattr(config, "driver_addr", None)
    if not addr:
        return None
    explicit_role = os.environ.get("MAGGY_TPU_ROLE")
    if explicit_role == "driver":
        return None
    if explicit_role != "worker":
        # infer from the JAX process index: process 0 hosts the driver
        try:
            import jax

            if jax.process_index() == 0:
                return None
        except Exception:
            return None
    secret = os.environ.get("MAGGY_TPU_SECRET", "")
    if not secret:
        raise RuntimeError(
            "Pod worker role needs MAGGY_TPU_SECRET (printed by the driver)."
        )
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port), secret


def partition_id() -> int:
    if "MAGGY_TPU_PARTITION" in os.environ:
        return int(os.environ["MAGGY_TPU_PARTITION"])
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def run_worker(
    train_fn: Callable, config, host: str, port: int, secret: str
) -> Any:
    """Run this process as one pod worker; returns the worker's outputs."""
    from maggy_tpu import util
    from maggy_tpu.core import rpc
    from maggy_tpu.core.executors.distributed import dist_executor_fn

    # pre-flight: fetch the driver's app/run ids so this worker's artifacts
    # land in the driver's experiment directory (env vars override)
    app_id = os.environ.get("MAGGY_TPU_APP_ID")
    run_id = os.environ.get("MAGGY_TPU_RUN_ID")
    if app_id is None or run_id is None:
        probe = rpc.Client((host, port), partition_id(), secret)
        try:
            cfg_reply = probe._request({"type": "EXEC_CONFIG"})
            app_id = app_id or cfg_reply.get("app_id") or util.new_app_id()
            run_id = run_id or cfg_reply.get("run_id") or 1
        finally:
            probe.stop()
    run_id = int(run_id)
    executor = dist_executor_fn(
        train_fn=train_fn,
        config=config,
        app_id=app_id,
        run_id=run_id,
        partition_id=partition_id(),
        server_addr=(host, port),
        secret=secret,
        devices=None,  # pod worker spans its host's devices
    )
    executor()
    return {"role": "worker", "partition_id": partition_id()}
