"""TPU-VM pod execution: every host runs the same user script.

The reference ships pickled closures to Spark executors over the JVM
(spark_driver.py:136-145). On a TPU pod that machinery is unnecessary — the
standard JAX SPMD launch already starts one identical Python process per host,
so the train_fn exists everywhere by construction. ``lagom(train_fn,
DistributedConfig(...))`` therefore behaves per role:

* **process 0** (or ``MAGGY_TPU_ROLE=driver``): full driver + its own worker.
* **worker hosts** (``MAGGY_TPU_ROLE=worker``, or a non-zero
  ``jax.process_index()``): skip the driver, connect a worker to the process-0
  driver over the host network, run the executor, return the local outputs.

Bootstrap contract: on a pod with ``data_plane="auto"`` the launcher (or the
top of the user script) calls ``jax.distributed.initialize()`` — standard JAX
practice — *before* ``lagom``. The framework never initializes it late (the
backend is already up by the time executors run) and fails loudly instead of
silently training unsynchronized replicas. The driver address travels
out-of-band: ``MAGGY_TPU_DRIVER=host:port`` + ``MAGGY_TPU_SECRET=...`` env
vars, or ``DistributedConfig(driver_addr=...)``; the driver logs its reachable
address at startup for launcher tooling.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, NamedTuple, Optional, Tuple


def initialize_data_plane(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Form the global JAX data plane — call at the very top of a pod script,
    before any other JAX use (the reference's MASTER_ADDR/NCCL rendezvous,
    torch_dist_executor.py:121-140, as one explicit bootstrap call).

    Arguments default from the launcher environment (MAGGY_TPU_COORDINATOR /
    NUM_EXECUTORS / PARTITION, exported by ``python -m maggy_tpu.run
    --global-mesh``); returns False (no-op) when no coordinator is configured,
    so the same script runs single-process unchanged. On a CPU fleet (tests,
    dev boxes) cross-process collectives go through gloo automatically.
    """
    coordinator = coordinator or os.environ.get("MAGGY_TPU_COORDINATOR")
    if not coordinator:
        return False
    num_processes = int(
        num_processes
        if num_processes is not None
        else os.environ.get("MAGGY_TPU_NUM_EXECUTORS", "1")
    )
    process_id = int(
        process_id
        if process_id is not None
        else os.environ.get("MAGGY_TPU_PARTITION", "0")
    )
    if jax_backend_initialized():
        raise RuntimeError(
            "initialize_data_plane() must run before any JAX backend use "
            "(move it to the top of the script, before model/data imports "
            "that touch jax)."
        )
    import jax

    from maggy_tpu import telemetry

    tel = telemetry.get()
    # multi-process CPU collectives need the gloo transport; harmless when the
    # resolved platform is TPU (the knob only affects the CPU backend), and the
    # platform cannot be resolved before initialize without starting a backend
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    t0 = time.perf_counter()
    with tel.span("data_plane_init", coordinator=coordinator, rank=process_id):
        jax.distributed.initialize(
            coordinator, num_processes=num_processes, process_id=process_id
        )
        # Create the backend NOW: backend creation runs a global device-exchange
        # barrier across all processes, so every rank must reach it at the same
        # program point. Deferring it lets rank roles diverge — e.g. the driver
        # touching jax before its RPC server is up while workers wait on that
        # server before touching jax — a circular wait only broken by a timeout.
        jax.devices()
    tel.gauge("data_plane_init_ms", (time.perf_counter() - t0) * 1e3)
    return True


def jax_backend_initialized() -> bool:
    """True if XLA backends already exist (without creating them)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # internal API moved — assume initialized (safe side)
        return True


def driver_address(config) -> Optional[str]:
    """The single source of pod-mode detection for driver AND workers."""
    return os.environ.get("MAGGY_TPU_DRIVER") or getattr(config, "driver_addr", None)


def discover_driver(app_id: str) -> Optional[dict]:
    """Look up a running driver's {host, port, secret} by app id in the Env's
    driver registry (shared storage) — the fallback when MAGGY_TPU_DRIVER /
    MAGGY_TPU_SECRET are not set. Mirrors the reference's Hopsworks REST
    driver discovery (environment/hopsworks.py:136-190).

    Only scope="pod" records qualify for worker bootstrap: "local" records
    advertise a loopback address for same-host monitor attach and would
    misdirect a remote worker to its own machine.

    Staleness: a SIGKILLed driver cannot unregister, so a record can outlive
    its driver. A restarted driver overwrites the record at init; a worker
    that discovered a dead record fails at the connect deadline with an error
    naming the registry path (``_connect_with_deadline`` below)."""
    from maggy_tpu.core.env import EnvSing

    rec = EnvSing.get_instance().lookup_driver(app_id)
    if rec is not None and rec.get("scope", "pod") != "pod":
        return None
    return rec


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(
            f"MAGGY_TPU_DRIVER/driver_addr must be 'host:port', got {addr!r}"
        )
    return host or "127.0.0.1", int(port)


class WorkerRole(NamedTuple):
    host: str
    port: int
    secret: str
    via_registry: bool = False


def worker_role(config) -> Optional[WorkerRole]:
    """Return a :class:`WorkerRole` if this process should run as a pod
    worker, else None (run the driver)."""
    explicit_role = os.environ.get("MAGGY_TPU_ROLE")
    if explicit_role == "driver":
        return None
    addr = driver_address(config)
    discovered = None
    app_id = os.environ.get("MAGGY_TPU_APP_ID")
    if not addr and app_id:
        # No explicit address: poll the shared-storage driver registry. An
        # explicit worker waits out the driver's JAX bring-up (the record is
        # written only once the RPC server is up) — without the wait, a
        # worker that checks early would silently become a second driver and
        # deadlock the reservation barrier.
        deadline = time.time() + (
            float(os.environ.get("MAGGY_TPU_CONNECT_TIMEOUT", "120"))
            if explicit_role == "worker"
            else 0.0
        )
        while True:
            discovered = discover_driver(app_id)
            if discovered or time.time() >= deadline:
                break
            time.sleep(0.5)
        if discovered:
            addr = f"{discovered['host']}:{discovered['port']}"
    if not addr:
        if explicit_role == "worker":
            raise RuntimeError(
                "MAGGY_TPU_ROLE=worker but no driver address: set "
                "MAGGY_TPU_DRIVER=host:port, or make the driver's registry "
                "record reachable (MAGGY_TPU_APP_ID + the driver's "
                "MAGGY_TPU_LOG_ROOT on shared storage)."
            )
        return None
    if explicit_role != "worker":
        # Infer from the JAX process index. Meaningful only when
        # jax.distributed is already up; a single-process backend (dev box,
        # driver host in tests) infers "driver". A real pod must therefore
        # either initialize jax.distributed before lagom() or set
        # MAGGY_TPU_ROLE per host — otherwise every host becomes a driver and
        # the run fails loudly at the reservation barrier.
        import jax

        if jax.process_index() == 0:
            return None
    # via_registry marks the ADDRESS as registry-sourced (drives the
    # stale-record hint on connect timeout) — a registry-sourced secret with
    # an env-var address must not blame the registry for a bad address
    addr_from_registry = discovered is not None
    secret = os.environ.get("MAGGY_TPU_SECRET", "")
    if not secret:
        # the registry can supply the secret even when the address came from
        # MAGGY_TPU_DRIVER/driver_addr
        if discovered is None and app_id:
            discovered = discover_driver(app_id)
        if discovered:
            secret = discovered.get("secret", "")
    if not secret:
        raise RuntimeError(
            "Pod worker role needs MAGGY_TPU_SECRET (printed by the driver) "
            "or a driver-registry record reachable via MAGGY_TPU_APP_ID."
        )
    host, port = _parse_addr(addr)
    return WorkerRole(host, port, secret, via_registry=addr_from_registry)


def partition_id() -> int:
    if "MAGGY_TPU_PARTITION" in os.environ:
        return int(os.environ["MAGGY_TPU_PARTITION"])
    import jax

    return jax.process_index()


def _connect_with_deadline(
    host: str,
    port: int,
    pid: int,
    secret: str,
    deadline_s: float,
    hb_interval: float = 1.0,  # rpc.Client's own default
    via_registry: bool = False,
):
    """Pod hosts start simultaneously; the driver may need many seconds of JAX
    bring-up before it listens — retry well past Client's own 3 attempts.
    ``via_registry`` marks an address that came from the discovery registry so
    the timeout error can point at a possibly-stale record."""
    from maggy_tpu.core import rpc
    from maggy_tpu.exceptions import RpcError

    from maggy_tpu import telemetry

    start = time.perf_counter()
    deadline = time.time() + deadline_s
    delay = 0.2
    while True:
        try:
            client = rpc.Client((host, port), pid, secret, hb_interval)
            telemetry.get().gauge(
                "driver_connect_ms", (time.perf_counter() - start) * 1e3
            )
            return client
        except RpcError as e:
            if time.time() > deadline:
                hint = ""
                if via_registry:
                    from maggy_tpu.core.env import EnvSing

                    app_id = os.environ.get("MAGGY_TPU_APP_ID", "<app>")
                    hint = (
                        f" (address came from the driver registry "
                        f"{EnvSing.get_instance().driver_registry_path(app_id)};"
                        f" the record may be stale — a SIGKILLed driver cannot"
                        f" unregister)"
                    )
                raise RpcError(
                    f"Could not reach driver at {host}:{port} within "
                    f"{deadline_s:.0f}s{hint}: {e}"
                ) from e
            time.sleep(delay)
            delay = min(delay * 1.5, 5.0)


def _bootstrap_ids(
    host: str, port: int, pid: int, secret: str, via_registry: bool
) -> Tuple[str, int]:
    """Fetch the driver's app/run ids so this worker's artifacts land in the
    driver's experiment directory (env vars override)."""
    from maggy_tpu import util

    connect_timeout = float(os.environ.get("MAGGY_TPU_CONNECT_TIMEOUT", "120"))
    app_id = os.environ.get("MAGGY_TPU_APP_ID")
    run_id = os.environ.get("MAGGY_TPU_RUN_ID")
    if app_id is None or run_id is None:
        probe = _connect_with_deadline(host, port, pid, secret, connect_timeout,
                                       via_registry=via_registry)
        try:
            cfg_reply = probe._request({"type": "EXEC_CONFIG"})
            app_id = app_id or cfg_reply.get("app_id") or util.new_app_id()
            run_id = run_id or cfg_reply.get("run_id") or 1
        finally:
            probe.stop()
    return app_id, int(run_id)


def run_worker(
    train_fn: Callable, config, host: str, port: int, secret: str,
    via_registry: bool = False,
) -> Any:
    """Run this process as one pod worker; returns the worker's outputs."""
    from maggy_tpu.core.executors.distributed import dist_executor_fn

    pid = partition_id()
    app_id, run_id = _bootstrap_ids(host, port, pid, secret, via_registry)
    executor = dist_executor_fn(
        train_fn=train_fn,
        config=config,
        app_id=app_id,
        run_id=run_id,
        partition_id=pid,
        server_addr=(host, port),
        secret=secret,
        devices=None,  # pod worker spans its host's devices
        via_registry=via_registry,
    )
    executor()
    return {"role": "worker", "partition_id": pid}


def _worker_devices():
    """This trial worker's device lease. Default (None): span the host (one
    worker per host). MAGGY_TPU_WORKER_DEVICES="0,1" leases a subset of
    jax.local_devices() so several worker processes can share one host, each
    trial training on its own sub-slice — the trial ↔ device-lease model the
    local (thread) executors get from devices_per_trial, extended to pod
    workers. CPU/GPU hosts only, or TPU processes already chip-partitioned
    by the platform (TPU_VISIBLE_CHIPS etc.): a plain TPU runtime is
    host-exclusive, so two unpartitioned processes cannot both initialize it.

    Returns None (no lease) or a zero-arg CALLABLE resolving to the device
    list — deferred so the worker never touches the jax backend before it
    registers with the driver (a wedged accelerator transport would
    otherwise hang it invisibly; executors keep jax lazy by design,
    core/executors/trial.py)."""
    spec = os.environ.get("MAGGY_TPU_WORKER_DEVICES", "").strip()
    if not spec:
        return None
    # everything that needs no jax validates EAGERLY: a typo'd env var must
    # fail at worker startup, not after the worker has registered and been
    # handed a trial (which would strand that trial until worker_timeout —
    # and loop forever under --respawn)
    try:
        idxs = [int(i) for i in spec.split(",")]
    except ValueError as e:
        raise RuntimeError(
            f"MAGGY_TPU_WORKER_DEVICES={spec!r} is not a comma-separated "
            f"list of local device indices: {e}"
        ) from e
    if len(set(idxs)) != len(idxs) or any(i < 0 for i in idxs):
        raise RuntimeError(
            f"MAGGY_TPU_WORKER_DEVICES={spec!r} must name distinct "
            "non-negative indices — duplicate or negative leases would "
            "silently alias devices instead of a disjoint sub-slice"
        )

    def resolve():
        import jax

        local = jax.local_devices()
        if any(i >= len(local) for i in idxs):
            raise RuntimeError(
                f"MAGGY_TPU_WORKER_DEVICES={spec!r} indexes past this "
                f"host's {len(local)} local device(s)"
            )
        return [local[i] for i in idxs]

    return resolve


def run_trial_worker(
    train_fn: Callable, config, host: str, port: int, secret: str,
    via_registry: bool = False,
) -> Any:
    """Run this process as one remote TRIAL executor for an HPO/ablation
    experiment (reference parity: Spark runs trial executors on cluster
    hosts, spark_driver.py:136-145 + trial_executor.py:35-213; here any host
    running the same script with MAGGY_TPU_ROLE=worker adds trial capacity).
    Loops {register → GET → run trial → FINAL} until the driver answers
    GSTOP. A driver that has already finished and torn down its server reads
    as a graceful stop, not a crash."""
    from maggy_tpu.core.executors.trial import trial_executor_fn
    from maggy_tpu.exceptions import RpcError

    pid = partition_id()
    app_id, run_id = _bootstrap_ids(host, port, pid, secret, via_registry)
    resolve = None
    study = getattr(config, "ablation_study", None)
    if study is not None:
        # the worker holds the same AblationConfig the driver does, so the
        # model/dataset variant resolver is rebuilt host-side
        from maggy_tpu.core.driver.ablation import make_ablation_resolver

        resolve = make_ablation_resolver(study)
    executor = trial_executor_fn(
        train_fn=train_fn,
        config=config,
        app_id=app_id,
        run_id=run_id,
        partition_id=pid,
        server_addr=(host, port),
        secret=secret,
        devices=_worker_devices(),
        resolve=resolve,
    )
    try:
        executor()
    except RpcError as e:
        # the driver is unreachable mid-loop. Normal completion is NOT this
        # path (the driver answers GSTOP before tearing its server down), so
        # propagate: the process exits nonzero and a supervisor
        # (maggy_tpu.run --respawn) can put the capacity back — swallowing
        # here would read as a clean exit and defeat the respawn.
        import sys

        print(
            f"[maggy_tpu pod worker {pid}] driver unreachable ({e}); exiting "
            "for the supervisor to respawn",
            file=sys.stderr,
        )
        raise
    return {"role": "trial_worker", "partition_id": pid}
