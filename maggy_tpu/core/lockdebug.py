"""Runtime lock-order assertion (docs/static_analysis.md).

The static analyzer (``tools/check_concurrency.py``) proves the *declared*
lock graph acyclic; this module checks the *observed* one. With
``MAGGY_TPU_LOCK_ORDER=1`` the :func:`lock`/:func:`rlock` factories return
:class:`OrderedLock` wrappers that record every held→acquired pair in a
process-global order graph and raise :class:`LockOrderError` the moment an
acquisition would close a cycle — the acquisition that *could* deadlock
fails loudly on the first inverted interleaving instead of hanging once in
a thousand runs. Unset (the default), the factories return plain
``threading`` primitives with zero overhead, so production code pays
nothing for the instrumentation.

Chaos/fleet tests flip the env var to run the whole serve stack under the
assertion (tests/test_concurrency_lint.py).
"""
import os
import threading
from typing import Dict, List, Set, Tuple

__all__ = [
    "LockOrderError",
    "OrderedLock",
    "enabled",
    "lock",
    "rlock",
    "condition",
    "observed_order",
    "reset",
]

ENV_VAR = "MAGGY_TPU_LOCK_ORDER"


class LockOrderError(RuntimeError):
    """An acquisition closed a cycle in the observed lock-order graph."""


# name -> names observed acquired while it was held; one process-global
# graph so an inversion between two subsystems' locks is caught no matter
# which objects embody them
_graph_lock = threading.Lock()
_order: Dict[str, Set[str]] = {}
_tls = threading.local()


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") not in ("", "0")


def reset() -> None:
    """Drop the observed graph (test isolation)."""
    with _graph_lock:
        _order.clear()


def observed_order() -> Dict[str, Tuple[str, ...]]:
    """Copy of the observed held→acquired graph."""
    with _graph_lock:
        return {src: tuple(sorted(dsts)) for src, dsts in _order.items()}


def _held() -> List[str]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _reaches(src: str, dst: str) -> bool:
    # caller holds _graph_lock
    seen: Set[str] = set()
    frontier = [src]
    while frontier:
        n = frontier.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        frontier.extend(_order.get(n, ()))
    return False


def _note_acquire(name: str) -> None:
    held = _held()
    for h in held:
        if h == name:
            continue
        with _graph_lock:
            if _reaches(name, h):
                raise LockOrderError(
                    f"lock-order inversion: acquiring {name!r} while holding "
                    f"{h!r}, but the order {name!r} -> ... -> {h!r} was "
                    "already observed — two threads interleaving these "
                    "acquisitions can deadlock"
                )
            _order.setdefault(h, set()).add(name)


class OrderedLock:
    """A named lock that asserts global acquisition order.

    Forwards the ``_release_save``/``_acquire_restore``/``_is_owned`` trio
    so ``threading.Condition`` built over an ordered rlock keeps exact
    RLock wait semantics.
    """

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _note_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held().append(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        stack = _held()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:
            stack.remove(self.name)

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    # ---- Condition integration (recursive full-release around wait()) ----

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        stack = _held()
        while self.name in stack:  # wait() drops every recursion level
            stack.remove(self.name)
        return state

    def _acquire_restore(self, state) -> None:
        _note_acquire(self.name)
        self._inner._acquire_restore(state)
        _held().append(self.name)

    def locked(self) -> bool:
        probe = getattr(self._inner, "locked", None)
        return bool(probe()) if probe is not None else False

    def __repr__(self) -> str:
        return f"OrderedLock({self.name!r}, {self._inner!r})"


def lock(name: str):
    """A ``threading.Lock`` — order-asserted when MAGGY_TPU_LOCK_ORDER=1."""
    inner = threading.Lock()
    return OrderedLock(name, inner) if enabled() else inner


def rlock(name: str):
    """A ``threading.RLock`` — order-asserted when MAGGY_TPU_LOCK_ORDER=1."""
    inner = threading.RLock()
    return OrderedLock(name, inner) if enabled() else inner


def condition(name: str):
    """A ``threading.Condition`` over an order-asserted rlock."""
    return threading.Condition(rlock(name))
