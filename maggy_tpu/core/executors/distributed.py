"""Distributed-training executor.

Capability parity with the reference's ``torch_dist_executor_fn`` /
``tf_dist_executor`` (core/executors/torch_dist_executor.py:63-422,
tf_dist_executor.py:35-480): register → await all workers → fetch the cluster
config → initialize the data plane → inject → run → barrier-free finalize.

TPU-native data plane: no NCCL env rendezvous — on a multi-host pod each worker
calls ``jax.distributed.initialize(coordinator, num_processes, process_id)``
with the coordinator address distributed via EXEC_CONFIG, then builds one
global mesh; XLA collectives ride ICI/DCN. In local mode (one process) the mesh
spans the host's devices directly.
"""

from __future__ import annotations

import os
import socket as socket_mod
import time
import traceback
from typing import Callable, Optional

from maggy_tpu import util
from maggy_tpu.core.env import EnvSing
from maggy_tpu.exceptions import EarlyStopException, RpcError, WorkerLost
from maggy_tpu.reporter import Reporter
from maggy_tpu.resilience.membership import (
    MembershipChanged,
    MembershipMonitor,
    MembershipView,
    SliceLost,
    SliceRejoin,
)


def dist_executor_fn(
    train_fn: Callable,
    config,
    app_id: str,
    run_id: int,
    partition_id: int,
    server_addr,
    secret: str,
    devices: Optional[list] = None,
    via_registry: bool = False,
) -> Callable[[], None]:
    def _executor() -> None:
        from maggy_tpu import telemetry

        env = EnvSing.get_instance()
        exp_dir = env.experiment_dir(app_id, run_id)
        reporter = Reporter(
            log_file=os.path.join(exp_dir, f"executor_{partition_id}.log"),
            partition_id=partition_id,
        )
        # per-worker recorder, ambient for this thread: Trainer.fit inside
        # the train_fn records step metrics into it, heartbeats attach
        # snapshots for the driver's STATUS panel and flush it to JSONL
        tel = telemetry.worker_telemetry(partition_id, exp_dir, role="dist", env=env)
        telemetry.set_current(tel)
        # pod hosts start simultaneously: the driver may need many seconds of
        # JAX bring-up before it listens, so retry well past Client's own 3
        # attempts
        from maggy_tpu.core.pod import _connect_with_deadline

        client = _connect_with_deadline(
            server_addr[0],
            server_addr[1],
            partition_id,
            secret,
            float(os.environ.get("MAGGY_TPU_CONNECT_TIMEOUT", "120")),
            hb_interval=config.hb_interval,
            via_registry=via_registry,
        )
        client.telemetry = tel
        try:
            client.register(meta={"host": socket_mod.gethostname()})
            client.start_heartbeat(reporter)
            with tel.span("await_reservations"):
                client.await_reservations()
            exec_config = client.get_message("EXEC_CONFIG")

            # elastic membership (docs/resilience.md): the monitor holds the
            # view this worker's mesh is built for; heartbeats report its
            # epoch and a RESHAPE reply flags it for the next step boundary
            monitor = None
            if exec_config.get("membership"):
                view = MembershipView.from_dict(exec_config["membership"])
                monitor = MembershipMonitor(
                    view,
                    self_slice=partition_id if view.mode == "workers" else None,
                )
                client.membership = monitor
            reporter.reset(trial_id=f"dist_{partition_id}")
            worker_dir = os.path.join(exp_dir, f"worker_{partition_id}")

            module = _apply_model_policies(
                config.module, config.mixed_precision, config.remat
            )
            hparams = dict(getattr(config, "hparams", None) or {})
            dataset = config.dataset
            if config.process_data is not None:
                dataset = config.process_data(dataset)

            metric = None
            outputs = {}
            error = None
            while True:
                with tel.span("build_context"):
                    ctx = _build_context(exec_config, config, monitor)
                available = {
                    "module": module,
                    "model": module,
                    "dataset": dataset,
                    "hparams": hparams,
                    "reporter": reporter,
                    "ctx": ctx,
                    "train_ctx": ctx,
                    "mesh": ctx.mesh,
                    "trial_dir": worker_dir,
                    "rng": _seed_key(config.seed),
                }
                kwargs = util.inject_kwargs(train_fn, available)
                try:
                    # train_fn prints ship with the heartbeat logs, same as
                    # the trial executor (reference trial_executor.py:93-103)
                    from maggy_tpu.reporter import capture_prints

                    with tel.span(
                        "train_fn", partition=partition_id
                    ), capture_prints(reporter):
                        retval = train_fn(**kwargs)
                    if retval is not None:
                        # per-worker dir: concurrent workers must not clobber
                        # outputs. The evaluator's outputs are free-form (no
                        # optimization-key requirement) but persist identically.
                        metric, outputs = util.normalize_return_val(
                            retval, "metric", require_metric=ctx.role != "evaluator"
                        )
                        util.persist_outputs(outputs, metric, worker_dir)
                    break
                except EarlyStopException as e:
                    metric = e.metric
                    outputs = {"metric": metric}
                    break
                except (SliceLost, SliceRejoin, MembershipChanged) as e:
                    if monitor is None:
                        raise  # not elastic: SliceLost reads as worker death
                    if (
                        isinstance(e, SliceLost)
                        and monitor.self_slice is not None
                        and e.slice_id == monitor.self_slice
                    ):
                        # this worker IS the lost slice: die like one — the
                        # driver's death hook turns it into a membership
                        # drop and the survivors reshape
                        raise
                    # the reshape loop: report the event, wait out the
                    # barrier, rebuild for the new view, re-enter train_fn
                    # (which resumes from the latest complete checkpoint)
                    exec_config = _reshape(client, monitor, config, e, tel, reporter)
                except WorkerLost:
                    # worker death (preemption / chaos kill): no FINAL — the
                    # executor dies and the driver's elastic path
                    # (max_restarts relaunch, or a membership drop when
                    # elastic=True) takes over
                    raise
                except Exception as e:  # noqa: BLE001
                    error = f"{type(e).__name__}: {e}"
                    reporter.log(
                        f"Distributed worker {partition_id} failed:\n"
                        f"{traceback.format_exc()}"
                    )
                    break
            tel.flush()  # events are durable before FINAL ships
            client.finalize_metric(
                f"dist_{partition_id}", metric, outputs=util._jsonify(outputs), error=error
            )
        finally:
            client.stop()
            reporter.close()
            telemetry.set_current(None)
            tel.close()

    def _reshape(client, monitor, config, event, tel, reporter):
        """One membership transition on the worker side: report the observed
        slice event (if this worker observed one), wait at the reshape
        barrier until every member acked the new epoch, adopt the view, and
        re-run the EXEC_CONFIG exchange for the new layout."""
        old_epoch = monitor.epoch
        kind = (
            "drop"
            if isinstance(event, SliceLost)
            else "rejoin"
            if isinstance(event, SliceRejoin)
            else None
        )
        if kind is not None:
            client.request(
                {
                    "type": "SLICE_EVENT",
                    "kind": kind,
                    "slice": event.slice_id,
                    "step": event.step,
                }
            )
        reporter.log(
            f"Worker {partition_id}: membership event ({event}); awaiting "
            "reshape barrier"
        )
        t0 = time.perf_counter()
        deadline = time.time() + float(
            os.environ.get("MAGGY_TPU_RESHAPE_TIMEOUT", "120")
        )
        acked = old_epoch
        while True:
            reply = client.request({"type": "MEMBERSHIP", "epoch": acked})
            if reply.get("aborted"):
                raise RpcError(
                    "membership reshape aborted by the driver (see the "
                    "experiment error — e.g. a min_slices violation)"
                )
            view = MembershipView.from_dict(reply["view"])
            acked = view.epoch
            if view.epoch > old_epoch and reply.get("ready"):
                monitor.adopt(view)
                break
            if time.time() > deadline:
                raise RpcError(
                    f"reshape barrier for epoch > {old_epoch} did not "
                    "complete within MAGGY_TPU_RESHAPE_TIMEOUT"
                )
            time.sleep(0.01)
        tel.gauge("resilience.membership_epoch", view.epoch)
        tel.gauge("resilience.active_slices", view.n_active)
        reporter.log(
            f"Worker {partition_id}: reshaped to membership epoch "
            f"{view.epoch} (active slices {list(view.active)}/"
            f"{view.total_slices}, {(time.perf_counter() - t0) * 1e3:.0f}ms "
            "barrier); resuming from the latest complete checkpoint"
        )
        return client.get_message("EXEC_CONFIG")

    def _build_context(exec_config, config, monitor=None):
        import jax

        from maggy_tpu.train.trainer import TrainContext

        num_processes = exec_config.get("num_processes", 1)
        data_plane = getattr(config, "data_plane", "auto")
        mesh_devices = devices if devices else None
        membership = exec_config.get("membership") or {}
        if monitor is not None and membership.get("mode") == "sim":
            # simulated slices (docs/distributed.md "Slice topology"): this
            # worker's device lease splits into total_slices contiguous
            # partitions; the mesh spans the ACTIVE ones under an outer
            # `slice` axis, so n=16+ elastic geometries run on the CPU mesh
            view = monitor.view
            return TrainContext.create_sliced(
                config.sharding,
                total_slices=view.total_slices,
                active=view.active,
                devices=mesh_devices,
                role="chief" if partition_id == 0 else "worker",
                membership=monitor,
            )
        if exec_config.get("evaluator_partition") == partition_id:
            # dedicated evaluation role (reference tf_dist_executor.py:138-144):
            # outside the training group, so never part of a global mesh —
            # build a host-local context over this worker's device lease
            n = len(mesh_devices) if mesh_devices is not None else len(jax.devices())
            return TrainContext.create(
                config.resolve_sharding(n), devices=mesh_devices, role="evaluator"
            )
        pod = bool(exec_config.get("coordinator"))  # driver advertises this only in pod mode
        if data_plane == "auto":
            if jax.process_count() > 1:
                mesh_devices = None  # launcher-formed global mesh (§2.9 ICI/DCN)
            elif pod and num_processes > 1:
                # The MASTER_ADDR/NCCL-rendezvous moment (reference
                # torch_dist_executor.py:121-140). By executor time the XLA
                # backend is long since initialized, so a late
                # jax.distributed.initialize cannot work — require the
                # standard JAX practice and fail loudly; silently
                # unsynchronized replicas would be worse.
                raise RuntimeError(
                    "data_plane='auto' on a multi-host pod requires "
                    "jax.distributed.initialize() before lagom() (call it at "
                    "the top of your script or via the launcher), or pass "
                    "DistributedConfig(data_plane='local') for independent "
                    "per-host replicas."
                )

        n = len(mesh_devices) if mesh_devices is not None else len(jax.devices())
        spec = config.resolve_sharding(n)
        role = "chief" if partition_id == 0 else "worker"
        return TrainContext.create(
            spec, devices=mesh_devices, role=role, membership=monitor
        )

    return _executor


def _seed_key(seed: int):
    import jax

    return jax.random.key(int(seed))


def _apply_model_policies(module, mixed_precision: bool, remat: bool):
    """Apply config-level dtype/remat policy to framework model families.

    Our models carry a frozen ``cfg`` dataclass with dtype/remat fields
    (models/transformer.py); user modules without one keep their own policy —
    the knobs only override what they can reach, loudly."""
    import dataclasses
    import logging

    cfg = getattr(module, "cfg", None)
    if cfg is None or not dataclasses.is_dataclass(cfg):
        if not mixed_precision or remat:
            logging.getLogger(__name__).warning(
                "mixed_precision/remat requested but %s has no cfg dataclass; "
                "module keeps its own dtype/remat policy.",
                type(module).__name__,
            )
        return module
    import jax.numpy as jnp

    updates = {}
    if hasattr(cfg, "dtype"):
        updates["dtype"] = jnp.bfloat16 if mixed_precision else jnp.float32
    if hasattr(cfg, "remat"):
        updates["remat"] = bool(remat or getattr(cfg, "remat", False))
    if not updates:
        return module
    return type(module)(dataclasses.replace(cfg, **updates))
