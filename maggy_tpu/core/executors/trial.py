"""Trial executor — the worker-side loop for HPO/ablation/single-run experiments.

Capability parity with the reference ``trial_executor_fn``
(core/executors/trial_executor.py:35-213): register → heartbeat → loop
{blocking get_suggestion → per-trial logdir + .hparams.json → signature-based
kwarg injection → train_fn → normalize return value → finalize_metric} until
GSTOP. Early stops arrive as EarlyStopException out of ``reporter.broadcast``
and keep the last metric (trial_executor.py:194-196).

TPU-native differences: the worker holds a lease on a disjoint device group
(passed as the ``devices`` kwarg, usable as ``jax.jit(..., device=devices[0])``
or a sub-mesh); train_fn errors are reported to the driver as errored trials
instead of killing a Spark task.
"""

from __future__ import annotations

import os
import socket as socket_mod
import traceback
from typing import Any, Callable, Dict, Optional

from maggy_tpu import constants, util
from maggy_tpu.core import rpc
from maggy_tpu.core.env import EnvSing
from maggy_tpu.exceptions import EarlyStopException, WorkerLost
from maggy_tpu.reporter import Reporter, capture_prints

# keys stripped from trial params before they reach the train_fn as hparams
# ("budget" stays available via the dedicated kwarg and in hparams for ASHA-style
# train_fns; "run"/"rep" are pure bookkeeping nonces)
_CONTROL_KEYS = ("run", "rep")


def trial_executor_fn(
    train_fn: Callable,
    config,
    app_id: str,
    run_id: int,
    partition_id: int,
    server_addr,
    secret: str,
    devices: Optional[list] = None,
    resolve: Optional[Callable[[Dict[str, Any], Dict[str, Any]], Dict[str, Any]]] = None,
) -> Callable[[], None]:
    # one lease-wide TrainContext shared by every trial this worker runs
    # (same devices -> same mesh; built only if the train_fn asks for it,
    # so metric-only train_fns never touch jax). ``devices`` may be a
    # zero-arg callable (pod workers' env-spec lease, core/pod.py
    # _worker_devices) resolved here — also lazily, same reason
    _ctx_cache: Dict[str, Any] = {}

    def _lease_devices():
        if "devices" not in _ctx_cache:
            _ctx_cache["devices"] = devices() if callable(devices) else devices
        return _ctx_cache["devices"]

    def _lease_ctx():
        if "ctx" not in _ctx_cache:
            from maggy_tpu.train.trainer import TrainContext

            # honor a sharding preset configured on the experiment; default dp
            preset = getattr(config, "sharding", None) or "dp"
            _ctx_cache["ctx"] = TrainContext.create(
                preset, devices=_lease_devices() or None
            )
        return _ctx_cache["ctx"]

    def _executor() -> None:
        from maggy_tpu import telemetry

        env = EnvSing.get_instance()
        exp_dir = env.experiment_dir(app_id, run_id)
        log_file = os.path.join(exp_dir, f"executor_{partition_id}.log")
        reporter = Reporter(log_file=log_file, partition_id=partition_id)
        # per-worker recorder, ambient for this thread: Trainer.fit and the
        # Checkpointer inside the train_fn record into it; the heartbeat
        # attaches snapshots and flushes it to the JSONL sink every beat
        tel = telemetry.worker_telemetry(partition_id, exp_dir, role="trial", env=env)
        telemetry.set_current(tel)
        client = rpc.Client(
            server_addr, partition_id, secret, hb_interval=config.hb_interval,
            telemetry=tel,
        )
        try:
            client.register(
                meta={
                    "host": socket_mod.gethostname(),
                    # a callable lease is deliberately NOT resolved here —
                    # registration must never touch the jax backend
                    "devices": (
                        [f"lease:{os.environ.get('MAGGY_TPU_WORKER_DEVICES', '?')}"]
                        if callable(devices)
                        else [str(d) for d in (devices or [])]
                    ),
                }
            )
            client.start_heartbeat(reporter)
            while True:
                reply = client.get_suggestion()
                if reply["type"] == "GSTOP":
                    break
                _run_trial(reply, client, reporter, env)
        finally:
            client.stop()
            reporter.close()
            telemetry.set_current(None)
            tel.close()

    def _run_trial(reply: Dict[str, Any], client: rpc.Client, reporter: Reporter, env) -> None:
        from maggy_tpu import tensorboard as tb

        trial_id, params = reply["trial_id"], dict(reply["params"])
        reporter.reset(trial_id)
        trial_dir = env.trial_dir(app_id, run_id, trial_id)
        tb._register(trial_dir)  # registry only; persistence is the line below
        try:
            env.dump(util._jsonify(params), os.path.join(trial_dir, constants.HPARAMS_FILE))
        except OSError:
            pass

        hparams = {
            **dict(getattr(config, "hparams", None) or {}),
            **{k: v for k, v in params.items() if k not in _CONTROL_KEYS},
        }
        import inspect as _inspect

        fn_params = _inspect.signature(train_fn).parameters
        available = {
            "hparams": hparams,
            "reporter": reporter,
            "model": getattr(config, "model", None),
            "dataset": getattr(config, "dataset", None),
            # resolved only when asked for: a callable (env-spec) lease
            # touches the jax backend, and metric-only train_fns never do
            "devices": _lease_devices() if "devices" in fn_params else None,
            "trial_dir": trial_dir,
            "budget": params.get("budget"),
        }
        if resolve is not None:
            # experiment-kind hook: ablation swaps in per-trial model/dataset
            available = resolve(params, available)
        if "ctx" in fn_params:
            # lease-wide TrainContext, built only when the train_fn asks for
            # it so metric-only train_fns never touch jax
            available["ctx"] = _lease_ctx()
        kwargs = util.inject_kwargs(train_fn, available)

        from maggy_tpu import telemetry

        tel = telemetry.get()
        metric: Optional[float] = None
        outputs: Dict[str, Any] = {}
        error: Optional[str] = None
        early = False
        try:
            # train_fn prints ship to the driver with the heartbeat logs
            # (reference trial_executor.py:93-103)
            with tel.span("trial", trial_id=trial_id), capture_prints(reporter):
                retval = train_fn(**kwargs)
            metric = util.handle_return_val(
                retval, trial_dir, config.optimization_key
            )
            outputs = retval if isinstance(retval, dict) else {config.optimization_key: metric}
        except EarlyStopException as e:
            early = True
            metric = e.metric if e.metric is not None else reporter.get_metric()
            outputs = {config.optimization_key: metric}
            reporter.log(f"Trial {trial_id} early-stopped at metric {metric}")
        except WorkerLost:
            # worker death (preemption / chaos kill), not a trial error: no
            # FINAL goes out — the executor dies with it and the driver
            # requeues the in-flight trial and respawns/quarantines the slot
            tb._unregister()
            raise
        except Exception as e:  # noqa: BLE001 - errored trial, not a dead worker
            error = f"{type(e).__name__}: {e}"
            reporter.log(f"Trial {trial_id} failed:\n{traceback.format_exc()}")

        tb._unregister()
        tel.count("trials_errored" if error else "trials_done")
        tel.flush()  # trial boundary: events are durable before FINAL ships
        client.finalize_metric(
            trial_id,
            metric,
            outputs=util._jsonify(outputs),
            error=error,
            early_stopped=early,
        )

    return _executor
