"""GCS environment — the cloud-storage analogue of the reference's HDFS/DBFS
environments (core/environment/hopsworks.py:33, databricks.py:23).

Uses ``fsspec``/``gcsfs`` when importable; otherwise raises a clear error at first
use so local development never needs the dependency.
"""

from __future__ import annotations

import posixpath
from typing import List, Optional

from maggy_tpu.core.env.base import BaseEnv


def _fs():
    try:
        import fsspec

        return fsspec.filesystem("gs")
    except Exception as e:  # pragma: no cover - exercised only on cloud images
        raise RuntimeError(
            "GCS environment requires fsspec+gcsfs; install them or use a local "
            "MAGGY_TPU_LOG_ROOT."
        ) from e


class GcsEnv(BaseEnv):
    def __init__(self, root: Optional[str] = None):
        super().__init__(root or "gs://maggy-tpu-experiments")
        self._fs = None

    @property
    def fs(self):
        if self._fs is None:
            self._fs = _fs()
        return self._fs

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def mkdir(self, path: str) -> None:
        self.fs.makedirs(path, exist_ok=True)

    def delete(self, path: str, recursive: bool = False) -> None:
        if self.fs.exists(path):
            self.fs.rm(path, recursive=recursive)

    def open_file(self, path: str, mode: str = "r"):
        # BaseEnv.dump/load_json work unchanged through this override.
        return self.fs.open(path, mode)

    def listdir(self, path: str) -> List[str]:
        return sorted(posixpath.basename(p) for p in self.fs.ls(path))

    def _atomic_dump(self, data, path: str) -> None:
        # a GCS object PUT is atomic at the object level: readers see the old
        # object or the new one, never a partial write — no rename dance needed
        self.dump(data, path)

    def experiment_dir(self, app_id: str, run_id: int) -> str:
        d = posixpath.join(self.root, app_id, str(run_id))
        self.mkdir(d)
        return d

    def trial_dir(self, app_id: str, run_id: int, trial_id: str) -> str:
        d = posixpath.join(self.experiment_dir(app_id, run_id), trial_id)
        self.mkdir(d)
        return d
