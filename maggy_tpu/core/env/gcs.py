"""Cloud-storage environment — the analogue of the reference's HDFS/DBFS
environments (core/environment/hopsworks.py:33, databricks.py:23).

Backed by ``fsspec``: the filesystem protocol comes from the root URL's
scheme (``gs://`` in production, ``memory://`` in tests — which is how this
class is exercised for real without a bucket, VERDICT r3 item 8). Raises a
clear error at first use when the protocol's driver isn't importable, so
local development never needs gcsfs.
"""

from __future__ import annotations

import posixpath
from typing import List, Optional

from maggy_tpu.core.env.base import BaseEnv


def _fs(protocol: str):
    try:
        import fsspec

        return fsspec.filesystem(protocol)
    except Exception as e:
        raise RuntimeError(
            f"Cloud environment requires fsspec with the {protocol!r} driver "
            "(gcsfs for gs://); install it or use a local MAGGY_TPU_LOG_ROOT."
        ) from e


class GcsEnv(BaseEnv):
    def __init__(self, root: Optional[str] = None):
        super().__init__(root or "gs://maggy-tpu-experiments")
        self.protocol = self.root.split("://", 1)[0] if "://" in self.root else "gs"
        self._fs = None

    @property
    def fs(self):
        if self._fs is None:
            self._fs = _fs(self.protocol)
        return self._fs

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def mkdir(self, path: str) -> None:
        self.fs.makedirs(path, exist_ok=True)

    def delete(self, path: str, recursive: bool = False) -> None:
        if self.fs.exists(path):
            self.fs.rm(path, recursive=recursive)

    def open_file(self, path: str, mode: str = "r"):
        # BaseEnv.dump/load_json work unchanged through this override.
        return self.fs.open(path, mode)

    def listdir(self, path: str) -> List[str]:
        # fs.ls raises FileNotFoundError (an OSError) for missing paths —
        # exactly what callers catch; no extra exists() round-trip
        return sorted(
            posixpath.basename(p) for p in self.fs.ls(path, detail=False)
        )

    def _atomic_dump(self, data, path: str) -> None:
        # an object-store PUT is atomic at the object level: readers see the
        # old object or the new one, never a partial write — no rename dance
        self.dump(data, path)

    def experiment_dir(self, app_id: str, run_id: int) -> str:
        d = posixpath.join(self.root, app_id, str(run_id))
        self.mkdir(d)
        return d

    def trial_dir(self, app_id: str, run_id: int, trial_id: str) -> str:
        d = posixpath.join(self.experiment_dir(app_id, run_id), trial_id)
        self.mkdir(d)
        return d
