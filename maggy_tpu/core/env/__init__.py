"""Environment singleton (reference core/environment/singleton.py:20-62).

Selection: a ``MAGGY_TPU_LOG_ROOT`` with a URL scheme (``gs://``,
``memory://``, any fsspec protocol — or ``MAGGY_TPU_ENV=gcs``) picks the
cloud environment; otherwise local filesystem.
"""

from __future__ import annotations

import os
from typing import Optional

from maggy_tpu.core.env.base import BaseEnv

_instance: Optional[BaseEnv] = None


def get_instance() -> BaseEnv:
    global _instance
    if _instance is None:
        root = os.environ.get("MAGGY_TPU_LOG_ROOT", "")
        # any URL scheme routes through fsspec (incl. file:// — fsspec's
        # local driver handles it; BaseEnv would treat it as a literal path)
        if "://" in root or os.environ.get("MAGGY_TPU_ENV") == "gcs":
            from maggy_tpu.core.env.gcs import GcsEnv

            _instance = GcsEnv(root or None)
        else:
            _instance = BaseEnv(root or None)
    return _instance


def set_instance(env: Optional[BaseEnv]) -> None:
    """Override the ambient environment (used by tests and embedding apps)."""
    global _instance
    _instance = env


class EnvSing:
    """Reference-shaped accessor (singleton.py:20-62)."""

    @staticmethod
    def get_instance() -> BaseEnv:
        return get_instance()
