"""Environment abstraction — local filesystem implementation.

Parity with the reference's L0 environment layer (core/environment/base.py:25-222):
file I/O behind a narrow interface, experiment-directory layout, and worker-count
discovery. The reference's Hopsworks/Databricks variants become a GCS variant here
(core/env/gcs.py) selected by path scheme or env var, keeping every upper layer
storage-agnostic.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, List, Optional


class BaseEnv:
    """Local-filesystem environment."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get(
            "MAGGY_TPU_LOG_ROOT", os.path.join(os.getcwd(), "experiment_log")
        )

    # ------------------------------------------------------------------ fs ops

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str, recursive: bool = False) -> None:
        if not os.path.exists(path):
            return
        if os.path.isdir(path):
            if recursive:
                shutil.rmtree(path)
            else:
                os.rmdir(path)
        else:
            os.remove(path)

    def open_file(self, path: str, mode: str = "r"):
        if "w" in mode or "a" in mode:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        return open(path, mode)

    def dump(self, data: Any, path: str) -> None:
        """Write text or JSON-serializable data to a file."""
        with self.open_file(path, "w") as f:
            if isinstance(data, str):
                f.write(data)
            else:
                json.dump(data, f, sort_keys=True, default=str)

    def load_json(self, path: str) -> Any:
        with self.open_file(path, "r") as f:
            return json.load(f)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    # ---------------------------------------------------------- experiment dirs

    def experiment_dir(self, app_id: str, run_id: int) -> str:
        d = os.path.join(self.root, app_id, str(run_id))
        self.mkdir(d)
        return d

    def trial_dir(self, app_id: str, run_id: int, trial_id: str) -> str:
        d = os.path.join(self.experiment_dir(app_id, run_id), trial_id)
        self.mkdir(d)
        return d

    # ---------------------------------------------------------- cluster info

    def num_devices(self) -> int:
        """Addressable accelerator devices on this host."""
        try:
            import jax

            return jax.local_device_count()
        except Exception:
            return 1

    def process_index(self) -> int:
        try:
            import jax

            return jax.process_index()
        except Exception:
            return 0

    def num_processes(self) -> int:
        try:
            import jax

            return jax.process_count()
        except Exception:
            return 1
