"""Environment abstraction — local filesystem implementation.

Parity with the reference's L0 environment layer (core/environment/base.py:25-222):
file I/O behind a narrow interface, experiment-directory layout, and worker-count
discovery. The reference's Hopsworks/Databricks variants become a GCS variant here
(core/env/gcs.py) selected by path scheme or env var, keeping every upper layer
storage-agnostic.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, List, Optional


class BaseEnv:
    """Local-filesystem environment."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get(
            "MAGGY_TPU_LOG_ROOT", os.path.join(os.getcwd(), "experiment_log")
        )

    # ------------------------------------------------------------------ fs ops

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str, recursive: bool = False) -> None:
        if not os.path.exists(path):
            return
        if os.path.isdir(path):
            if recursive:
                shutil.rmtree(path)
            else:
                os.rmdir(path)
        else:
            os.remove(path)

    def open_file(self, path: str, mode: str = "r"):
        if "w" in mode or "a" in mode:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        return open(path, mode)

    def dump(self, data: Any, path: str) -> None:
        """Write text or JSON-serializable data to a file."""
        with self.open_file(path, "w") as f:
            if isinstance(data, str):
                f.write(data)
            else:
                json.dump(data, f, sort_keys=True, default=str)

    def load_json(self, path: str) -> Any:
        with self.open_file(path, "r") as f:
            return json.load(f)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    # ---------------------------------------------------------- experiment dirs

    def experiment_dir(self, app_id: str, run_id: int) -> str:
        d = os.path.join(self.root, app_id, str(run_id))
        self.mkdir(d)
        return d

    def trial_dir(self, app_id: str, run_id: int, trial_id: str) -> str:
        d = os.path.join(self.experiment_dir(app_id, run_id), trial_id)
        self.mkdir(d)
        return d

    # ---------------------------------------------------------- driver registry

    def driver_registry_path(self, app_id: str) -> str:
        # posixpath: correct for local linux paths AND gs:// URLs, so GcsEnv
        # inherits the registry unchanged
        import posixpath

        return posixpath.join(self.root, ".drivers", f"{app_id}.json")

    def register_driver(
        self,
        app_id: str,
        run_id: int,
        host: str,
        port: int,
        secret: Optional[str] = None,
        scope: str = "pod",
    ) -> None:
        """Advertise a running driver so pod workers and monitors can find it
        by app id — the storage-seam analogue of the reference registering its
        driver with the Hopsworks REST endpoint (environment/hopsworks.py:
        136-190 posts {hostIp, port, appId, secret} to /maggy/drivers). The
        record lives in the experiment root (same trust domain as
        logs/checkpoints, like the reference's registry).

        ``scope``: "pod" records bootstrap remote workers (host must be
        cross-host reachable); "local" records advertise a loopback address
        for same-host monitor auto-attach ONLY — worker discovery ignores
        them (a loopback record would poison cross-host bootstrap)."""
        import time

        record = {
            "app_id": app_id,
            "run_id": run_id,
            "host": host,
            "port": port,
            "scope": scope,
            "ts": time.time(),
        }
        if secret is not None:
            record["secret"] = secret
        self._atomic_dump(record, self.driver_registry_path(app_id))

    def list_drivers(self) -> List[dict]:
        """All registry records, newest first (for monitor auto-attach)."""
        import posixpath

        out = []
        d = posixpath.join(self.root, ".drivers")
        try:
            names = self.listdir(d)
        except OSError:  # GcsEnv.listdir raises for missing paths
            return out
        for name in names:
            if not name.endswith(".json") or ".tmp." in name:
                continue
            try:
                out.append(self.load_json(posixpath.join(d, name)))
            except (OSError, ValueError):
                continue
        return sorted(out, key=lambda r: r.get("ts", 0), reverse=True)

    def _atomic_dump(self, data: Any, path: str) -> None:
        """Publish a JSON record atomically: a concurrently-polling worker must
        see either no record or a complete one, never truncated JSON. Local
        FS: temp file + rename. (GcsEnv inherits plain dump — a GCS object PUT
        is already atomic at the object level.)"""
        tmp = f"{path}.tmp.{os.getpid()}"
        self.dump(data, tmp)
        os.replace(tmp, path)

    def lookup_driver(self, app_id: str) -> Optional[dict]:
        path = self.driver_registry_path(app_id)
        try:
            if not self.exists(path):
                return None
            return self.load_json(path)
        except (OSError, ValueError):
            return None

    def unregister_driver(self, app_id: str) -> None:
        try:
            self.delete(self.driver_registry_path(app_id))
        except OSError:
            pass

    # ---------------------------------------------------------- cluster info

    def num_devices(self) -> int:
        """Addressable accelerator devices on this host."""
        try:
            import jax

            return jax.local_device_count()
        except Exception:
            return 1

    def process_index(self) -> int:
        try:
            import jax

            return jax.process_index()
        except Exception:
            return 0

    def num_processes(self) -> int:
        try:
            import jax

            return jax.process_count()
        except Exception:
            return 1
