"""Experiment driver base class.

Template-method orchestration with the same shape as the reference's Spark/Python
drivers (core/experiment_driver/spark_driver.py:39-287, python_driver.py:39-267):
``run_experiment`` = startup callback → init (RPC server + digestion thread) →
launch executors → await completion → final callback → stop.

Execution substrate: instead of Spark's ``foreachPartition`` long-running tasks
(spark_driver.py:136-145), executors are local worker threads, each leasing a
disjoint group of accelerator devices (trial ↔ sub-slice placement). Multi-host
pods reuse the same RPC protocol with workers connecting over the host network.
"""

from __future__ import annotations

import atexit
import logging
import os
import queue
import threading
import time
import traceback
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, List, Optional

from maggy_tpu.core import rpc
from maggy_tpu.core.env import EnvSing

logger = logging.getLogger(__name__)


def device_groups(devices_per_trial: int = 1) -> List[list]:
    """Partition this host's accelerators into disjoint trial leases.

    The TPU-native replacement for "1 Spark executor = 1 worker": a worker is a
    device group (sub-slice), so N trials train concurrently on one host without
    contending for chips.
    """
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return [[]]
    k = max(1, devices_per_trial)
    n_groups = max(1, len(devices) // k)
    return [devices[i * k : (i + 1) * k] for i in range(n_groups)]


class Driver(ABC):
    def __init__(self, config, app_id: str, run_id: int):
        self.config = config
        self.app_id = app_id
        self.run_id = run_id
        self.env = EnvSing.get_instance()
        self.exp_dir = config.log_dir or self.env.experiment_dir(app_id, run_id)
        self.num_executors: int = 1
        self.server: Optional[rpc.Server] = None
        self.result: Any = None
        self.executor_logs: List[str] = []
        self.exception: Optional[BaseException] = None
        self.lock = threading.RLock()
        self.abort = threading.Event()
        self.experiment_done = threading.Event()
        self._worker_threads: List[threading.Thread] = []
        self._digestion_thread: Optional[threading.Thread] = None
        self.job_start: Optional[float] = None
        self.duration: Optional[float] = None
        self._log_fd = None
        # telemetry: the driver's own recorder (server verb latencies land
        # here) plus the latest per-worker snapshot shipped on heartbeats —
        # folded into STATUS so monitors render a live throughput panel
        from maggy_tpu import telemetry as _telemetry

        self.telemetry = _telemetry.worker_telemetry(
            "driver", self.exp_dir, role="driver", env=self.env
        )
        self.worker_telemetry: Dict[str, Any] = {}
        self._traces_exported = False

    # ------------------------------------------------------------------ hooks

    @abstractmethod
    def _make_server(self) -> rpc.Server:
        ...

    @abstractmethod
    def _register_msg_callbacks(self) -> None:
        ...

    @abstractmethod
    def _executor_fn(self, train_fn: Callable, partition_id: int, devices: list) -> Callable:
        """Return the zero-arg callable that runs one worker's loop."""

    def _exp_startup_callback(self) -> None:
        ...

    def _exp_final_callback(self) -> None:
        ...

    def _handle_message(self, msg: Dict[str, Any]) -> None:
        """Digestion-thread message handling; override per driver."""

    def _on_tick(self) -> None:
        """Digestion-thread periodic hook (assignment retries, early-stop sweeps)."""

    # ------------------------------------------------------------------ template

    def run_experiment(self, train_fn: Callable) -> Any:
        self.job_start = time.time()
        self._open_log()
        self.log(
            f"Starting experiment {self.config.name} "
            f"({type(self).__name__}, {self.num_executors} executors)"
        )
        # experiment state metadata: RUNNING -> FINISHED/FAILED, KILLED on
        # interpreter death (reference atexit/except hooks,
        # experiment_pyspark.py:149-183)
        self._write_state("RUNNING")
        atexit.register(self._kill_hook)
        try:
            self._exp_startup_callback()
            self.init()
            self._launch_executors(train_fn)
            self._await_completion()
            with self.lock:
                exc = self.exception
            if exc is not None:
                raise exc
            self._exp_final_callback()
            self.duration = time.time() - self.job_start
            self._write_state("FINISHED")
            return self.result
        except BaseException:
            self._write_state("FAILED")
            raise
        finally:
            atexit.unregister(self._kill_hook)
            self.stop()

    def _write_state(self, state: str) -> None:
        self._state = state
        try:
            self.env.dump(
                {
                    "state": state,
                    "name": self.config.name,
                    "app_id": self.app_id,
                    "run_id": self.run_id,
                    "ts": time.time(),
                },
                os.path.join(self.exp_dir, "state.json"),
            )
        except OSError:
            pass

    def _kill_hook(self) -> None:
        if getattr(self, "_state", None) == "RUNNING":
            self._write_state("KILLED")

    def note_worker_telemetry(self, msg: Dict[str, Any]) -> None:
        """Record a heartbeat's telemetry snapshot (event-loop thread; a
        single GIL-atomic dict store, like ``_touch``)."""
        snap = msg.get("telemetry")
        if snap:
            self.worker_telemetry[str(msg.get("partition_id"))] = snap

    def init(self) -> None:
        self.server = self._make_server()
        self.server.telemetry = self.telemetry
        self._register_msg_callbacks()
        # structured snapshot for monitors — registered for every driver kind
        # (the LOG verb ships lines; STATUS ships state — reference notebooks
        # only had the former)
        self.server.register_callback(
            "STATUS", lambda m: {"type": "STATUS", **self._status()}
        )
        # a launcher (python -m maggy_tpu.run) pre-assigns the port so workers
        # can be started with MAGGY_TPU_DRIVER before the driver is up
        self.server.start(port=int(os.environ.get("MAGGY_TPU_BIND_PORT", "0")))
        self._advertise()
        self._digestion_thread = threading.Thread(
            target=self._digest_loop, name="maggy-digestion", daemon=True
        )
        self._digestion_thread.start()

    def _advertise(self) -> None:
        """Write the driver-registry record (reference drivers register with
        Hopsworks REST, hopsworks.py:136-190). Pod drivers advertise their
        reachable hostname for cross-host worker bootstrap; every other driver
        advertises loopback with scope="local", which worker discovery ignores
        and monitor auto-attach (python -m maggy_tpu.monitor --latest) uses."""
        self._registered_driver = False
        pod = bool(getattr(self, "pod_mode", False))
        if pod:
            import socket as socket_mod

            host, scope = socket_mod.gethostname(), "pod"
        else:
            host, scope = "127.0.0.1", "local"
        # The registry record lives in the experiment root, so anyone who can
        # read that storage can join the control plane with the embedded
        # secret. On shared buckets set MAGGY_TPU_REGISTRY_NO_SECRET=1 to
        # register address-only; workers/monitors then need MAGGY_TPU_SECRET
        # out-of-band (docs/distributed.md "Trust boundary").
        omit_secret = os.environ.get("MAGGY_TPU_REGISTRY_NO_SECRET", "") not in ("", "0")
        try:
            self.env.register_driver(
                self.app_id, self.run_id, host, self.server.port,
                secret=None if omit_secret else self.server.secret, scope=scope,
            )
            self._registered_driver = True
        # broad: the record is best-effort on every non-pod path, and cloud
        # storage raises non-OSError types (gcsfs HttpError, the RuntimeError
        # GcsEnv raises without gcsfs) that must not kill the experiment
        except Exception as e:  # noqa: BLE001
            # pod workers relying on discovery would otherwise time out much
            # later blaming a stale record — name the real failure now
            self.log(
                f"WARNING: could not write driver registry record "
                f"{self.env.driver_registry_path(self.app_id)}: {e}"
                + (
                    "; workers must use MAGGY_TPU_DRIVER/MAGGY_TPU_SECRET"
                    if pod
                    else ""
                )
            )

    def _local_partitions(self) -> List[int]:
        """Partitions this process hosts; pod-mode drivers narrow this."""
        return list(range(self.num_executors))

    def _launch_executors(self, train_fn: Callable) -> None:
        # kept for elastic respawn (_respawn_executor): a replacement worker
        # for a dead slot needs the same train_fn/devices wiring
        self._train_fn = train_fn
        self._local_pids = set(self._local_partitions())
        groups = self._device_groups()
        for pid in self._local_partitions():
            devices = groups[pid % len(groups)] if groups else []
            fn = self._executor_fn(train_fn, pid, devices)
            t = threading.Thread(
                target=self._worker_wrapper, args=(fn, pid),
                name=f"maggy-executor-{pid}", daemon=True,
            )
            self._worker_threads.append(t)
            t.start()

    def _respawn_executor(self, partition_id: int) -> None:
        """Relaunch one local executor slot after an absorbed worker death
        (digestion thread; see ``_on_worker_death``). The replacement builds
        a fresh RPC client — its new attempt nonce makes the re-REG read as
        a worker restart, which is exactly what it is."""
        groups = self._device_groups()
        devices = groups[partition_id % len(groups)] if groups else []
        fn = self._executor_fn(self._train_fn, partition_id, devices)
        t = threading.Thread(
            target=self._worker_wrapper, args=(fn, partition_id),
            name=f"maggy-executor-{partition_id}-respawn", daemon=True,
        )
        self._worker_threads.append(t)
        t.start()
        self.log(f"Executor {partition_id} respawned")

    def _device_groups(self) -> List[list]:
        return device_groups(getattr(self.config, "devices_per_trial", 1))

    def _worker_wrapper(self, fn: Callable, partition_id: int) -> None:
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - unabsorbed death aborts the experiment
            if self._on_worker_death(partition_id, e):
                self.log(
                    f"Executor {partition_id} died ({type(e).__name__}: {e}); "
                    "absorbed by the resilience policy"
                )
                return
            with self.lock:
                if self.exception is None:
                    self.exception = e
            self.log(
                f"Executor {partition_id} died: {e}\n{traceback.format_exc()}"
            )
            self.abort.set()
            self.experiment_done.set()

    def _on_worker_death(self, partition_id: int, exc: BaseException) -> bool:
        """Hook for resilient drivers: return True when the death was
        absorbed (trial requeued / elastic restart queued) so the experiment
        continues; False (default) aborts it. Runs on the dying worker's
        thread — implementations must only enqueue work for the digestion
        thread, never touch controller state directly."""
        return False

    def _await_completion(self) -> None:
        for t in self._worker_threads:
            while t.is_alive():
                t.join(timeout=0.5)
                if self.abort.is_set():
                    # give workers a grace period to see GSTOP, then move on
                    t.join(timeout=5)
                    break

    def _digest_loop(self) -> None:
        while not self.experiment_done.is_set() or not self.server.message_queue.empty():
            try:
                msg = self.server.message_queue.get(timeout=0.1)
            except queue.Empty:
                msg = None
            try:
                if msg is not None:
                    self._handle_message(msg)
                self._on_tick()
            except BaseException as e:  # noqa: BLE001 - surfaced at finalization
                with self.lock:
                    if self.exception is None:
                        self.exception = e
                self.log(f"Driver digestion error: {e}\n{traceback.format_exc()}")
                self.abort.set()
                self.experiment_done.set()
                return

    def _export_telemetry(self) -> None:
        """Flush the driver recorder and assemble the merged Chrome trace +
        TensorBoard mirror from every worker's JSONL (local workers flushed
        theirs before FINAL; pod workers wrote to the shared root). Once per
        experiment, best-effort — observability must never fail a run."""
        if self._traces_exported:
            return
        self._traces_exported = True
        from maggy_tpu import telemetry as _telemetry

        if not _telemetry.enabled():
            return
        try:
            self.telemetry.close()
            from maggy_tpu.telemetry.export import (
                export_chrome_trace,
                mirror_to_tensorboard,
            )

            path = export_chrome_trace(self.env, self.exp_dir)
            if path:
                mirror_to_tensorboard(self.env, self.exp_dir)
                self.log(f"telemetry: merged Chrome trace at {path}")
        except Exception as e:  # noqa: BLE001 - exporters are best-effort
            logger.warning("telemetry export failed: %s", e)

    def stop(self) -> None:
        self.experiment_done.set()
        self._export_telemetry()
        if getattr(self, "_registered_driver", False):
            self.env.unregister_driver(self.app_id)
            self._registered_driver = False
        if self._digestion_thread is not None and self._digestion_thread.is_alive():
            self._digestion_thread.join(timeout=5)
        if self.server is not None:
            self.server.stop()
            self.server = None
        if self._log_fd is not None:
            self._log_fd.close()
            self._log_fd = None
        if getattr(self, "_remote_log", False) and self._log_history:
            import posixpath

            try:
                self.env.dump(
                    "\n".join(self._log_history) + "\n",
                    posixpath.join(self.exp_dir, "maggy.log"),
                )
            except Exception:  # noqa: BLE001 - logs are best-effort
                pass
            self._log_history = []

    # ------------------------------------------------------------------ logging

    def _open_log(self) -> None:
        # remote roots: object stores can't append — buffer and publish once
        # at close() via the env seam (mirrors Reporter's executor logs)
        self._remote_log = "://" in str(self.exp_dir)
        self._log_history: List[str] = []
        if self._remote_log:
            self._log_fd = None
            return
        try:
            self._log_fd = open(os.path.join(self.exp_dir, "maggy.log"), "a", buffering=1)
        except OSError:
            self._log_fd = None

    def log(self, message: str) -> None:
        line = f"[{time.strftime('%H:%M:%S')}] {message}"
        with self.lock:
            self.executor_logs.append(line)
            if self._log_fd is not None:
                self._log_fd.write(line + "\n")
            elif getattr(self, "_remote_log", False):
                self._log_history.append(line)
        logger.info(message)

    def add_executor_logs(self, logs: List[str]) -> None:
        with self.lock:
            self.executor_logs.extend(logs)

    def drain_logs(self) -> List[str]:
        with self.lock:
            out, self.executor_logs = self.executor_logs, []
            return out

    def progress(self) -> str:
        return ""

    def _status(self) -> Dict[str, Any]:
        """Structured snapshot for the STATUS verb; drivers extend it."""
        out = {
            "kind": type(self).__name__,
            "state": getattr(self, "_state", "UNKNOWN"),
            "name": self.config.name,
            "app_id": self.app_id,
            "run_id": self.run_id,
            "num_executors": self.num_executors,
            "elapsed_s": time.time() - self.job_start if self.job_start else None,
        }
        snaps = dict(self.worker_telemetry)  # event-loop-thread read; snapshot
        if self.telemetry.active:
            # the driver's own recorder rides along: resilience counters
            # (requeues, quarantines, restarts) live here, not on any worker
            drv = self.telemetry.snapshot()
            if drv.get("counters") or drv.get("gauges"):
                snaps = {**snaps, "driver": drv}
        if snaps:
            out["telemetry"] = snaps
        return out
