"""Hyperparameter-optimization driver — the HPO orchestrator.

Capability parity with the reference ``HyperparameterOptDriver``
(core/experiment_driver/optimization_driver.py:40-692): optimizer/early-stop
wiring, executor cap at min(executors, trials), message callbacks for
REG (lost-trial detection on re-registration), METRIC (early-stop sweep),
FINAL (finalize → persist → next suggestion → assign or idle or done), periodic
idle-assignment retries, and best/worst/avg result aggregation persisted to
``result.json``.

``BaseDriver`` (reference base_driver.py:35-258) reuses the same machinery with a
SingleRun optimizer and one executor, returning the train_fn's outputs directly.
"""

from __future__ import annotations

import os
import statistics
import time
from typing import Any, Callable, Dict, List

from maggy_tpu import constants, util
from maggy_tpu.config.base import BaseConfig
from maggy_tpu.core import rpc
from maggy_tpu.core.driver.base import Driver, device_groups
from maggy_tpu.core.executors.trial import trial_executor_fn
from maggy_tpu.optimizer import IDLE, get_earlystop, get_optimizer
from maggy_tpu.optimizer.gridsearch import GridSearch
from maggy_tpu.resilience import QuarantineTracker, RetryPolicy
from maggy_tpu.trial import Trial


class HyperparameterOptDriver(Driver):
    def __init__(self, config, app_id: str, run_id: int):
        super().__init__(config, app_id, run_id)
        self.searchspace = config.searchspace
        self.direction = config.direction
        self.optimization_key = config.optimization_key

        self.trial_store: Dict[str, Trial] = {}
        self.final_store: List[Trial] = []
        # STATUS monitors tail recent controller decisions from memory
        from collections import deque

        self._controller_tail = deque(maxlen=40)

        # pruner (optional) — wired before the optimizer so it can override
        # num_trials (reference optimization_driver.py:88-89)
        self.pruner = self._make_pruner(config)
        num_trials = config.num_trials
        if self.pruner is not None:
            num_trials = self.pruner.num_trials()
        if isinstance(config.optimizer, str) and config.optimizer.lower() in (
            "gridsearch",
            "grid",
        ):
            num_trials = GridSearch.get_num_trials(config.searchspace)
        self.num_trials = num_trials

        # resume: preload a previous run's finalized trials so the controller
        # observes them and the driver never re-schedules them (§5.4 upgrade
        # over the reference, which cannot resume experiments)
        if getattr(config, "resume_from", None):
            from maggy_tpu.train.checkpoint import load_finalized_trials

            for trial in load_finalized_trials(config.resume_from):
                self.final_store.append(trial)

        self.controller = get_optimizer(config.optimizer, seed=config.seed)
        self.controller.setup(
            config.searchspace,
            self.num_trials,
            self.trial_store,
            self.final_store,
            direction=config.direction,
            pruner=self.pruner,
        )
        self.earlystop = get_earlystop(config.es_policy)
        self._es_last_check = time.time()
        self._optimizer_exhausted = False
        self._maybe_idle: set = set()

        # resilience (docs/resilience.md): trials lost to TRANSIENT failures
        # (worker death / RPC loss) are requeued with a per-trial retry budget
        # and jittered exponential backoff instead of terminal ERROR; a worker
        # whose consecutive trials keep dying is quarantined out of
        # _try_assign for a cooldown. All state below is digestion-thread
        # owned (reads under self.lock where the STATUS path also looks).
        self.retry_policy = RetryPolicy.from_config(config)
        self.quarantine = QuarantineTracker(
            threshold=getattr(config, "quarantine_after", 3),
            cooldown=getattr(config, "quarantine_cooldown", 300.0),
        )
        self._retry_queue: List[tuple] = []  # (ready_ts, Trial), unordered
        self._stashed_suggestion = None  # probe result awaiting a worker

        # pod mode (reference parity: Spark runs trial executors on cluster
        # hosts, spark_driver.py:136-145): remote hosts running the same
        # script with MAGGY_TPU_ROLE=worker connect as trial executors; the
        # driver hosts partition 0 itself. Capacity is elastic — a silent
        # worker's trial is freed after worker_timeout and the experiment
        # continues on the remaining workers; a respawned worker re-registers
        # (new attempt nonce) and serves again.
        from maggy_tpu.core.pod import driver_address

        self.pod_mode = bool(driver_address(config))
        self._last_seen: Dict[int, float] = {}
        self._gstop_sent: set = set()  # pids whose GET saw the experiment end

        groups = device_groups(config.devices_per_trial)
        default_cap = 1 if self.pod_mode else len(groups)
        self.num_executors = max(
            1, min(config.num_executors or default_cap, self.num_trials)
        )

    def _exp_startup_callback(self) -> None:
        # HParams plugin experiment config (reference tensorboard.py:47-102):
        # written once per experiment so the TB dashboard gets typed columns
        from maggy_tpu import tensorboard as tb

        if len(self.config.searchspace):
            tb.write_hparams_config(self.exp_dir, self.config.searchspace)

    def _make_pruner(self, config):
        if config.pruner is None:
            return None
        if isinstance(config.pruner, str):
            if config.pruner.lower() == "hyperband":
                try:
                    from maggy_tpu.pruner.hyperband import Hyperband
                except ImportError as e:
                    raise NotImplementedError(
                        f"The hyperband pruner requires the pruner module: {e}"
                    ) from e
                pruner_config = dict(config.pruner_config)
                pruner_config.setdefault("direction", config.direction)
                return Hyperband(
                    trial_metric_getter=self._trial_metric_getter, **pruner_config
                )
            raise ValueError(f"Unknown pruner {config.pruner!r}")
        return config.pruner

    def _trial_metric_getter(self, trial_ids):
        """Lookup final metrics by trial id for the pruner (reference pruner
        callbacks)."""
        if isinstance(trial_ids, str):
            trial_ids = [trial_ids]
        out = {}
        with self.lock:
            for t in self.final_store:
                if t.trial_id in trial_ids:
                    out[t.trial_id] = t.final_metric
        return out

    # ------------------------------------------------------------------ server

    def _make_server(self) -> rpc.Server:
        # pod launchers distribute one secret to every process via env; local
        # runs mint a fresh one (Server does)
        return rpc.Server(
            self.num_executors, secret=os.environ.get("MAGGY_TPU_SECRET") or None
        )

    def _register_msg_callbacks(self) -> None:
        s = self.server
        s.register_callback("REG", self._reg_callback)
        s.register_callback("QUERY", lambda m: {"type": "QUERY", "ready": s.reservations.done()})
        s.register_callback("GET", self._get_callback)
        s.register_callback("METRIC", self._metric_callback)
        s.register_callback("FINAL", self._final_callback)
        s.register_callback("LOG", self._log_callback)
        # pod trial workers bootstrap their app/run ids from the driver
        # (core/pod.py run_trial_worker), same exchange the distributed
        # driver serves
        s.register_callback(
            "EXEC_CONFIG",
            lambda m: {
                "type": "EXEC_CONFIG",
                "app_id": self.app_id,
                "run_id": self.run_id,
            },
        )

    # --- event-loop handlers: fast, lock briefly, enqueue heavy work ----------

    def _touch(self, msg) -> None:
        # GIL-atomic dict store; read by the digestion thread's liveness sweep
        self._last_seen[msg["partition_id"]] = time.time()

    def _reg_callback(self, msg) -> Dict[str, Any]:
        self._touch(msg)
        reregistered = self.server.reservations.register(
            msg["partition_id"], msg.get("meta", {})
        )
        self.server.enqueue({**msg, "reregistered": reregistered})
        return {"type": "OK"}

    def _get_callback(self, msg) -> Dict[str, Any]:
        self._touch(msg)
        pid = msg["partition_id"]
        assignment = self.server.reservations.get_assignment(pid)
        if assignment is not None:
            with self.lock:
                trial = self.trial_store.get(assignment)
            if trial is not None:
                return {"type": "TRIAL", "trial_id": trial.trial_id, "params": trial.params}
        if self.experiment_done.is_set() or self.abort.is_set():
            self._gstop_sent.add(pid)
            return {"type": "GSTOP"}
        return {"type": "IDLE"}

    def _metric_callback(self, msg) -> Dict[str, Any]:
        self._touch(msg)
        self.note_worker_telemetry(msg)
        self.server.enqueue(msg)
        if self.abort.is_set():
            # interrupt every broadcasting train_fn so aborted experiments do not
            # leave workers training on leased devices
            return {"type": "STOP"}
        trial_id = msg.get("trial_id")
        if trial_id:
            with self.lock:
                trial = self.trial_store.get(trial_id)
            if trial is not None and trial.get_early_stop():
                return {"type": "STOP"}
        return {"type": "OK"}

    def _final_callback(self, msg) -> Dict[str, Any]:
        self._touch(msg)
        # unassign synchronously (event loop), before the reply: the worker's
        # next GET must never see its finished trial still assigned, or it
        # would run it twice (reference clears in the socket thread too,
        # rpc.py:463-471)
        self.server.reservations.assign_trial(msg["partition_id"], None)
        self.server.enqueue(msg)
        return {"type": "OK"}

    def _log_callback(self, msg) -> Dict[str, Any]:
        return {"type": "LOG", "logs": self.drain_logs(), "progress": self.progress()}

    # ------------------------------------------------ digestion-thread handlers

    def _handle_message(self, msg: Dict[str, Any]) -> None:
        verb = msg.get("type")
        if verb == "REG":
            self._digest_reg(msg)
        elif verb == "METRIC":
            self._digest_metric(msg)
        elif verb == "FINAL":
            self._digest_final(msg)
        elif verb == "_WORKER_LOST":
            self._digest_worker_lost(msg)

    def _on_worker_death(self, partition_id: int, exc: BaseException) -> bool:
        """A local executor thread died. TRANSIENT failures (worker kill /
        RPC loss) are absorbed: the in-flight trial is requeued and the
        worker slot respawned on the digestion thread. Deterministic
        failures keep the fail-fast abort."""
        from maggy_tpu.resilience import TRANSIENT, classify_failure

        if self.experiment_done.is_set() or classify_failure(exc) != TRANSIENT:
            return False
        self.telemetry.count("resilience.worker_deaths")
        self.server.enqueue(
            {
                "type": "_WORKER_LOST",
                "partition_id": partition_id,
                "error": f"{type(exc).__name__}: {exc}",
            }
        )
        return True

    def _digest_worker_lost(self, msg) -> None:
        pid = msg["partition_id"]
        self.log(f"Executor {pid} died ({msg['error']}); recovering")
        self._lose_assignment(pid, f"executor {pid} died: {msg['error']}")
        self._last_seen.pop(pid, None)
        self._maybe_idle.discard(pid)
        # respawn lost LOCAL capacity (remote pod workers come back through
        # their own supervisor, `maggy_tpu.run --respawn`) — unless the slot
        # is quarantined, in which case it stays down for the cooldown
        if pid in getattr(self, "_local_pids", ()) and not self.quarantine.is_quarantined(pid):
            self._respawn_executor(pid)
        self._maybe_finish()

    def _digest_reg(self, msg) -> None:
        pid = msg["partition_id"]
        if msg.get("reregistered"):
            # worker restarted: its in-flight trial is lost
            # (reference rpc.py:415-437 -> optimization_driver.py:473-483)
            self._lose_assignment(pid, f"executor {pid} re-registered")
        self._try_assign(pid)

    def _lose_assignment(self, pid: int, reason: str) -> None:
        """Free ``pid``'s in-flight trial after a TRANSIENT loss (worker
        death / re-registration / RPC silence — the only paths that reach
        here; a train_fn exception arrives as a FINAL error instead and
        fails fast). The trial is requeued with backoff while its retry
        budget lasts; only an exhausted budget marks ERROR. Digestion thread
        only (controller-adjacent state)."""
        assignment = self.server.reservations.get_assignment(pid)
        if assignment is None:
            return
        with self.lock:
            lost = self.trial_store.pop(assignment, None)
        self.server.reservations.assign_trial(pid, None)
        if lost is None:
            return
        if self.quarantine.record_failure(pid):
            self.telemetry.count("resilience.workers_quarantined")
            self.log(
                f"Executor {pid} quarantined: {self.quarantine.threshold} "
                f"consecutive trials died on it (cooldown "
                f"{self.quarantine.cooldown:.0f}s)"
            )
        retries = int(lost.info_dict.get("retries", 0))
        if retries < self.retry_policy.max_retries:
            delay = self.retry_policy.delay(retries)
            lost.reset_for_retry()
            lost.info_dict["retries"] = retries + 1
            with self.lock:
                self._retry_queue.append((time.time() + delay, lost))
            self.telemetry.count("resilience.trials_requeued")
            self.log(
                f"Trial {assignment} lost ({reason}); requeued — retry "
                f"{retries + 1}/{self.retry_policy.max_retries} in {delay:.1f}s"
            )
        else:
            lost.error()
            with self.lock:
                self.final_store.append(lost)
            self._persist_trial(lost)
            self.telemetry.count("resilience.trials_exhausted")
            self.log(
                f"Trial {assignment} lost ({reason}); retry budget "
                f"({self.retry_policy.max_retries}) exhausted — marked ERROR"
            )
            self._maybe_finish()

    def _liveness_sweep(self) -> None:
        """Pod mode: a registered worker silent past worker_timeout is
        presumed dead — free its trial so the budget completes on the
        remaining capacity (the reference gets this from Spark re-running the
        executor task, spark_driver.py:136-145; nothing here aborts, so a
        respawned worker — ``maggy_tpu.run --respawn`` — re-registers and
        serves again)."""
        timeout = getattr(self.config, "worker_timeout", 600.0)
        now = time.time()
        for pid, ts in list(self._last_seen.items()):
            if now - ts <= timeout:
                continue
            # drop so the sweep fires once per death; a re-REG re-adds it
            self._last_seen.pop(pid, None)
            self._maybe_idle.discard(pid)
            self.log(
                f"Executor {pid} silent for {now - ts:.0f}s (> worker_timeout "
                f"{timeout:.0f}s); freeing its trial and continuing on the "
                "remaining workers"
            )
            self._lose_assignment(pid, f"executor {pid} presumed dead")
        # a dead worker must never strand completion — even before budget
        # exhaustion (_maybe_finish probes the controller directly instead of
        # waiting for a worker GET that may never come)
        self._maybe_finish()

    def _digest_metric(self, msg) -> None:
        trial_id, metric, step = msg.get("trial_id"), msg.get("metric"), msg.get("step")
        logs = msg.get("logs") or []
        if logs:
            self.add_executor_logs(logs)
        if trial_id and metric is not None:
            with self.lock:
                trial = self.trial_store.get(trial_id)
            if trial is not None:
                if trial.status != Trial.RUNNING:
                    trial.begin()
                trial.append_metric(metric, step if step is not None and step >= 0 else None)
        self._earlystop_sweep()

    def _earlystop_sweep(self) -> None:
        """Reference optimization_driver.py:433-471: run the early-stop policy
        every es_interval seconds once es_min trials have finalized."""
        cfg = self.config
        if time.time() - self._es_last_check < cfg.es_interval:
            return
        self._es_last_check = time.time()
        with self.lock:
            if len(self.final_store) < cfg.es_min:
                return
            to_check = {
                tid: t for tid, t in self.trial_store.items() if t.metric_history
            }
            final = list(self.final_store)
        for tid in self.earlystop.earlystop_check(to_check, final, self.direction):
            with self.lock:
                trial = self.trial_store.get(tid)
            if trial is not None and not trial.get_early_stop():
                trial.set_early_stop()
                self.log(f"Early stopping trial {tid}")

    def _digest_final(self, msg) -> None:
        pid = msg["partition_id"]
        trial_id = msg["trial_id"]
        with self.lock:
            trial = self.trial_store.pop(trial_id, None)
        if trial is None:
            # duplicate FINAL, or a live worker the liveness sweep falsely
            # presumed dead (its trial was already freed): the worker is
            # healthy and unassigned — reschedule it, or it idles forever
            self._try_assign(pid)
            return
        if msg.get("error"):
            trial.error()
            self.log(f"Trial {trial_id} errored: {msg['error']}")
            with self.lock:
                had_success = any(t.status == Trial.FINALIZED for t in self.final_store)
            if not had_success:
                # fail fast when nothing has ever succeeded — a broken train_fn
                # should not burn the whole trial budget
                raise RuntimeError(
                    f"First trial(s) failed with: {msg['error']} — aborting experiment."
                )
        else:
            trial.finalize(msg.get("metric"))
            trial.info_dict["outputs"] = msg.get("outputs") or {}
            if msg.get("early_stopped"):
                trial.info_dict["early_stopped"] = True
        with self.lock:
            self.final_store.append(trial)
        self._persist_trial(trial)
        # any completed trial (even an errored one — the WORKER survived to
        # report it) clears the worker's death streak
        self.quarantine.record_success(pid)
        # reservation already cleared synchronously by _final_callback
        self.log(
            f"Trial {trial_id} {trial.status} metric={trial.final_metric} "
            f"({len(self.final_store)} done)"
        )
        self._try_assign(pid)

    def _on_tick(self) -> None:
        if self.pod_mode:
            self._liveness_sweep()
        # retry partitions that previously got IDLE (reference
        # optimization_driver.py:542-568 debounced retries) — these also pick
        # up requeued trials whose backoff has elapsed
        for pid in list(self._maybe_idle):
            self._try_assign(pid)
        self._maybe_finish()

    def _try_assign(self, pid: int) -> None:
        # THREADING INVARIANT (round-1 verdict weak #6): the controller
        # (optimizer/pruner) is single-threaded state — every
        # controller.get_suggestion call happens HERE, and _try_assign runs
        # only on the digestion thread (_handle_message/_on_tick). Event-loop
        # callbacks may read trial_store under self.lock but must never call
        # into the controller; keep it that way when adding verbs.
        if self.experiment_done.is_set():
            return
        if self.server.reservations.get_assignment(pid) is not None:
            return
        if self.quarantine.is_quarantined(pid):
            # no work for a quarantined worker; keep it on the tick radar so
            # it gets reconsidered once the cooldown releases it
            self._maybe_idle.add(pid)
            return
        # requeued trials outrank fresh suggestions: their budget is already
        # spent and the controller has observed nothing for them yet
        now = time.time()
        retry = None
        with self.lock:
            for i, (ready_ts, trial) in enumerate(self._retry_queue):
                if ready_ts <= now:
                    retry = self._retry_queue.pop(i)[1]
                    break
        if retry is not None:
            self._assign(pid, retry, note="retry")
            return
        with self.lock:
            finished = self.final_store[-1] if self.final_store else None
            done_ids = {t.trial_id for t in self.final_store}
            stash, self._stashed_suggestion = self._stashed_suggestion, None
        if stash is not None and stash.trial_id not in done_ids:
            self._assign(pid, stash)
            return
        suggestion = self.controller.get_suggestion(finished)
        # resumed experiments: skip suggestions that already finalized in the
        # previous run (bounded — each skip consumes the controller's budget)
        skips = 0
        while isinstance(suggestion, Trial) and suggestion.trial_id in done_ids:
            skips += 1
            if skips > self.num_trials + 1:
                suggestion = None
                break
            suggestion = self.controller.get_suggestion(None)
        if isinstance(suggestion, Trial):
            self._assign(pid, suggestion)
        elif suggestion == IDLE:
            self._maybe_idle.add(pid)
        else:  # None: optimizer exhausted
            self._optimizer_exhausted = True
            with self.lock:
                pending = len(self._retry_queue)
            if pending:
                # a requeued trial still needs this worker once its backoff
                # elapses — keep it on the tick radar
                self._maybe_idle.add(pid)
            else:
                self._maybe_idle.discard(pid)
            self._maybe_finish()

    def _assign(self, pid: int, trial: Trial, note: str = "") -> None:
        """Hand ``trial`` to executor ``pid`` (digestion thread only)."""
        trial.schedule(pid)
        with self.lock:
            self.trial_store[trial.trial_id] = trial
        self.server.reservations.assign_trial(pid, trial.trial_id)
        self._maybe_idle.discard(pid)
        kind = note or trial.info_dict.get("sample_type", "?")
        self._controller_log(
            f"{kind} trial {trial.trial_id} -> executor {pid} "
            f"budget={trial.params.get('budget')}"
        )

    def _maybe_finish(self) -> None:
        """Complete the experiment when no more work can or will be
        scheduled. Fixes the stranded-completion edge: the last worker dying
        *before* budget exhaustion used to leave nobody to poll the
        controller, hanging ``_await_completion`` forever — with nothing in
        flight and nothing queued, probe the controller directly; a Trial it
        returns is stashed for the next ``_try_assign``. Digestion thread
        only (calls into the controller)."""
        if self.experiment_done.is_set():
            return
        with self.lock:
            in_flight = len(self.trial_store)
            pending = len(self._retry_queue)
            stash = self._stashed_suggestion
            finished = self.final_store[-1] if self.final_store else None
        if in_flight or pending or stash is not None:
            return
        if not self._optimizer_exhausted:
            suggestion = self.controller.get_suggestion(finished)
            if isinstance(suggestion, Trial):
                with self.lock:
                    self._stashed_suggestion = suggestion
                return
            if suggestion == IDLE:
                # nothing in flight yet the controller is waiting — transient
                # (e.g. a pruner mid-decision); probe again next tick
                return
            self._optimizer_exhausted = True
        self._finish_experiment()

    def _finish_experiment(self) -> None:
        self._update_result()
        self.experiment_done.set()

    # ------------------------------------------------------------------ results

    def _ranked_done(self) -> List[Trial]:
        """Finalized metric-bearing trials, best first (single source of the
        ranking for both result.json and the live STATUS dashboard).
        Call under self.lock."""
        done = [t for t in self.final_store if t.final_metric is not None]
        return sorted(
            done, key=lambda t: t.final_metric, reverse=self.direction == "max"
        )

    def _update_result(self) -> None:
        with self.lock:
            ranked = self._ranked_done()
            errors = [t for t in self.final_store if t.status == Trial.ERROR]
            stopped = [t for t in self.final_store if t.info_dict.get("early_stopped")]
        if not ranked:
            self.result = {"num_trials": len(self.final_store), "best": None}
            return
        done = ranked
        best, worst = ranked[0], ranked[-1]
        self.result = {
            "best": {
                "trial_id": best.trial_id,
                "params": best.params,
                self.optimization_key: best.final_metric,
                "outputs": best.info_dict.get("outputs", {}),
            },
            "worst": {
                "trial_id": worst.trial_id,
                "params": worst.params,
                self.optimization_key: worst.final_metric,
            },
            "avg": statistics.mean(t.final_metric for t in done),
            "num_trials": len(self.final_store),
            "early_stopped": len(stopped),
            "errors": len(errors),
            "duration": time.time() - self.job_start if self.job_start else None,
        }

    def _persist_trial(self, trial: Trial) -> None:
        try:
            d = self.env.trial_dir(self.app_id, self.run_id, trial.trial_id)
            self.env.dump(trial.to_dict(), os.path.join(d, constants.TRIAL_FILE))
        except OSError as e:
            self.log(f"Could not persist trial {trial.trial_id}: {e}")

    def _exp_final_callback(self) -> None:
        self._update_result()
        try:
            self.env.dump(
                util._jsonify(self.result),
                os.path.join(self.exp_dir, constants.RESULT_FILE),
            )
            self.env.dump(
                {
                    "name": self.config.name,
                    "app_id": self.app_id,
                    "run_id": self.run_id,
                    "num_trials": self.num_trials,
                    "direction": self.direction,
                    "optimizer": self.controller.name(),
                    "duration": time.time() - self.job_start if self.job_start else None,
                },
                os.path.join(self.exp_dir, constants.EXPERIMENT_FILE),
            )
        except OSError as e:
            self.log(f"Could not persist experiment result: {e}")
        self.controller.finalize_experiment(self.final_store)

    def progress(self) -> str:
        with self.lock:
            return util.progress_bar(len(self.final_store), self.num_trials)

    def _controller_log(self, message: str) -> None:
        """Controller decision log (reference optimizer.log/pruner.log,
        abstractoptimizer.py:84-134 + abstractpruner.py:72-85). Also kept in a
        ring buffer so STATUS monitors can tail it without file access."""
        line = f"[{time.strftime('%H:%M:%S')}] {message}"
        with self.lock:
            self._controller_tail.append(line)
        try:
            with self.env.open_file(
                os.path.join(self.exp_dir, "optimizer.log"), "a"
            ) as f:
                f.write(line + "\n")
        except OSError:
            pass

    def _status(self):
        base = super()._status()
        with self.lock:
            ranked = self._ranked_done()
            best = None
            if ranked:
                best = {
                    "trial_id": ranked[0].trial_id,
                    "metric": ranked[0].final_metric,
                    "params": ranked[0].params,
                }
            base.update(
                controller=self.controller.name(),
                direction=self.direction,
                trials_done=len(self.final_store),
                trials_total=self.num_trials,
                trials_running=len(self.trial_store),
                early_stopped=sum(
                    1 for t in self.final_store
                    if t.info_dict.get("early_stopped")
                ),
                errors=sum(
                    1 for t in self.final_store if t.status == Trial.ERROR
                ),
                best=best,
                controller_log=list(self._controller_tail),
                trials_requeued=len(self._retry_queue),
            )
            quarantined = self.quarantine.snapshot()
            if quarantined:
                base.update(quarantined=quarantined)
            if self.pod_mode:
                # dict() snapshot: the digestion thread's liveness sweep pops
                # entries concurrently with this event-loop-thread iteration
                base.update(
                    last_seen={
                        str(pid): round(time.time() - ts, 1)
                        for pid, ts in dict(self._last_seen).items()
                    }
                )
        return base

    # ------------------------------------------------------------------ executor

    def _await_completion(self) -> None:
        super()._await_completion()
        if not self.pod_mode or self.abort.is_set():
            return
        # linger until every LIVE remote worker's next GET has seen GSTOP —
        # tearing the server down the instant the local executor returns
        # turns a cleanly finished study into an RpcError for any worker
        # sleeping between GETs (it would then exit nonzero and burn a
        # --respawn slot on a doomed replacement). Dead workers are excluded
        # by heartbeat freshness; the wait is bounded regardless.
        fresh = max(2.0, 4 * getattr(self.config, "hb_interval", 1.0))
        deadline = time.time() + 10.0
        while time.time() < deadline:
            now = time.time()
            waiting = [
                pid
                for pid, ts in dict(self._last_seen).items()
                if now - ts < fresh and pid not in self._gstop_sent
            ]
            if not waiting:
                return
            time.sleep(0.05)

    def _local_partitions(self) -> List[int]:
        if not self.pod_mode:
            return super()._local_partitions()
        import socket as socket_mod

        self.log(
            f"Pod mode: HPO driver at {socket_mod.gethostname()}:"
            f"{self.server.port} (secret via MAGGY_TPU_SECRET), running local "
            f"trial executor 0; remote workers add capacity as they register"
        )
        return [0]

    def _device_groups(self) -> List[list]:
        if not self.pod_mode:
            return super()._device_groups()
        # the local executor spans this host's devices; remote workers lease
        # their own hosts' devices themselves
        try:
            import jax

            return [jax.local_devices()]
        except Exception:
            return [[]]

    def _executor_fn(self, train_fn: Callable, partition_id: int, devices: list) -> Callable:
        return trial_executor_fn(
            train_fn=train_fn,
            config=self.config,
            app_id=self.app_id,
            run_id=self.run_id,
            partition_id=partition_id,
            server_addr=(self.server.host, self.server.port),
            secret=self.server.secret,
            devices=devices,
        )


class BaseDriver(HyperparameterOptDriver):
    """Single-run experiment (reference base_driver.py:35-258): run the train_fn
    once under full experiment bookkeeping and return its outputs."""

    def __init__(self, config: BaseConfig, app_id: str, run_id: int):
        from maggy_tpu.config.hpo import HyperparameterOptConfig
        from maggy_tpu.searchspace import Searchspace

        hpo_config = HyperparameterOptConfig(
            num_trials=1,
            optimizer="none",
            searchspace=Searchspace(),
            optimization_key="metric",
            es_policy="none",
            es_min=2**31,
            name=config.name,
            description=config.description,
            hb_interval=config.hb_interval,
            model=config.model,
            dataset=config.dataset,
            num_executors=1,
            log_dir=config.log_dir,
        )
        hpo_config.hparams = config.hparams
        super().__init__(hpo_config, app_id, run_id)

    def _exp_final_callback(self) -> None:
        super()._exp_final_callback()
        best = (self.result or {}).get("best") or {}
        outputs = best.get("outputs") or {}
        # return the train_fn's own outputs, like the reference BaseDriver
        # (base_driver.py:221-242)
        self.result = outputs if outputs else self.result
