"""Distributed-training driver.

Capability parity with the reference's ``TorchDistributedTrainingDriver`` /
``TfDistributedTrainingDriver`` (core/experiment_driver/
torch_distributed_training_driver.py:28-146, tf_distributed_training_driver.py:
37-271): one registration barrier, an EXEC_CONFIG exchange that tells every
worker the cluster layout, per-worker final metrics averaged into the result.

Topology note: a "worker" here is one JAX *process* (one host of a pod), not
one device — SPMD over each host's chips happens inside pjit. Locally that
means exactly one worker spanning all visible devices.
"""

from __future__ import annotations

import os
import statistics
import time
from typing import Any, Callable, Dict, List

from maggy_tpu.core import rpc
from maggy_tpu.core.driver.base import Driver
from maggy_tpu.core.executors.distributed import dist_executor_fn


class DistributedTrainingDriver(Driver):
    def __init__(self, config, app_id: str, run_id: int):
        super().__init__(config, app_id, run_id)
        try:
            import jax

            default_workers = jax.process_count()
        except Exception:
            default_workers = 1
        self.num_executors = config.num_executors or default_workers
        # last worker becomes a dedicated evaluator (reference
        # tf_dist_executor.py:138-144); it shares the control plane but not
        # the training group
        self.evaluator_partition: Any = None
        if getattr(config, "evaluator", False):
            if self.num_executors < 2:
                raise ValueError(
                    "evaluator=True needs num_executors >= 2 (one training "
                    "worker plus the evaluator)"
                )
            if config.data_plane == "auto" and default_workers > 1:
                raise ValueError(
                    "evaluator=True requires data_plane='local': in a global "
                    "jax.distributed mesh every process is part of the "
                    "training collective and none can be carved out."
                )
            self.evaluator_partition = self.num_executors - 1
        self._finals: List[Dict[str, Any]] = []
        self._coordinator = None  # host:port of worker 0, filled at registration
        self._last_seen: Dict[int, float] = {}  # partition -> last contact ts
        self._final_pids: set = set()
        # elastic restart (docs/resilience.md): a TRANSIENT worker death
        # consumes one restart slot and relaunches that partition — the
        # replacement re-runs registration + EXEC_CONFIG and its train_fn
        # resumes from the latest checkpoint via fit(resume="auto")
        self.max_restarts = int(getattr(config, "max_restarts", 0))
        self._restarts = 0
        # restart serialization: every processed restart is one membership
        # transition — _RESTART messages carry the epoch their death was
        # observed at, and a partition restarts at most once per epoch, so
        # the thread-death and liveness-sweep paths double-reporting one
        # loss can never double-respawn a partition or double-charge the
        # budget (the double-fault window fix)
        self._restart_epoch = 0
        self._restarted_at: Dict[int, int] = {}  # partition -> epoch of last restart
        # elastic membership (docs/resilience.md "Elastic membership"):
        # epoch-numbered views of the active slice set; on slice loss or
        # rejoin the mesh RESHAPES instead of relaunching at fixed width
        self.elastic = bool(getattr(config, "elastic", False))
        self.membership = None
        self._member_acks: Dict[int, int] = {}  # partition -> last acked epoch
        self._reshape_t0: float = 0.0  # perf_counter at the last epoch bump
        self._reshape_epoch_timed = -1  # epoch whose barrier was already gauged
        if self.elastic:
            from maggy_tpu.resilience.membership import MembershipView

            total = int(getattr(config, "num_slices", None) or self.num_executors)
            min_slices = int(getattr(config, "min_slices", 1))
            if min_slices > total:
                raise ValueError(
                    f"min_slices={min_slices} exceeds the launch width "
                    f"({total} slice(s))"
                )
            # one executor hosting several slices = simulated partitions of
            # the local device mesh; several executors = one slice each
            mode = "sim" if (self.num_executors == 1 and total > 1) else "workers"
            self.membership = MembershipView.full(total, min_slices, mode=mode)
            self.telemetry.gauge("resilience.membership_epoch", 0)
            self.telemetry.gauge("resilience.active_slices", total)
        # pod mode: remote hosts run their own copy of the script and connect
        # as workers (core/pod.py); this driver launches only partition 0
        from maggy_tpu.core.pod import driver_address

        self.pod_mode = bool(driver_address(config))

    # ------------------------------------------------------------------ server

    def _make_server(self) -> rpc.Server:
        # a launcher distributes one secret to every pod process via env
        return rpc.Server(
            self.num_executors, secret=os.environ.get("MAGGY_TPU_SECRET") or None
        )

    def _register_msg_callbacks(self) -> None:
        s = self.server
        s.register_callback("REG", self._reg_callback)
        s.register_callback(
            "QUERY", lambda m: {"type": "QUERY", "ready": s.reservations.done()}
        )
        s.register_callback("EXEC_CONFIG", self._exec_config_callback)
        # full cluster spec (reference TensorflowServer RESERVATIONS verb,
        # rpc.py:614-620)
        s.register_callback(
            "RESERVATIONS",
            lambda m: {
                "type": "RESERVATIONS",
                "cluster": s.reservations.cluster_spec(),
            },
        )
        s.register_callback("METRIC", self._metric_callback)
        s.register_callback("FINAL", self._final_callback)
        if self.elastic:
            # membership protocol (docs/resilience.md): SLICE_EVENT reports
            # a drop/rejoin for digestion; MEMBERSHIP is the reshape
            # barrier poll — it records the caller's acked epoch and
            # reports whether every active member has converged
            s.register_callback("SLICE_EVENT", self._slice_event_callback)
            s.register_callback("MEMBERSHIP", self._membership_callback)
        s.register_callback("GET", lambda m: {"type": "GSTOP"})
        s.register_callback(
            "LOG", lambda m: {"type": "LOG", "logs": self.drain_logs(), "progress": ""}
        )

    def _touch(self, pid: int) -> None:
        with self.lock:
            self._last_seen[pid] = time.time()

    def _reg_callback(self, msg) -> Dict[str, Any]:
        restarted = self.server.reservations.register(
            msg["partition_id"], msg.get("meta", {})
        )
        self._touch(msg["partition_id"])
        if (
            restarted
            and self.elastic
            and self.membership.mode == "workers"
            and msg["partition_id"] in self.membership.inactive
        ):
            # a dropped slice's worker came back (supervisor respawn):
            # re-admit it through the membership protocol — the rejoin
            # epoch reshapes every survivor back to the wider mesh
            self.server.enqueue(
                {
                    "type": "_SLICE_EVENT",
                    "kind": "rejoin",
                    "slice": msg["partition_id"],
                    "partition_id": msg["partition_id"],
                }
            )
        return {"type": "OK"}

    def _exec_config_callback(self, msg) -> Dict[str, Any]:
        # worker 0's host becomes the jax.distributed coordinator
        # (the reference's MASTER_ADDR selection, rpc.py:544-553); app/run ids
        # ride along so pod workers land their artifacts in the driver's
        # experiment directory
        spec = self.server.reservations.cluster_spec()
        coordinator = None
        # advertised only on pods — a plain local multi-worker run must not
        # look like a multi-host cluster to the executors
        if self.pod_mode and self.num_executors > 1 and spec:
            host = spec[0].get("host") or "127.0.0.1"
            # derive from the experiment's RPC port unless pinned on the
            # config: concurrent experiments on one host get distinct ports
            port = getattr(self.config, "coordinator_port", None) or (
                1024 + (self.server.port + 1000) % 64000
            )
            coordinator = f"{host}:{port}"
        num_processes = self.num_executors - (
            1 if self.evaluator_partition is not None else 0
        )
        out = {
            "type": "EXEC_CONFIG",
            # the evaluator is outside the training group (reference: the TF
            # evaluator is not in the TF_CONFIG worker list)
            "num_processes": num_processes,
            "coordinator": coordinator,
            "cluster": spec,
            "evaluator_partition": self.evaluator_partition,
            "app_id": self.app_id,
            "run_id": self.run_id,
        }
        if self.elastic:
            # membership rides the config exchange: a reshape re-runs
            # EXEC_CONFIG, so the layout a worker builds is always the one
            # the current epoch's view describes
            view = self.membership
            out["membership"] = view.as_dict()
            if view.mode == "workers":
                out["num_processes"] = view.n_active
        return out

    def _metric_callback(self, msg) -> Dict[str, Any]:
        self._touch(msg["partition_id"])
        self.note_worker_telemetry(msg)
        self.server.enqueue(msg)
        if self.abort.is_set():
            return {"type": "STOP"}
        if self.elastic and msg.get("epoch") is not None:
            view = self.membership  # atomic read; digestion swaps whole views
            if int(msg["epoch"]) < view.epoch:
                # this worker runs a stale layout: tell it to reshape — its
                # fit raises MembershipChanged at the next step boundary
                return {"type": "RESHAPE", "epoch": view.epoch}
        return {"type": "OK"}

    # ------------------------------------------------------- membership verbs

    def _slice_event_callback(self, msg) -> Dict[str, Any]:
        """A worker observed a slice drop/rejoin (chaos or real): enqueue
        for digestion — the epoch bump and all accounting happen there."""
        self.server.enqueue(
            {
                "type": "_SLICE_EVENT",
                "kind": msg.get("kind"),
                "slice": msg.get("slice"),
                "partition_id": msg.get("partition_id"),
                "step": msg.get("step"),
            }
        )
        return {"type": "OK"}

    def _membership_callback(self, msg) -> Dict[str, Any]:
        """Reshape-barrier poll: record the caller's acked epoch; ready once
        every member expected at the barrier has acked the current epoch.
        The barrier is what makes the reshape *checkpoint-consistent*: no
        member rebuilds its mesh until all of them have converged on the
        view (and therefore on the checkpoint the transition saved)."""
        import time as _time

        view = self.membership
        pid = msg.get("partition_id")
        acked = msg.get("epoch")
        with self.lock:
            if pid is not None and acked is not None:
                self._member_acks[int(pid)] = int(acked)
            members = self._barrier_members()
            ready = all(
                self._member_acks.get(p, -1) >= view.epoch for p in members
            )
            if ready and view.epoch > 0 and self._reshape_epoch_timed < view.epoch:
                self._reshape_epoch_timed = view.epoch
                self.telemetry.gauge(
                    "resilience.reshape_ms",
                    (_time.perf_counter() - self._reshape_t0) * 1e3,
                )
        return {
            "type": "MEMBERSHIP",
            "view": view.as_dict(),
            "ready": ready,
            "aborted": self.abort.is_set(),
        }

    def _barrier_members(self) -> List[int]:
        """Partitions whose ack the reshape barrier waits for (call under
        ``self.lock``): the single hosting executor in sim mode, the active
        slices' workers otherwise — minus workers that already FINALed
        (they will never poll again, and their result is already in)."""
        if self.membership.mode == "sim":
            return [p for p in (0,) if p not in self._final_pids]
        return [
            p
            for p in self.membership.active
            if p < self.num_executors and p not in self._final_pids
        ]

    def _final_callback(self, msg) -> Dict[str, Any]:
        with self.lock:
            self._final_pids.add(msg["partition_id"])
        self._touch(msg["partition_id"])
        self.server.enqueue(msg)
        return {"type": "OK"}

    # ------------------------------------------------------------------ digestion

    def _on_worker_death(self, partition_id: int, exc: BaseException) -> bool:
        """Local worker-thread death: absorb TRANSIENT failures while restart
        budget remains — or, under elastic membership, reshape the mesh
        around the lost slice (runs on the dying thread — only enqueues)."""
        from maggy_tpu.resilience import TRANSIENT, classify_failure

        if self.experiment_done.is_set() or classify_failure(exc) != TRANSIENT:
            return False
        if self.elastic and self.membership.mode == "workers":
            # slice == worker process: the death IS a membership drop —
            # digestion bumps the epoch, survivors reshape, and no restart
            # slot is charged. A min_slices violation aborts cleanly from
            # digestion (the death still reads as absorbed here: the
            # violation is the authoritative error, not the thread's).
            self.telemetry.count("resilience.worker_deaths")
            self.server.enqueue(
                {
                    "type": "_SLICE_EVENT",
                    "kind": "drop",
                    "slice": partition_id,
                    "partition_id": partition_id,
                    "error": f"{type(exc).__name__}: {exc}",
                }
            )
            return True
        with self.lock:
            if self._restarts >= self.max_restarts:
                return False
            self._restarts += 1
            nth = self._restarts
            # serialize behind the restart epoch: the relaunch for THIS
            # death is valid only while no other restart of the same
            # partition lands first (double-fault window fix)
            observed_epoch = self._restart_epoch
        self.telemetry.count("resilience.dist_restarts")
        self.server.enqueue(
            {
                "type": "_RESTART",
                "partition_id": partition_id,
                "error": f"{type(exc).__name__}: {exc}",
                "restart": nth,
                "epoch": observed_epoch,
            }
        )
        return True

    def _digest_restart(self, msg: Dict[str, Any]) -> None:
        pid = msg["partition_id"]
        with self.lock:
            # double-fault window: the thread-death and liveness-sweep paths
            # can both report one loss, and a relaunch may already be in
            # flight for this partition. A restart observed BEFORE the
            # partition's last processed restart epoch is that duplicate —
            # refund the slot it charged and keep the one relaunch instead
            # of spawning a second executor for the partition (which would
            # double-FINAL and corrupt completion accounting). A death
            # observed at or after it is the relaunched worker genuinely
            # dying again and restarts normally.
            if self._restarted_at.get(pid, -1) > msg.get("epoch", 0):
                self._restarts = max(0, self._restarts - 1)
                self.log(
                    f"Worker {pid} death report superseded by an in-flight "
                    f"restart (epoch {self._restarted_at[pid]}); restart slot "
                    "refunded"
                )
                return
            self._restart_epoch += 1
            self._restarted_at[pid] = self._restart_epoch
            # the partition's previous FINAL (if any) is void — its rerun
            # reports the authoritative one
            self._finals = [m for m in self._finals if m["partition_id"] != pid]
            self._final_pids.discard(pid)
            self._last_seen.pop(pid, None)
        self.log(
            f"Worker {pid} died ({msg['error']}); elastic restart "
            f"{msg['restart']}/{self.max_restarts}: re-running registration "
            f"+ EXEC_CONFIG for partition {pid} and relaunching its train_fn "
            "from the latest checkpoint"
        )
        self._respawn_executor(pid)

    def _digest_slice_event(self, msg: Dict[str, Any]) -> None:
        """Apply a membership transition (digestion thread): bump the epoch,
        start the reshape clock, and let the heartbeat/barrier paths carry
        the new view to every member. A min_slices violation aborts the run
        with the violation as the experiment error — deterministic, never a
        hang on a barrier that cannot complete."""
        from maggy_tpu.resilience.membership import MembershipViolation

        kind, slice_id = msg.get("kind"), msg.get("slice")
        view = self.membership
        try:
            new = view.drop(slice_id) if kind == "drop" else view.rejoin(slice_id)
        except (MembershipViolation, ValueError) as e:
            self.log(f"Membership {kind} of slice {slice_id} rejected: {e}")
            with self.lock:
                if self.exception is None:
                    self.exception = e
            self.abort.set()
            self.experiment_done.set()
            return
        if new.epoch == view.epoch:
            self.log(
                f"Membership {kind} of slice {slice_id} ignored "
                f"(duplicate report at epoch {view.epoch})"
            )
            return
        import time as _time

        with self.lock:
            self.membership = new
            self._reshape_t0 = _time.perf_counter()
            if kind == "drop":
                self._last_seen.pop(slice_id, None)
        self.telemetry.count(
            "resilience.slice_drops" if kind == "drop" else "resilience.slice_rejoins"
        )
        self.telemetry.gauge("resilience.membership_epoch", new.epoch)
        self.telemetry.gauge("resilience.active_slices", new.n_active)
        self.log(
            f"Membership epoch {new.epoch}: slice {slice_id} "
            f"{'left' if kind == 'drop' else 'rejoined'}"
            + (f" ({msg['error']})" if msg.get("error") else "")
            + f"; active slices {list(new.active)}/{new.total_slices} — "
            "reshape barrier open, survivors converge on the latest "
            "complete checkpoint"
        )
        # a drop can complete the experiment retroactively: every REMAINING
        # member may already have FINALed at full width
        self._check_elastic_completion()

    def _needed_finals(self) -> int:
        if self.elastic and self.membership.mode == "workers":
            return self.membership.n_active
        return self.num_executors

    def _check_elastic_completion(self) -> None:
        with self.lock:
            done = len(self._finals)
        if done >= self._needed_finals() and not self.experiment_done.is_set():
            self._aggregate()
            self.experiment_done.set()

    def _handle_message(self, msg: Dict[str, Any]) -> None:
        verb = msg.get("type")
        if verb == "_RESTART":
            self._digest_restart(msg)
        elif verb == "_SLICE_EVENT":
            self._digest_slice_event(msg)
        elif verb == "METRIC":
            logs = msg.get("logs") or []
            if logs:
                self.add_executor_logs(logs)
        elif verb == "FINAL":
            if msg.get("error"):
                raise RuntimeError(
                    f"Distributed worker {msg['partition_id']} failed: {msg['error']}"
                )
            with self.lock:
                # a re-admitted (restarted) worker may FINAL twice for one
                # partition — keep only its latest result
                self._finals = [
                    m
                    for m in self._finals
                    if m["partition_id"] != msg["partition_id"]
                ]
                self._finals.append(msg)
                done = len(self._finals)
            needed = self._needed_finals()
            self.log(f"Worker {msg['partition_id']} finished ({done}/{needed})")
            if done >= needed:
                self._aggregate()
                self.experiment_done.set()

    def _aggregate(self) -> None:
        """Average per-worker numeric test metrics (reference
        torch_distributed_training_driver.py:49-69, 137-146). The evaluator's
        outputs are reported separately, never averaged into the training
        mean (reference: the TF evaluator lives outside the worker list)."""
        finals = self._finals
        evaluator = None
        if self.evaluator_partition is not None:
            ev = [m for m in finals if m["partition_id"] == self.evaluator_partition]
            finals = [m for m in finals if m["partition_id"] != self.evaluator_partition]
            if ev:
                evaluator = ev[0].get("outputs") or {}
                if ev[0].get("metric") is not None:
                    evaluator.setdefault("metric", ev[0]["metric"])
        outputs = [m.get("outputs") or {} for m in finals]
        metrics = [m.get("metric") for m in finals if m.get("metric") is not None]
        result: Dict[str, Any] = {"num_workers": len(finals)}
        if metrics:
            result["metric"] = statistics.mean(metrics)
        keys = set().union(*outputs) if outputs else set()
        for k in keys:
            vals = [o[k] for o in outputs if isinstance(o.get(k), (int, float))]
            if vals:
                result.setdefault("outputs", {})[k] = statistics.mean(vals)
        if evaluator is not None:
            result["evaluator"] = evaluator
        self.result = result

    def _status(self) -> Dict[str, Any]:
        base = super()._status()
        with self.lock:
            base.update(
                workers_done=len(self._final_pids),
                evaluator_partition=self.evaluator_partition,
                restarts=self._restarts,
                max_restarts=self.max_restarts,
                last_seen={
                    str(pid): round(time.time() - ts, 1)
                    for pid, ts in self._last_seen.items()
                },
            )
            if self.elastic:
                view = self.membership
                base.update(
                    membership_epoch=view.epoch,
                    active_slices=list(view.active),
                    num_slices=view.total_slices,
                    min_slices=view.min_slices,
                    membership_mode=view.mode,
                )
        return base

    def _exp_final_callback(self) -> None:
        if self.result and "outputs" in self.result:
            flat = dict(self.result["outputs"])
            flat.update({k: v for k, v in self.result.items() if k != "outputs"})
            self.result = flat

    # ------------------------------------------------------------------ executor

    def _local_partitions(self) -> List[int]:
        if not self.pod_mode:
            return super()._local_partitions()
        import socket as socket_mod

        # reachable hostname, not the loopback the Server records for 0.0.0.0
        # binds — launcher tooling copies this into MAGGY_TPU_DRIVER
        self.log(
            f"Pod mode: driver at {socket_mod.gethostname()}:{self.server.port} "
            f"(secret via MAGGY_TPU_SECRET), running local partition 0, "
            f"awaiting {self.num_executors - 1} remote workers"
        )
        return [0]

    def _await_completion(self) -> None:
        super()._await_completion()
        # workers exit right after FINAL is *enqueued*; wait for the digestion
        # thread to actually aggregate before run_experiment reads self.result
        if self.exception is not None or self.abort.is_set():
            return
        if self.pod_mode:
            # remote workers may train for hours: wait for every FINAL, but a
            # registered worker that goes silent past worker_timeout (its
            # heartbeat beats every hb_interval) fails the run loudly instead
            # of hanging the driver forever
            timeout = getattr(self.config, "worker_timeout", 1800.0)
            while not self.experiment_done.wait(timeout=1.0):
                if self.abort.is_set():
                    return
                now = time.time()
                with self.lock:
                    stale = [
                        pid
                        for pid, ts in self._last_seen.items()
                        if now - ts > timeout and pid not in self._final_pids
                    ]
                if stale:
                    if self.elastic and self.membership.mode == "workers":
                        # heartbeat-silent slices leave the membership: the
                        # mesh reshapes around them (min_slices violations
                        # abort from digestion) — no restart budget burned,
                        # and a later re-registration rejoins them
                        for pid in stale:
                            with self.lock:
                                self._last_seen.pop(pid, None)
                            self.telemetry.count("resilience.worker_deaths")
                            self.server.enqueue(
                                {
                                    "type": "_SLICE_EVENT",
                                    "kind": "drop",
                                    "slice": pid,
                                    "partition_id": pid,
                                    "error": f"silent > {timeout:.0f}s",
                                }
                            )
                        continue
                    with self.lock:
                        budget_left = self.max_restarts - self._restarts
                        if budget_left >= len(stale):
                            # elastic window: charge the budget, forget the
                            # dead registrations, and keep waiting — the
                            # respawned hosts (supervisor/launcher) re-register
                            # and resume from the latest checkpoint
                            self._restarts += len(stale)
                            for pid in stale:
                                self._last_seen.pop(pid, None)
                            restarts = self._restarts
                        else:
                            restarts = None
                    if restarts is not None:
                        self.telemetry.count(
                            "resilience.dist_restarts", len(stale)
                        )
                        self.log(
                            f"Pod worker(s) {stale} silent > {timeout:.0f}s; "
                            f"elastic restart window open "
                            f"({restarts}/{self.max_restarts} restarts used) "
                            "— awaiting re-registration"
                        )
                        continue
                    with self.lock:
                        if self.exception is None:
                            self.exception = RuntimeError(
                                f"Pod worker(s) {stale} silent for more than "
                                f"{timeout:.0f}s; aborting experiment."
                            )
                    self.abort.set()
                    self.experiment_done.set()
                    return
        else:
            # local mode: wait for digestion to aggregate the finals. A dead
            # executor may be about to come back via elastic restart, so the
            # 60s grace clock only runs while NO executor thread is alive —
            # a respawned worker (which may train for minutes) resets it.
            grace_deadline = None
            while not self.experiment_done.wait(timeout=0.5):
                if self.abort.is_set():
                    return
                if any(t.is_alive() for t in self._worker_threads):
                    grace_deadline = None
                    continue
                if grace_deadline is None:
                    grace_deadline = time.time() + 60
                elif time.time() > grace_deadline:
                    return

    def _device_groups(self) -> List[list]:
        # one worker per process; with several local workers each leases a
        # disjoint device group, with one worker it spans every local device
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return [[]]
        if self.pod_mode:
            # remote pod workers span their whole host; the driver's local
            # partition must match, not take a 1/num_executors lease
            return [devices]
        n = self.num_executors
        if n <= 1 or len(devices) < n:
            return [devices]
        per = len(devices) // n
        return [devices[i * per : (i + 1) * per] for i in range(n)]

    def _executor_fn(self, train_fn: Callable, partition_id: int, devices: list) -> Callable:
        return dist_executor_fn(
            train_fn=train_fn,
            config=self.config,
            app_id=self.app_id,
            run_id=self.run_id,
            partition_id=partition_id,
            server_addr=(self.server.host, self.server.port),
            secret=self.server.secret,
            devices=devices,
        )
