from maggy_tpu.core.driver.base import Driver
from maggy_tpu.core.driver.hpo import BaseDriver, HyperparameterOptDriver

__all__ = ["Driver", "HyperparameterOptDriver", "BaseDriver"]
