"""Ablation-study driver.

Capability parity with the reference ``AblationDriver``
(core/experiment_driver/ablation_driver.py:32-208): reuses the HPO driver's
entire scheduling/RPC machinery with a LOCO controller and no early stopping
(the reference forces NoStoppingRule, ablation_driver.py:52). Per-trial model
and dataset variants are resolved on the worker via the study's generators —
the flax-factory replacement for the reference's Keras-JSON layer surgery.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

from maggy_tpu.ablation.ablationstudy import AblationStudy, default_dataset_generator
from maggy_tpu.ablation.ablator import LOCO, AbstractAblator
from maggy_tpu.config.hpo import HyperparameterOptConfig
from maggy_tpu.core.driver.hpo import HyperparameterOptDriver
from maggy_tpu.core.executors.trial import trial_executor_fn
from maggy_tpu.optimizer.abstractoptimizer import AbstractOptimizer
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


class AblatorController(AbstractOptimizer):
    """Adapter exposing an AbstractAblator through the optimizer interface the
    driver polls (reference ablation_driver.py:144-151 controller_get_next)."""

    def __init__(self, ablator: AbstractAblator, **kwargs):
        super().__init__(**kwargs)
        self.ablator = ablator

    def initialize(self) -> None:
        self.ablator.final_store = self.final_store
        self.ablator.initialize()

    def get_suggestion(self, trial: Optional[Trial] = None) -> Union[Trial, str, None]:
        return self.ablator.get_trial(trial)

    def finalize_experiment(self, trials) -> None:
        self.ablator.finalize_experiment(trials)

    def name(self) -> str:
        return type(self.ablator).__name__


def _make_ablator(config) -> AbstractAblator:
    if isinstance(config.ablator, AbstractAblator):
        return config.ablator
    if isinstance(config.ablator, str):
        if config.ablator.lower() == "loco":
            return LOCO(config.ablation_study)
        raise ValueError(f"Unknown ablator {config.ablator!r}; expected 'loco'")
    if isinstance(config.ablator, type) and issubclass(config.ablator, AbstractAblator):
        return config.ablator(config.ablation_study)
    raise TypeError(f"ablator must be a name or AbstractAblator, got {config.ablator!r}")


class AblationDriver(HyperparameterOptDriver):
    def __init__(self, config, app_id: str, run_id: int):
        if not isinstance(config.ablation_study, AblationStudy):
            raise TypeError("AblationConfig.ablation_study must be an AblationStudy")
        self.study = config.ablation_study
        ablator = _make_ablator(config)
        hpo_config = HyperparameterOptConfig(
            num_trials=ablator.get_number_of_trials(),
            optimizer=AblatorController(ablator),
            searchspace=Searchspace(),
            optimization_key=config.optimization_key,
            direction=config.direction,
            es_policy="none",  # reference forces NoStoppingRule (ablation_driver.py:52)
            es_min=2**31,
            name=config.name,
            description=config.description,
            hb_interval=config.hb_interval,
            model=config.model,
            dataset=config.dataset,
            num_executors=config.num_executors,
            devices_per_trial=config.devices_per_trial,
            log_dir=config.log_dir,
            sharding=config.sharding,
            driver_addr=getattr(config, "driver_addr", None),
            worker_timeout=getattr(config, "worker_timeout", 600.0),
            trial_retries=getattr(config, "trial_retries", 2),
            retry_backoff=getattr(config, "retry_backoff", 0.5),
            quarantine_after=getattr(config, "quarantine_after", 3),
            quarantine_cooldown=getattr(config, "quarantine_cooldown", 300.0),
        )
        super().__init__(hpo_config, app_id, run_id)

    # ------------------------------------------------------------------ executor

    def _resolver(self):
        return make_ablation_resolver(self.study)

    def _executor_fn(self, train_fn: Callable, partition_id: int, devices: list) -> Callable:
        return trial_executor_fn(
            train_fn=train_fn,
            config=self.config,
            app_id=self.app_id,
            run_id=self.run_id,
            partition_id=partition_id,
            server_addr=(self.server.host, self.server.port),
            secret=self.server.secret,
            devices=devices,
            resolve=self._resolver(),
        )


def make_ablation_resolver(study):
    """Trial-params -> train_fn-kwargs resolver for ablation trials. Module
    level so pod trial workers — which hold the same AblationConfig the
    driver does — can rebuild it host-side (core/pod.py run_trial_worker)."""
    dataset_generator = study.dataset_generator or default_dataset_generator

    def resolve(params, available):
        feature = params.get("ablated_feature")
        component = params.get("ablated_component")
        feature = None if feature in (None, "None") else feature
        component = None if component in (None, "None") else component

        available = dict(available)
        available["ablated_feature"] = feature
        available["ablated_component"] = component
        # the markers ride dedicated kwargs; hparams stays clean so train_fns
        # that splat it into config constructors remain oblivious
        available["hparams"] = {
            k: v
            for k, v in available["hparams"].items()
            if k not in ("ablated_feature", "ablated_component")
        }
        available["dataset"] = dataset_generator(available["dataset"], feature)

        if component is not None and component.startswith("custom:"):
            name = component[len("custom:"):]
            available["model"] = study.model.custom_generators[name]()
        elif study.model.factory is not None:
            ablated = (
                frozenset() if component is None else frozenset(component.split("|"))
            )
            available["model"] = study.model.factory(ablated)
        elif component is not None:
            # factory-free path (reference parity: any model, zero
            # plumbing — loco.py:82-136): derive the variant from the
            # config model via config.without()/ablated-field rebuild, or
            # generic param-subtree masking
            from maggy_tpu.ablation.masking import auto_ablate

            base = available.get("model")
            if base is None:
                raise ValueError(
                    f"Trial ablates component {component!r} but the study "
                    "has no model factory and the config has no model; "
                    "pass AblationConfig(model=...) or call "
                    "study.model.set_factory(fn)."
                )
            available["model"] = auto_ablate(
                base, frozenset(component.split("|"))
            )
        return available

    return resolve
