"""Control-plane RPC: driver <-> executor messaging.

This is the entire control plane, the analogue of the reference's
``maggy/core/rpc.py`` (§2.4 of SURVEY.md) with the same verb set —
REG / QUERY / METRIC / FINAL / GET / LOG / EXEC_CONFIG / RESERVATIONS — but a
different transport design:

* **Framing:** 4-byte big-endian length + UTF-8 JSON. The reference frames
  cloudpickle (rpc.py:205-257); JSON removes arbitrary-code-execution risk from
  the wire and keeps messages debuggable. Functions are never shipped over this
  channel — workers receive the train_fn in-process (threads) or at launch.
* **Server:** one asyncio event loop on a daemon thread (replacing the reference's
  select() loop, rpc.py:350-381). Handlers must be non-blocking: they read
  thread-safe shared stores and enqueue heavy work for the driver's digestion
  thread — the socket loop never waits on an optimizer.
* **Auth:** every message carries the experiment secret, checked with
  ``secrets.compare_digest`` (reference rpc.py:366-375).

The client is synchronous (worker loops are plain Python), with a main socket and
a separate heartbeat socket so the heartbeat thread never interleaves frames with
the trial loop (reference rpc.py:647-651).
"""

from __future__ import annotations

import asyncio
import json
import queue
import random
import secrets as secrets_mod
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from maggy_tpu import constants
from maggy_tpu.exceptions import (
    ReservationTimeoutError,
    RpcError,
    RpcRejectedError,
)
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.telemetry import flightrec
from maggy_tpu.telemetry import tracing as tracing_mod

_LEN = struct.Struct(">I")


def _retry_delay(attempt: int) -> float:
    """Reconnect/retry backoff: linear base growth with a ±50% random spread.
    Without the jitter a whole pod of workers that lost the driver at the
    same instant (driver GC pause, network blip) would sleep identical
    delays and reconnect in lockstep, hammering the recovered server with a
    synchronized thundering herd. Base and retry count take env overrides
    via constants (MAGGY_TPU_RPC_RETRY_BASE / MAGGY_TPU_RPC_MAX_RETRIES)."""
    base = constants.RPC_RETRY_BASE * (attempt + 1)
    return base * (0.5 + random.random())


# --------------------------------------------------------------------------- framing


def send_frame(sock: socket.socket, payload: Dict[str, Any]) -> None:
    data = json.dumps(payload, separators=(",", ":"), default=str).encode("utf-8")
    if len(data) > constants.RPC_MAX_MESSAGE:
        raise RpcError(f"Message of {len(data)} bytes exceeds frame cap")
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_frame(sock: socket.socket) -> Dict[str, Any]:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > constants.RPC_MAX_MESSAGE:
        raise RpcError(f"Incoming frame of {length} bytes exceeds cap")
    return json.loads(_recv_exact(sock, length).decode("utf-8"))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(constants.RPC_BUFSIZE, n - len(buf)))
        if not chunk:
            raise RpcError("Connection closed by peer")
        buf.extend(chunk)
    return bytes(buf)


# ----------------------------------------------------------------------- reservations


class Reservations:
    """Thread-safe registry: partition_id -> registration + current trial assignment.

    The driver's scheduling substrate (reference rpc.py:45-123): the digestion
    thread writes assignments, the server's GET handler reads them.
    """

    def __init__(self, required: int):
        self.required = required
        self._lock = threading.RLock()
        self._entries: Dict[int, Dict[str, Any]] = {}
        self._assignments: Dict[int, Optional[str]] = {}

    def register(self, partition_id: int, meta: Dict[str, Any]) -> bool:
        """Returns True if a *different* worker instance had already registered this
        partition (re-registration = restarted worker; triggers lost-trial handling,
        reference rpc.py:415-437). A retried REG from the same instance carries the
        same ``attempt`` nonce and is idempotent — a lost reply must not look like
        a worker restart."""
        with self._lock:
            prev = self._entries.get(partition_id)
            restarted = prev is not None and prev.get("attempt") != meta.get("attempt")
            self._entries[partition_id] = dict(meta)
            if prev is None:
                self._assignments.setdefault(partition_id, None)
            return restarted

    def done(self) -> bool:
        with self._lock:
            return len(self._entries) >= self.required

    def count(self) -> int:
        with self._lock:
            return len(self._entries)

    def assign_trial(self, partition_id: int, trial_id: Optional[str]) -> None:
        with self._lock:
            self._assignments[partition_id] = trial_id

    def get_assignment(self, partition_id: int) -> Optional[str]:
        with self._lock:
            return self._assignments.get(partition_id)

    def get_assignments(self) -> Dict[int, Optional[str]]:
        with self._lock:
            return dict(self._assignments)

    def cluster_spec(self) -> List[Dict[str, Any]]:
        """All registrations ordered by partition id — the EXEC_CONFIG payload that
        lets rank 0 become the coordinator (reference rpc.py:544-553)."""
        with self._lock:
            return [
                {"partition_id": pid, **self._entries[pid]}
                for pid in sorted(self._entries)
            ]


# ---------------------------------------------------------------------------- server


class Server:
    """Asyncio TCP control-plane server owned by the experiment driver.

    ``callbacks`` maps verb -> handler(msg_dict) -> reply_dict. Handlers run on
    the event loop and must not block; anything heavy goes through
    ``message_queue`` to the driver's digestion thread.
    """

    def __init__(self, num_executors: int, secret: Optional[str] = None):
        self.reservations = Reservations(num_executors)
        self.secret = secret or secrets_mod.token_hex(16)
        # driver-owned telemetry recorder (set by Driver.init); _dispatch
        # records per-verb handler counts/latencies into it
        self.telemetry = None
        self.message_queue: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        self.callbacks: Dict[str, Callable[[Dict[str, Any]], Dict[str, Any]]] = {}
        # the four attributes below are published by the server thread
        # before it calls _started.set(); every other-thread reader first
        # waits on the Event, so the Event's release/acquire pair orders
        # the writes before the reads (stop() additionally only hands
        # _loop to call_soon_threadsafe, the documented thread-safe seam)
        self._loop: Optional[asyncio.AbstractEventLoop] = None  # race: ok — published before _started.set(); readers wait on the Event
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None  # race: ok — published before _started.set(); readers wait on the Event
        self.host = "127.0.0.1"  # race: ok — published before _started.set(); readers wait on the Event
        self.port = 0  # race: ok — published before _started.set(); readers wait on the Event

    # ------------------------------------------------------------------ lifecycle

    def start(self, host: str = "0.0.0.0", port: int = 0) -> Tuple[str, int]:
        self._thread = threading.Thread(
            target=self._run_loop, args=(host, port), name="maggy-rpc-server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            if self._start_error is not None:
                raise RpcError(
                    f"RPC server failed to start: {self._start_error}"
                ) from self._start_error
            raise RpcError("RPC server failed to start within 10s")
        if self._start_error is not None:  # e.g. EADDRINUSE on a preset port
            raise RpcError(
                f"RPC server failed to start: {self._start_error}"
            ) from self._start_error
        return self.host, self.port

    def _run_loop(self, host: str, port: int) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _main():
            try:
                self._server = await asyncio.start_server(
                    self._handle_client, host, port
                )
            except OSError as e:  # surface EADDRINUSE etc. to start()
                self._start_error = e
                self._started.set()
                return
            sockname = self._server.sockets[0].getsockname()
            self.host = "127.0.0.1" if host in ("0.0.0.0", "") else host
            self.port = sockname[1]
            self._started.set()
            async with self._server:
                await self._server.serve_forever()

        try:
            self._loop.run_until_complete(_main())
        except asyncio.CancelledError:
            pass
        finally:
            try:
                pending = asyncio.all_tasks(self._loop)
                for t in pending:
                    t.cancel()
                self._loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            finally:
                self._loop.close()

    def stop(self) -> None:
        if self._loop and self._loop.is_running():

            def _shutdown():
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()

            self._loop.call_soon_threadsafe(_shutdown)
        if self._thread:
            self._thread.join(timeout=5)

    # ------------------------------------------------------------------ handling

    async def _handle_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Per-connection loop, hardened against hostile/buggy peers: an
        oversized declared length gets an ERR reply and a close (the payload
        cannot be skipped safely); a correctly-framed garbage payload (bad
        JSON, or JSON that isn't an object) gets an ERR reply and the loop
        continues — framing is still aligned; a truncated frame (peer died
        mid-send) ends the connection silently. Every path is strictly
        per-connection: the accept loop and other clients never notice."""

        async def _reply(payload: Dict[str, Any]) -> None:
            data = json.dumps(payload, separators=(",", ":"), default=str).encode()
            writer.write(_LEN.pack(len(data)) + data)
            await writer.drain()

        def _frame_err(what: str) -> None:
            if self.telemetry is not None:
                self.telemetry.count(f"rpc_frame_errors.{what}")

        try:
            while True:
                header = await reader.readexactly(_LEN.size)
                (length,) = _LEN.unpack(header)
                if length > constants.RPC_MAX_MESSAGE:
                    _frame_err("oversized")
                    await _reply(
                        {
                            "type": "ERR",
                            "error": f"frame of {length} bytes exceeds cap "
                            f"({constants.RPC_MAX_MESSAGE})",
                        }
                    )
                    break
                raw = await reader.readexactly(length)
                try:
                    msg = json.loads(raw.decode("utf-8"))
                except (UnicodeDecodeError, json.JSONDecodeError):
                    _frame_err("garbage")
                    await _reply({"type": "ERR", "error": "malformed frame payload"})
                    continue
                if not isinstance(msg, dict):
                    _frame_err("not_object")
                    await _reply(
                        {"type": "ERR", "error": "frame payload must be a JSON object"}
                    )
                    continue
                reply = self._dispatch(msg)
                await _reply(reply)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 - peer already gone; close is best-effort
                pass

    def _dispatch(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        if not secrets_mod.compare_digest(str(msg.get("secret", "")), self.secret):
            return {"type": "ERR", "error": "bad secret"}
        verb = msg.get("type", "")
        # stall watchdog: the mark is armed for the whole dispatch —
        # including an injected chaos stall, which wedges the event loop
        # exactly like a stuck driver host — so a reply that never comes
        # back trips a flight-recorder dump (docs/observability.md)
        wd = flightrec.get()
        wd.begin(f"rpc.{verb}")
        try:
            ch = chaos_mod.get()
            if ch is not None:
                # chaos harness only: a matching rpc_stall rule delays this
                # verb's reply — deliberately blocking the event loop, the
                # way a wedged driver host stalls every connection at once
                stall = ch.rpc_stall(verb)
                if stall > 0:
                    time.sleep(stall)
            handler = self.callbacks.get(verb)
            if handler is None:
                return {"type": "ERR", "error": f"unknown verb {verb!r}"}
            tel = self.telemetry
            t0 = time.perf_counter() if tel is not None else 0.0
            try:
                # the frame's trace id becomes ambient for the handler, so
                # everything it records correlates with the caller's request
                with tracing_mod.scope(msg.get("trace")):
                    reply = handler(msg)
            except Exception as e:  # handler bugs must not kill the socket loop
                if tel is not None:
                    tel.rpc(f"srv.{verb}", (time.perf_counter() - t0) * 1e3, ok=False)
                return {"type": "ERR", "error": f"{type(e).__name__}: {e}"}
            if tel is not None:
                tel.rpc(f"srv.{verb}", (time.perf_counter() - t0) * 1e3)
            return reply if reply is not None else {"type": "OK"}
        finally:
            wd.end(f"rpc.{verb}")

    # ------------------------------------------------------------------ helpers

    def register_callback(self, verb: str, handler) -> None:
        self.callbacks[verb] = handler

    def register_metrics(self, source) -> None:
        """Expose a time-series store (or stores) under the ``METRICS`` verb.

        ``source`` is a zero-arg callable returning the reply body — usually
        a closure over ``SeriesStore.snapshot()`` — or a store itself. The
        reply is ``{"type": "METRICS", ...body}``; handlers run on the event
        loop, and ``snapshot()`` only copies bounded rings, so this is safe
        to serve while the owner keeps sampling."""

        def _on_metrics(_msg: Dict[str, Any]) -> Dict[str, Any]:
            body = source() if callable(source) else source.snapshot()
            out = {"type": "METRICS"}
            out.update(body or {})
            return out

        self.register_callback("METRICS", _on_metrics)

    def enqueue(self, msg: Dict[str, Any]) -> None:
        self.message_queue.put(msg)

    def await_reservations(
        self, timeout: float = constants.RESERVATION_TIMEOUT, abort: Optional[threading.Event] = None
    ) -> None:
        """Block until all executors registered (reference rpc.py:282-305)."""
        deadline = time.time() + timeout
        while not self.reservations.done():
            if abort is not None and abort.is_set():
                raise RpcError("Experiment aborted while awaiting reservations")
            if time.time() > deadline:
                raise ReservationTimeoutError(
                    self.reservations.count(), self.reservations.required, timeout
                )
            time.sleep(0.01)


# ---------------------------------------------------------------------------- client


class Client:
    """Synchronous worker-side client (reference rpc.py:636-802).

    Two sockets: the main socket serves the trial loop (register / GET / FINAL);
    the heartbeat socket belongs to the heartbeat thread, which drains the
    reporter every ``hb_interval`` seconds, sends METRIC, and flips the
    reporter's early-stop flag when the driver replies STOP.
    """

    def __init__(
        self,
        server_addr: Tuple[str, int],
        partition_id: int,
        secret: str,
        hb_interval: float = 1.0,
        telemetry=None,
    ):
        self.server_addr = tuple(server_addr)
        self.partition_id = partition_id
        self.secret = secret
        self.hb_interval = hb_interval
        # worker recorder: per-verb client latencies + heartbeat RTT land
        # here, and each beat attaches its snapshot for the driver's STATUS
        # aggregation. An explicit reference (not the thread-ambient getter)
        # because the heartbeat runs on its own thread.
        self.telemetry = telemetry
        # one nonce per client instance: lets the server tell a retried REG
        # (same nonce) from a restarted worker (new nonce)
        self.attempt_id = secrets_mod.token_hex(8)
        # elastic membership (docs/resilience.md): the executor installs its
        # MembershipMonitor here; every beat then reports the epoch the
        # worker is running under, and a RESHAPE reply signals the monitor
        self.membership = None
        self._main_sock = self._connect()  # guarded-by: _main_lock
        self._main_lock = threading.Lock()
        self._hb_sock: Optional[socket.socket] = None  # race: ok — heartbeat-thread-confined between start_heartbeat() and the post-join close in stop()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()

    def _connect(self) -> socket.socket:
        last_err = None
        for attempt in range(constants.RPC_MAX_RETRIES):
            try:
                sock = socket.create_connection(self.server_addr, timeout=30)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except OSError as e:
                last_err = e
                time.sleep(_retry_delay(attempt))
        raise RpcError(f"Could not connect to driver at {self.server_addr}: {last_err}")

    def _request(self, msg: Dict[str, Any], heartbeat: bool = False) -> Dict[str, Any]:
        """Send one frame and read the reply, reconnecting up to MAX_RETRIES
        (reference rpc.py:660-688)."""
        verb = msg.get("type", "?")
        msg = {**msg, "secret": self.secret, "partition_id": self.partition_id}
        if "trace" not in msg:
            # propagate the thread-ambient trace id on every frame — the
            # server re-installs it around its handler, so one request's
            # records correlate across processes (docs/observability.md)
            trace = tracing_mod.current()
            if trace is not None:
                msg["trace"] = trace
        last_err: Optional[Exception] = None
        tel = self.telemetry
        for attempt in range(constants.RPC_MAX_RETRIES):
            try:
                t0 = time.perf_counter()
                if heartbeat:
                    send_frame(self._hb_sock, msg)
                    reply = recv_frame(self._hb_sock)
                else:
                    with self._main_lock:
                        send_frame(self._main_sock, msg)  # blocking: ok — _main_lock exists to serialize whole round-trips on the shared main socket
                        reply = recv_frame(self._main_sock)  # blocking: ok — _main_lock exists to serialize whole round-trips on the shared main socket
                if tel is not None:
                    tel.rpc(verb, (time.perf_counter() - t0) * 1e3)
                if reply.get("type") == "ERR":
                    raise RpcRejectedError(
                        f"Driver rejected message: {reply.get('error')}"
                    )
                return reply
            except (OSError, RpcError) as e:
                if isinstance(e, RpcRejectedError):
                    raise
                if tel is not None:
                    tel.rpc(verb, None, ok=False)
                last_err = e
                time.sleep(_retry_delay(attempt))
                try:
                    if heartbeat:
                        self._hb_sock.close()
                        self._hb_sock = self._connect()
                    else:
                        with self._main_lock:
                            self._main_sock.close()
                            self._main_sock = self._connect()
                except RpcError:
                    pass
        raise RpcError(f"Request {msg.get('type')} failed after retries: {last_err}")

    # public alias: non-worker callers (serve client/router, monitor) speak
    # ad-hoc verbs over the same socket discipline — give them a supported
    # name instead of the private underscore
    request = _request

    # ------------------------------------------------------------------ verbs

    def register(self, meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return self._request(
            {"type": "REG", "meta": {**(meta or {}), "attempt": self.attempt_id}}
        )

    def await_reservations(
        self, timeout: float = constants.RESERVATION_TIMEOUT
    ) -> None:
        deadline = time.time() + timeout
        while True:
            reply = self._request({"type": "QUERY"})
            if reply.get("ready"):
                return
            if time.time() > deadline:
                raise RpcError("Timed out waiting for all executors to register")
            time.sleep(constants.POLL_INTERVAL)

    def get_suggestion(self, poll: float = constants.POLL_INTERVAL) -> Dict[str, Any]:
        """Blocking poll for the next trial; returns the TRIAL or GSTOP reply
        (reference rpc.py:739-748).

        Adaptive backoff: right after FINAL the driver's digestion thread
        assigns the next trial within ~a millisecond, so the first retries
        come fast (2 ms, doubling) and only a genuinely idle executor backs
        off to the full ``poll`` interval — "executors always busy" is the
        reference's one published claim (DistributedML'20), and a fixed
        50 ms first retry measurably taxed it (tools/bench_async_vs_bsp.py)."""
        delay = 0.002
        while True:
            reply = self._request({"type": "GET"})
            if reply.get("type") in ("TRIAL", "GSTOP"):
                return reply
            time.sleep(delay)
            delay = min(delay * 2, poll)

    def finalize_metric(
        self,
        trial_id: str,
        metric: Optional[float],
        outputs: Optional[Dict[str, Any]] = None,
        error: Optional[str] = None,
        early_stopped: bool = False,
    ) -> None:
        self._request(
            {
                "type": "FINAL",
                "trial_id": trial_id,
                "metric": metric,
                "outputs": outputs or {},
                "error": error,
                "early_stopped": early_stopped,
            }
        )

    def get_message(self, verb: str, timeout: float = 60.0) -> Dict[str, Any]:
        """Generic typed fetch with timeout (reference rpc.py:750-762)."""
        deadline = time.time() + timeout
        while True:
            reply = self._request({"type": verb})
            if reply.get("type") == verb:
                return reply
            if time.time() > deadline:
                raise RpcError(f"No {verb} reply within {timeout}s")
            time.sleep(constants.POLL_INTERVAL)

    # ------------------------------------------------------------------ heartbeat

    def start_heartbeat(self, reporter) -> None:
        self._hb_sock = self._connect()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            args=(reporter,),
            name=f"maggy-heartbeat-{self.partition_id}",
            daemon=True,
        )
        self._hb_thread.start()

    def _heartbeat_loop(self, reporter) -> None:
        """Reference rpc.py:716-737: drain reporter -> METRIC -> handle STOP reply."""
        while not self._hb_stop.wait(self.hb_interval):
            self._send_beat(reporter)
        self._send_beat(reporter)  # final flush so no metrics/logs are lost

    def _send_beat(self, reporter) -> None:
        ch = chaos_mod.get()
        if ch is not None and ch.drop_heartbeat(self.partition_id):
            return  # chaos: this worker goes silent for a beat
        trial_id, metric, step, logs = reporter.get_data()
        tel = self.telemetry
        beat = {
            "type": "METRIC",
            "trial_id": trial_id,
            "metric": metric,
            "step": step,
            "logs": logs,
        }
        membership = self.membership
        if membership is not None:
            # the driver compares this against its membership view and
            # replies RESHAPE when this worker is running a stale epoch
            beat["epoch"] = membership.epoch
        if tel is not None and tel.active:
            snap = tel.snapshot()
            if snap:
                beat["telemetry"] = snap
        t0 = time.perf_counter()
        try:
            reply = self._request(beat, heartbeat=True)
        except RpcError:
            return  # skip this beat; next one reconnects
        if tel is not None:
            # driver round-trip as seen by the worker: control-plane health
            tel.gauge("heartbeat_rtt_ms", (time.perf_counter() - t0) * 1e3)
            # heartbeat cadence doubles as the durable-flush cadence: events
            # reach the JSONL sink every beat, so a crash loses <=1 interval
            tel.flush()
        if reply.get("type") == "STOP":
            reporter.early_stop()
        elif reply.get("type") == "RESHAPE" and membership is not None:
            # membership moved: Trainer.fit sees the pending epoch at its
            # next step boundary and raises MembershipChanged
            membership.signal(reply.get("epoch"))

    def stop(self) -> None:
        self._hb_stop.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=2 * self.hb_interval + 5)
        for sock in (self._hb_sock, self._main_sock):  # race: ok — shutdown path after hb join; a racing close raises OSError, swallowed below
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
