"""Streaming sharded dataset: file-backed input pipeline.

The TPU-native replacement for the reference's petastorm delegation
(core/patching/dataloader.py:100-144: parquet row-groups sharded by
RANK/WORLD_SIZE under the hood of a torch DataLoader). Layout on disk::

    data_dir/
      tokens/shard-00000.npy
      tokens/shard-00001.npy
      labels/shard-00000.npy
      ...

One ``.npy`` per (field, shard). Local shards are memory-mapped — a training
run touches only the pages its batches gather, never the full dataset; remote
shards (GCS etc.) stream shard-at-a-time through the Env seam. Work splits
across processes at shard granularity, round-robin by ``process_index %
num_processes`` — exactly petastorm's row-group semantics, so per-process
coverage is disjoint by construction (tested).

Batches come off a background producer thread through the same bounded queue /
C++ gather machinery as :class:`~maggy_tpu.train.native_loader.NativeBatchLoader`
(two-level shuffle: shard order, then rows within the shard), overlapping host
IO+assembly with device step time.
"""

from __future__ import annotations

import io
import os
import queue
import re
import threading
import weakref
from typing import Dict, Iterator, List, Optional

import numpy as np

from maggy_tpu.train import native_loader

_SHARD_RE = re.compile(r"shard-(\d{5})\.npy$")


def _validate_and_split(arrays: Dict[str, np.ndarray], num_chunks: int) -> np.ndarray:
    """Shared writer validation: non-empty dict, equal leading dims, a chunk
    count in [1, rows]. Returns the row bounds for ``num_chunks`` chunks."""
    if not arrays:
        raise ValueError("arrays must be a non-empty dict")
    n = {v.shape[0] for v in arrays.values()}
    if len(n) != 1:
        raise ValueError(f"All arrays need equal leading dims, got {n}")
    n = n.pop()
    if num_chunks < 1 or num_chunks > n:
        raise ValueError(f"chunk count must be in [1, {n}], got {num_chunks}")
    return np.linspace(0, n, num_chunks + 1, dtype=np.int64)


def write_sharded(
    data_dir: str, arrays: Dict[str, np.ndarray], num_shards: int
) -> None:
    """Split ``arrays`` row-wise into ``num_shards`` .npy files per field."""
    bounds = _validate_and_split(arrays, num_shards)
    for field, arr in arrays.items():
        field_dir = os.path.join(data_dir, field)
        os.makedirs(field_dir, exist_ok=True)
        for s in range(num_shards):
            np.save(
                os.path.join(field_dir, f"shard-{s:05d}.npy"),
                np.ascontiguousarray(arr[bounds[s] : bounds[s + 1]]),
            )


class _ShardLoaderMixin:
    """Shared process-split + loader construction for shard-unit datasets
    (``.npy`` field shards, Parquet row groups). Subclasses provide
    ``fields``, ``num_shards`` and ``open_shard(field, shard)``."""

    def read_shard(self, shard: int) -> Dict[str, np.ndarray]:
        """All fields of one shard. Default: per-field ``open_shard`` calls;
        columnar subclasses override to read every column in one pass. Must
        be thread-safe — each loader reads from its own producer thread."""
        return {f: self.open_shard(f, shard) for f in self.fields}

    def my_shards(self, process_index: int = 0, num_processes: int = 1) -> List[int]:
        """Round-robin shard assignment (petastorm RANK/WORLD_SIZE split,
        reference dataloader.py:116-131): disjoint, near-balanced."""
        if not 0 <= process_index < num_processes:
            raise ValueError(f"process_index {process_index} not in [0, {num_processes})")
        if num_processes > self.num_shards:
            raise ValueError(
                f"{num_processes} processes but only {self.num_shards} shards; "
                "write more shards than processes"
            )
        return list(range(process_index, self.num_shards, num_processes))

    def loader(
        self,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        loop: bool = True,
        prefetch: int = 2,
        process_index: int = 0,
        num_processes: int = 1,
        ctx=None,
    ) -> "ShardedStreamLoader":
        """Build the streaming loader for this process's shard subset.

        Pass ``ctx`` (the injected TrainContext) to derive process topology;
        the batches are *process-local* — feed them through
        ``trainer.shard_batch(batch, local=True)``.
        """
        if ctx is not None:
            process_index = ctx.process_index
            num_processes = ctx.num_processes
        return ShardedStreamLoader(
            self,
            self.my_shards(process_index, num_processes),
            batch_size,
            shuffle=shuffle,
            seed=seed + process_index,  # decorrelate shard/row order per process
            loop=loop,
            prefetch=prefetch,
        )


class ShardedDataset(_ShardLoaderMixin):
    """Handle on a sharded dataset directory (local path or Env-seam URL).

    ``columns`` restricts the fields read (e.g. LOCO feature ablation drops
    one column without touching the files)."""

    def __init__(self, data_dir: str, columns: Optional[List[str]] = None):
        self.data_dir = data_dir
        self.fields = sorted(
            d for d in self._listdir(data_dir)
            if self._isdir(os.path.join(data_dir, d))
        )
        if not self.fields:
            raise ValueError(f"No field directories under {data_dir!r}")
        if columns is not None:
            if not columns:
                raise ValueError("columns must be a non-empty list (or None)")
            missing = [c for c in columns if c not in self.fields]
            if missing:
                raise ValueError(
                    f"Columns {missing} not in dataset fields {self.fields}"
                )
            self.fields = sorted(columns)
        per_field = {}
        for f in self.fields:
            shards = sorted(
                m.group(0)
                for m in map(_SHARD_RE.search, self._listdir(os.path.join(data_dir, f)))
                if m
            )
            per_field[f] = shards
        names = {tuple(s) for s in per_field.values()}
        if len(names) != 1:
            # exact same shard file names in every field, or rows pair up wrong
            raise ValueError(f"Inconsistent shard files across fields: {per_field}")
        self._shard_names = per_field[self.fields[0]]
        self.num_shards = len(self._shard_names)
        if self.num_shards == 0:
            raise ValueError(f"No shard files under {data_dir!r}")

    # ---------------------------------------------------------------- fs seam

    def _env(self):
        from maggy_tpu.core.env import EnvSing

        return EnvSing.get_instance()

    def _listdir(self, path: str) -> List[str]:
        if os.path.isdir(path):
            return os.listdir(path)
        return [os.path.basename(p) for p in self._env().listdir(path)]

    def _isdir(self, path: str) -> bool:
        if os.path.exists(path):
            return os.path.isdir(path)
        try:
            return bool(self._env().listdir(path))
        except Exception:
            return False

    def open_shard(self, field: str, shard: int) -> np.ndarray:
        """mmap local shards (page-level IO); stream remote ones whole."""
        path = os.path.join(self.data_dir, field, self._shard_names[shard])
        if os.path.exists(path):
            return np.load(path, mmap_mode="r")
        with self._env().open_file(path, "rb") as f:
            return np.load(io.BytesIO(f.read()))

class ParquetShardedDataset(_ShardLoaderMixin):
    """Columnar (Parquet/Arrow) ingestion — the reference's actual input
    format: petastorm reads parquet row groups sharded by RANK/WORLD_SIZE
    (reference dataloader.py:100-144). Here the **row group** is the shard
    unit: files under ``data_dir`` (or a single ``.parquet`` path) are
    enumerated sorted, their row groups form one global shard list split
    round-robin across processes, and batches flow through the same
    two-level shuffle + C++ row-gather as :class:`ShardedDataset`.

    Gated on pyarrow (optional dependency): importing this module never
    touches it; constructing without pyarrow raises with guidance.

    Columns may be scalars (one value per row) or fixed-length lists (token
    sequences); each maps to a ``[rows, ...]`` numpy field array.
    """

    def __init__(self, path: str, columns: Optional[List[str]] = None):
        if columns is not None and not columns:
            raise ValueError("columns must be a non-empty list (or None)")
        try:
            import pyarrow.parquet as pq
        except ImportError as e:  # pragma: no cover - env without pyarrow
            raise ImportError(
                "ParquetShardedDataset needs pyarrow; install it or convert "
                "the data with write_sharded() to the .npy layout."
            ) from e
        self.path = path
        if os.path.isdir(path):
            self.files = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith((".parquet", ".pq"))
            )
        else:
            self.files = [path]
        if not self.files:
            raise ValueError(f"No .parquet files under {path!r}")
        # global shard list: (file, row_group) in deterministic order; every
        # file's schema is checked for the selected columns AND their types
        # (a missing column or a different fixed-list width must fail here,
        # not as a mid-training producer error)
        self._units: List[tuple] = []
        first_schema = None
        col_types = None
        for f in self.files:
            pf = pq.ParquetFile(f)
            schema = pf.schema_arrow
            if first_schema is None:
                first_schema = schema
                self.fields = list(columns) if columns else list(schema.names)
                missing = [c for c in self.fields if c not in schema.names]
                if missing:
                    raise ValueError(
                        f"Columns {missing} not in parquet schema {schema.names}"
                    )
                col_types = {c: schema.field(c).type for c in self.fields}
            else:
                for c in self.fields:
                    if c not in schema.names:
                        raise ValueError(
                            f"File {f!r} lacks column {c!r} present in "
                            f"{self.files[0]!r}"
                        )
                    if schema.field(c).type != col_types[c]:
                        raise ValueError(
                            f"Column {c!r} type mismatch: {schema.field(c).type} "
                            f"in {f!r} vs {col_types[c]} in {self.files[0]!r}"
                        )
            self._units.extend((f, g) for g in range(pf.metadata.num_row_groups))
        self.num_shards = len(self._units)
        if self.num_shards == 0:
            raise ValueError(f"No row groups in {path!r}")
        # ParquetFile handles are stateful and not thread-safe; each loader
        # reads from its own producer thread, so cache handles per thread
        self._tls = threading.local()

    def _file(self, path: str):
        import pyarrow.parquet as pq

        handles = getattr(self._tls, "handles", None)
        if handles is None:
            handles = self._tls.handles = {}
        pf = handles.get(path)
        if pf is None:
            if len(handles) >= 8:  # bounded per-thread handle cache
                handles.pop(next(iter(handles)))
            pf = handles[path] = pq.ParquetFile(path)
        return pf

    def read_shard(self, shard: int) -> Dict[str, np.ndarray]:
        """One row group, all selected columns in a single read."""
        path, group = self._units[shard]
        table = self._file(path).read_row_group(group, columns=self.fields)
        return {f: _arrow_column_to_numpy(table.column(f)) for f in self.fields}

    def open_shard(self, field: str, shard: int) -> np.ndarray:
        """One row group's column as a ``[rows, ...]`` array."""
        path, group = self._units[shard]
        table = self._file(path).read_row_group(group, columns=[field])
        return _arrow_column_to_numpy(table.column(field))


def _arrow_column_to_numpy(col) -> np.ndarray:
    """Arrow column -> contiguous numpy rows: scalars as 1-D, (fixed-size)
    lists as 2-D — ragged lists are rejected (pad/pack upstream)."""
    import pyarrow as pa

    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    t = arr.type
    if pa.types.is_fixed_size_list(t):
        values = arr.flatten().to_numpy(zero_copy_only=False)
        return np.ascontiguousarray(values.reshape(len(arr), t.list_size))
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        lengths = arr.value_lengths().to_numpy(zero_copy_only=False)
        uniq = np.unique(lengths)
        if len(uniq) != 1:
            raise ValueError(
                f"Ragged list column (lengths {uniq[:5]}...); sequences must "
                "be padded/packed to a fixed length upstream"
            )
        values = arr.flatten().to_numpy(zero_copy_only=False)
        return np.ascontiguousarray(values.reshape(len(arr), int(uniq[0])))
    return np.ascontiguousarray(arr.to_numpy(zero_copy_only=False))


def write_parquet(
    path: str,
    arrays: Dict[str, np.ndarray],
    *,
    rows_per_group: int,
    num_files: int = 1,
) -> None:
    """Test/example helper: write ``arrays`` as Parquet with explicit row
    groups (2-D arrays become fixed-size-list columns)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    # empty part files would each still carry one empty row group, which
    # becomes a shard whose loader busy-spins — reject up front
    bounds = _validate_and_split(arrays, num_files)

    def column(arr):
        if arr.ndim == 1:
            return pa.array(arr)
        if arr.ndim == 2:
            flat = pa.array(np.ascontiguousarray(arr).reshape(-1))
            return pa.FixedSizeListArray.from_arrays(flat, arr.shape[1])
        raise ValueError("write_parquet supports 1-D and 2-D arrays")

    os.makedirs(path, exist_ok=True)
    for i in range(num_files):
        chunk = {k: v[bounds[i] : bounds[i + 1]] for k, v in arrays.items()}
        table = pa.table({k: column(v) for k, v in chunk.items()})
        pq.write_table(
            table,
            os.path.join(path, f"part-{i:05d}.parquet"),
            row_group_size=rows_per_group,
        )


class ShardedStreamLoader:
    """Background-thread iterator of dict batches over a shard subset."""

    def __init__(
        self,
        dataset: ShardedDataset,
        shard_ids: List[int],
        batch_size: int,
        *,
        shuffle: bool,
        seed: int,
        loop: bool,
        prefetch: int,
    ):
        self.dataset = dataset
        self.shard_ids = list(shard_ids)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.loop = loop
        self._lib = native_loader._native_lib()
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=_stream_producer,
            args=(weakref.ref(self),),
            name="maggy-sharded-loader",
            daemon=True,
        )
        self._thread.start()

    def _perm(self, n: int, salt: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(n, dtype=np.int64)
        return native_loader.perm_indices(self._lib, n, self.seed * 1_000_003 + salt)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        item = self._queue.get()
        if item is None:
            raise StopIteration
        if isinstance(item, _ProducerError):
            raise RuntimeError(
                f"Sharded loader producer failed: {item.message}"
            ) from item.cause
        return item

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)


class _ProducerError:
    """Queue sentinel carrying a producer-thread failure to the consumer."""

    def __init__(self, message: str, cause: BaseException):
        self.message = message
        self.cause = cause


def _emit(q: "queue.Queue", item, stop: threading.Event, loader_ref) -> bool:
    """Blocking put that aborts on stop/collection; True when delivered."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            if loader_ref() is None:
                return False
    return False


def _stream_producer(loader_ref: "weakref.ref") -> None:
    loader = loader_ref()
    if loader is None:
        return
    q, stop = loader._queue, loader._stop
    del loader
    try:
        _stream_batches(loader_ref, q, stop)
    except Exception as e:  # noqa: BLE001 — surfaced to the consumer
        _emit(q, _ProducerError(f"{type(e).__name__}: {e}", e), stop, loader_ref)


def _stream_batches(loader_ref, q, stop) -> None:
    epoch = 0
    carry: Optional[Dict[str, np.ndarray]] = None  # shard-tail rows
    while True:
        loader = loader_ref()
        if loader is None or stop.is_set():
            return
        shard_order = [
            loader.shard_ids[i]
            for i in loader._perm(len(loader.shard_ids), salt=epoch)
        ]
        ds, bs, one_epoch = loader.dataset, loader.batch_size, not loader.loop
        del loader
        for s in shard_order:
            loader = loader_ref()
            if loader is None or stop.is_set():
                return
            lib = loader._lib
            arrays = ds.read_shard(s)
            n = next(iter(arrays.values())).shape[0]
            perm = loader._perm(n, salt=epoch * 100_003 + s + 1)
            del loader
            if carry is not None:
                # complete the boundary batch with just enough head rows —
                # the rest of the shard stays mmap'd, no full-shard copy
                need = min(bs - len(carry[ds.fields[0]]), n)
                head = np.ascontiguousarray(perm[:need])
                boundary = {
                    f: np.concatenate(
                        [carry[f], native_loader.gather_rows(lib, arrays[f], head)]
                    )
                    for f in ds.fields
                }
                perm = perm[need:]
                n -= need
                carry = None
                if len(boundary[ds.fields[0]]) == bs:
                    if not _emit(q, boundary, stop, loader_ref):
                        return
                else:  # tiny shard: still short of a full batch
                    carry = boundary
                    continue
            for i in range(0, n - bs + 1, bs):
                idx = np.ascontiguousarray(perm[i : i + bs])
                batch = {
                    f: native_loader.gather_rows(lib, arrays[f], idx)
                    for f in ds.fields
                }
                if not _emit(q, batch, stop, loader_ref):
                    return
            tail = np.ascontiguousarray(perm[(n // bs) * bs :])
            if len(tail):
                carry = {
                    f: native_loader.gather_rows(lib, arrays[f], tail)
                    for f in ds.fields
                }
        epoch += 1
        if one_epoch:
            _emit(q, None, stop, loader_ref)  # non-pinning end-of-data put
            return
