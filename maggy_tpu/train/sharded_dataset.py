"""Streaming sharded dataset: file-backed input pipeline.

The TPU-native replacement for the reference's petastorm delegation
(core/patching/dataloader.py:100-144: parquet row-groups sharded by
RANK/WORLD_SIZE under the hood of a torch DataLoader). Layout on disk::

    data_dir/
      tokens/shard-00000.npy
      tokens/shard-00001.npy
      labels/shard-00000.npy
      ...

One ``.npy`` per (field, shard). Local shards are memory-mapped — a training
run touches only the pages its batches gather, never the full dataset; remote
shards (GCS etc.) stream shard-at-a-time through the Env seam. Work splits
across processes at shard granularity, round-robin by ``process_index %
num_processes`` — exactly petastorm's row-group semantics, so per-process
coverage is disjoint by construction (tested).

Batches come off a background producer thread through the same bounded queue /
C++ gather machinery as :class:`~maggy_tpu.train.native_loader.NativeBatchLoader`
(two-level shuffle: shard order, then rows within the shard), overlapping host
IO+assembly with device step time.
"""

from __future__ import annotations

import io
import os
import queue
import re
import threading
import weakref
from typing import Dict, Iterator, List, Optional

import numpy as np

from maggy_tpu.train import native_loader

_SHARD_RE = re.compile(r"shard-(\d{5})\.npy$")


def write_sharded(
    data_dir: str, arrays: Dict[str, np.ndarray], num_shards: int
) -> None:
    """Split ``arrays`` row-wise into ``num_shards`` .npy files per field."""
    if not arrays:
        raise ValueError("arrays must be a non-empty dict")
    n = {v.shape[0] for v in arrays.values()}
    if len(n) != 1:
        raise ValueError(f"All arrays need equal leading dims, got {n}")
    n = n.pop()
    if num_shards < 1 or num_shards > n:
        raise ValueError(f"num_shards must be in [1, {n}]")
    bounds = np.linspace(0, n, num_shards + 1, dtype=np.int64)
    for field, arr in arrays.items():
        field_dir = os.path.join(data_dir, field)
        os.makedirs(field_dir, exist_ok=True)
        for s in range(num_shards):
            np.save(
                os.path.join(field_dir, f"shard-{s:05d}.npy"),
                np.ascontiguousarray(arr[bounds[s] : bounds[s + 1]]),
            )


class ShardedDataset:
    """Handle on a sharded dataset directory (local path or Env-seam URL)."""

    def __init__(self, data_dir: str):
        self.data_dir = data_dir
        self.fields = sorted(
            d for d in self._listdir(data_dir)
            if self._isdir(os.path.join(data_dir, d))
        )
        if not self.fields:
            raise ValueError(f"No field directories under {data_dir!r}")
        per_field = {}
        for f in self.fields:
            shards = sorted(
                m.group(0)
                for m in map(_SHARD_RE.search, self._listdir(os.path.join(data_dir, f)))
                if m
            )
            per_field[f] = shards
        names = {tuple(s) for s in per_field.values()}
        if len(names) != 1:
            # exact same shard file names in every field, or rows pair up wrong
            raise ValueError(f"Inconsistent shard files across fields: {per_field}")
        self._shard_names = per_field[self.fields[0]]
        self.num_shards = len(self._shard_names)
        if self.num_shards == 0:
            raise ValueError(f"No shard files under {data_dir!r}")

    # ---------------------------------------------------------------- fs seam

    def _env(self):
        from maggy_tpu.core.env import EnvSing

        return EnvSing.get_instance()

    def _listdir(self, path: str) -> List[str]:
        if os.path.isdir(path):
            return os.listdir(path)
        return [os.path.basename(p) for p in self._env().listdir(path)]

    def _isdir(self, path: str) -> bool:
        if os.path.exists(path):
            return os.path.isdir(path)
        try:
            return bool(self._env().listdir(path))
        except Exception:
            return False

    def open_shard(self, field: str, shard: int) -> np.ndarray:
        """mmap local shards (page-level IO); stream remote ones whole."""
        path = os.path.join(self.data_dir, field, self._shard_names[shard])
        if os.path.exists(path):
            return np.load(path, mmap_mode="r")
        with self._env().open_file(path, "rb") as f:
            return np.load(io.BytesIO(f.read()))

    # ---------------------------------------------------------------- sharding

    def my_shards(self, process_index: int = 0, num_processes: int = 1) -> List[int]:
        """Round-robin shard assignment (petastorm RANK/WORLD_SIZE split,
        reference dataloader.py:116-131): disjoint, near-balanced."""
        if not 0 <= process_index < num_processes:
            raise ValueError(f"process_index {process_index} not in [0, {num_processes})")
        if num_processes > self.num_shards:
            raise ValueError(
                f"{num_processes} processes but only {self.num_shards} shards; "
                "write more shards than processes"
            )
        return list(range(process_index, self.num_shards, num_processes))

    def loader(
        self,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        loop: bool = True,
        prefetch: int = 2,
        process_index: int = 0,
        num_processes: int = 1,
        ctx=None,
    ) -> "ShardedStreamLoader":
        """Build the streaming loader for this process's shard subset.

        Pass ``ctx`` (the injected TrainContext) to derive process topology;
        the batches are *process-local* — feed them through
        ``trainer.shard_batch(batch, local=True)``.
        """
        if ctx is not None:
            process_index = ctx.process_index
            num_processes = ctx.num_processes
        return ShardedStreamLoader(
            self,
            self.my_shards(process_index, num_processes),
            batch_size,
            shuffle=shuffle,
            seed=seed + process_index,  # decorrelate shard/row order per process
            loop=loop,
            prefetch=prefetch,
        )


class ShardedStreamLoader:
    """Background-thread iterator of dict batches over a shard subset."""

    def __init__(
        self,
        dataset: ShardedDataset,
        shard_ids: List[int],
        batch_size: int,
        *,
        shuffle: bool,
        seed: int,
        loop: bool,
        prefetch: int,
    ):
        self.dataset = dataset
        self.shard_ids = list(shard_ids)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.loop = loop
        self._lib = native_loader._native_lib()
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=_stream_producer,
            args=(weakref.ref(self),),
            name="maggy-sharded-loader",
            daemon=True,
        )
        self._thread.start()

    def _perm(self, n: int, salt: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(n, dtype=np.int64)
        return native_loader.perm_indices(self._lib, n, self.seed * 1_000_003 + salt)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        item = self._queue.get()
        if item is None:
            raise StopIteration
        if isinstance(item, _ProducerError):
            raise RuntimeError(
                f"Sharded loader producer failed: {item.message}"
            ) from item.cause
        return item

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)


class _ProducerError:
    """Queue sentinel carrying a producer-thread failure to the consumer."""

    def __init__(self, message: str, cause: BaseException):
        self.message = message
        self.cause = cause


def _emit(q: "queue.Queue", item, stop: threading.Event, loader_ref) -> bool:
    """Blocking put that aborts on stop/collection; True when delivered."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            if loader_ref() is None:
                return False
    return False


def _stream_producer(loader_ref: "weakref.ref") -> None:
    loader = loader_ref()
    if loader is None:
        return
    q, stop = loader._queue, loader._stop
    del loader
    try:
        _stream_batches(loader_ref, q, stop)
    except Exception as e:  # noqa: BLE001 — surfaced to the consumer
        _emit(q, _ProducerError(f"{type(e).__name__}: {e}", e), stop, loader_ref)


def _stream_batches(loader_ref, q, stop) -> None:
    epoch = 0
    carry: Optional[Dict[str, np.ndarray]] = None  # shard-tail rows
    while True:
        loader = loader_ref()
        if loader is None or stop.is_set():
            return
        shard_order = [
            loader.shard_ids[i]
            for i in loader._perm(len(loader.shard_ids), salt=epoch)
        ]
        ds, bs, one_epoch = loader.dataset, loader.batch_size, not loader.loop
        del loader
        for s in shard_order:
            loader = loader_ref()
            if loader is None or stop.is_set():
                return
            lib = loader._lib
            arrays = {f: ds.open_shard(f, s) for f in ds.fields}
            n = next(iter(arrays.values())).shape[0]
            perm = loader._perm(n, salt=epoch * 100_003 + s + 1)
            del loader
            if carry is not None:
                # complete the boundary batch with just enough head rows —
                # the rest of the shard stays mmap'd, no full-shard copy
                need = min(bs - len(carry[ds.fields[0]]), n)
                head = np.ascontiguousarray(perm[:need])
                boundary = {
                    f: np.concatenate(
                        [carry[f], native_loader.gather_rows(lib, arrays[f], head)]
                    )
                    for f in ds.fields
                }
                perm = perm[need:]
                n -= need
                carry = None
                if len(boundary[ds.fields[0]]) == bs:
                    if not _emit(q, boundary, stop, loader_ref):
                        return
                else:  # tiny shard: still short of a full batch
                    carry = boundary
                    continue
            for i in range(0, n - bs + 1, bs):
                idx = np.ascontiguousarray(perm[i : i + bs])
                batch = {
                    f: native_loader.gather_rows(lib, arrays[f], idx)
                    for f in ds.fields
                }
                if not _emit(q, batch, stop, loader_ref):
                    return
            tail = np.ascontiguousarray(perm[(n // bs) * bs :])
            if len(tail):
                carry = {
                    f: native_loader.gather_rows(lib, arrays[f], tail)
                    for f in ds.fields
                }
        epoch += 1
        if one_epoch:
            _emit(q, None, stop, loader_ref)  # non-pinning end-of-data put
            return
