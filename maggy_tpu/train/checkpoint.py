"""Checkpointing and experiment resume.

The reference has **no model checkpointing** (SURVEY.md §5.4 — users hand-roll
saves inside train_fn); here it is first-class:

* :class:`Checkpointer` — orbax-backed async save/restore of (sharded)
  TrainStates into a trial directory; restore rebuilds arrays directly on
  their mesh devices from the abstract target.
* experiment resume — ``HyperparameterOptConfig(resume_from=<exp_dir>)``
  preloads that experiment's persisted ``trial.json`` records into the new
  driver's final store, so finished trials are never re-run (the driver skips
  suggestions whose trial id already finalized).
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Any, Dict, List, Optional

# sidecar directory (non-numeric name: invisible to orbax's step scan)
# holding one JSON per step with the system config the state was saved under
_META_DIR = "system_meta"


class Checkpointer:
    """Thin orbax wrapper bound to one directory (per trial or per run)."""

    def __init__(self, directory: str, max_to_keep: int = 3, async_save: bool = True):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any, meta: Optional[Dict[str, Any]] = None) -> None:
        """Save ``state`` at ``step``. ``meta`` — the active system config
        (``Trainer.checkpoint_meta()``: ShardingSpec axes, n_microbatches,
        dtype) — is recorded in a JSON sidecar so a later restore can warn
        when the live configuration differs from the one that wrote the
        checkpoint."""
        import orbax.checkpoint as ocp

        from maggy_tpu import telemetry

        tel = telemetry.get()
        t0 = time.perf_counter()
        with tel.span("checkpoint_save", step=int(step)):
            self._manager.save(int(step), args=ocp.args.StandardSave(state))
        if meta is not None:
            self._write_meta(int(step), meta)
        # async saves measure the blocking (dispatch) cost — the part that
        # actually steals step time
        tel.gauge("checkpoint_save_ms", (time.perf_counter() - t0) * 1e3)

    # ------------------------------------------------------------------ meta

    def _meta_path(self, step: int) -> str:
        return os.path.join(self.directory, _META_DIR, f"{int(step)}.json")

    def _write_meta(self, step: int, meta: Dict[str, Any]) -> None:
        from maggy_tpu.util import _jsonify

        path = self._meta_path(step)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump(_jsonify(meta), f, sort_keys=True)
        except OSError:
            pass  # metadata is advisory; never fail a save over it

    def saved_meta(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """The system-config metadata recorded with ``step`` (default:
        latest), or None for checkpoints saved without it."""
        step = int(step) if step is not None else self.latest_step()
        if step is None:
            return None
        try:
            with open(self._meta_path(step)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # mesh/world-size keys get the dedicated warn-and-reshard signal in
    # _check_reshard; _check_meta covers the rest (microbatch, dtype, ...)
    _LAYOUT_KEYS = ("mesh_axes", "num_devices", "n_processes")

    def _check_meta(self, step: int, expect_meta: Dict[str, Any]) -> None:
        """Warn (never fail) when the checkpoint's recorded system config
        disagrees with the live one on any shared key — restoring across
        mesh shapes or microbatch settings is *supported* (adopt_state /
        convert_pipeline_state re-place the arrays), but doing it silently
        has burned enough people that the mismatch deserves a signal."""
        from maggy_tpu.util import _jsonify

        saved = self.saved_meta(step)
        if not saved or not expect_meta:
            return
        expect = _jsonify(expect_meta)
        diffs = [
            f"{k}: saved={saved[k]!r} live={expect[k]!r}"
            for k in sorted(set(saved) & set(expect) - set(self._LAYOUT_KEYS))
            if saved[k] != expect[k]
        ]
        if diffs:
            warnings.warn(
                f"checkpoint step {step} was saved under a different system "
                f"config than the live one ({'; '.join(diffs)}); the state "
                "will be re-placed onto the live mesh, but training dynamics "
                "(batch/microbatch semantics) may differ",
                stacklevel=3,
            )

    @staticmethod
    def _template_layout(state_template: Any) -> Optional[Dict[str, Any]]:
        """The live mesh layout implied by the restore template's leaf
        shardings (None when the template carries no mesh — e.g. plain
        numpy trees in unit tests)."""
        import jax

        for leaf in jax.tree.leaves(state_template):
            sharding = getattr(leaf, "sharding", None)
            mesh = getattr(sharding, "mesh", None)
            if mesh is not None and getattr(mesh, "shape", None) is not None:
                try:
                    return {
                        "mesh_axes": {
                            k: v for k, v in dict(mesh.shape).items() if v > 1
                        },
                        "num_devices": int(mesh.size),
                        "n_processes": int(jax.process_count()),
                    }
                except (TypeError, ValueError):
                    return None
        return None

    def _check_reshard(self, step: int, state_template: Any) -> None:
        """Warn-and-reshard (docs/resilience.md): when the sidecar meta
        records a different mesh/world size than the template's live mesh,
        say so explicitly — the restore still proceeds (device_put onto the
        template's shardings re-places every leaf), but a silent cross-mesh
        restore has mis-sharded enough runs that the transition deserves a
        loud signal and a counter. This is the world-size-independent
        restore the elastic membership reshape rides."""
        from maggy_tpu import telemetry

        saved = self.saved_meta(step)
        live = self._template_layout(state_template)
        if not saved or not live:
            return
        diffs = [
            f"{k}: saved={saved[k]!r} live={live[k]!r}"
            for k in ("mesh_axes", "num_devices", "n_processes")
            if saved.get(k) is not None and saved[k] != live[k]
        ]
        if diffs:
            telemetry.get().count("resilience.ckpt_reshards")
            warnings.warn(
                f"checkpoint step {step} was saved on a different mesh "
                f"({'; '.join(diffs)}); resharding every leaf onto the live "
                "mesh during restore",
                stacklevel=3,
            )

    def restore(
        self,
        state_template: Any,
        step: Optional[int] = None,
        expect_meta: Optional[Dict[str, Any]] = None,
    ) -> Any:
        """Restore onto the template's shardings (pass an abstract or concrete
        state built by ``Trainer.make_state``). Pass the live trainer's
        ``checkpoint_meta()`` as ``expect_meta`` to be warned when the
        checkpoint was written under a different sharding/microbatch/dtype
        configuration.

        Fallback (docs/resilience.md): when no explicit ``step`` was
        requested and the latest retained step is unreadable/partial (a save
        interrupted by the very crash being recovered from), older retained
        steps are tried newest-first — each skip warns and counts a
        ``checkpoint_fallback`` telemetry counter. An explicitly requested
        step never falls back."""
        import orbax.checkpoint as ocp

        from maggy_tpu import telemetry

        explicit = step is not None
        step = int(step) if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"No checkpoint found under {self.directory}")
        candidates = (
            [step]
            if explicit
            else sorted((s for s in self.all_steps() if s <= step), reverse=True)
        )
        last_err: Optional[BaseException] = None
        for i, s in enumerate(candidates):
            self._check_reshard(s, state_template)
            if expect_meta is not None:
                self._check_meta(s, expect_meta)
            try:
                with telemetry.get().span("checkpoint_restore", step=s):
                    return self._manager.restore(
                        s, args=ocp.args.StandardRestore(state_template)
                    )
            # broad: orbax surfaces corrupt/truncated checkpoints as many
            # types (ValueError, json/msgpack decode errors, zarr/tensorstore
            # failures) — anything but success means "this step is gone"
            except Exception as e:  # noqa: BLE001
                last_err = e
                if explicit or i == len(candidates) - 1:
                    raise
                telemetry.get().count("checkpoint_fallback")
                warnings.warn(
                    f"checkpoint step {s} under {self.directory} is "
                    f"unreadable ({type(e).__name__}: {e}); falling back to "
                    f"the previous retained step {candidates[i + 1]}",
                    stacklevel=2,
                )
        raise last_err  # unreachable; keeps the control flow explicit

    def restore_params(self, step: Optional[int] = None) -> Any:
        """Params-only restore for serving: pull just the ``params`` subtree
        out of a saved TrainState without rebuilding the trainer/optimizer,
        unboxing flax ``Partitioned`` wrappers (template-free restores
        return them as ``{"value": array}`` dicts) down to raw arrays —
        exactly what ``model.apply({"params": ...})`` and the serve engine
        take."""
        from maggy_tpu import telemetry

        step = int(step) if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"No checkpoint found under {self.directory}")
        with telemetry.get().span("checkpoint_restore_params", step=step):
            restored = self._manager.restore(step)
        tree = restored if isinstance(restored, dict) else restored.__dict__
        if "params" not in tree:
            raise ValueError(
                f"checkpoint at step {step} has no 'params' subtree "
                f"(keys: {sorted(tree)})"
            )

        def unbox(node):
            if isinstance(node, dict):
                if "value" in node and not isinstance(node["value"], dict):
                    return node["value"]
                return {k: unbox(v) for k, v in node.items()}
            return node

        return unbox(tree["params"])

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self) -> List[int]:
        return list(self._manager.all_steps())

    def wait(self) -> None:
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


_DENSE_ZERO = {"stage": 0, "bucket_mb": None, "shards": 1}


def restore_zero_compat(
    checkpointer: Checkpointer,
    state_template: Any,
    *,
    live_meta: Optional[Dict[str, Any]] = None,
    step: Optional[int] = None,
) -> Any:
    """Restore a TrainState across ``zero_stage`` / bucket / data-width
    transitions (docs/distributed.md "Gradient overlap & ZeRO").

    Under ``zero_stage=1`` the optimizer state is saved as flat
    data-sharded bucket vectors (parallel/overlap.py), a layout keyed by
    the bucketing plan — which changes with ``bucket_mb`` and the data-axis
    width. The sidecar meta records that layout (``checkpoint_meta()["zero"]``,
    PR 9's provenance discipline); when it differs from the live trainer's,
    this wrapper restores into a template of the SAVED layout, warns,
    counts ``resilience.ckpt_zero_reshards``, and converts dense↔flat (or
    flat↔flat across plans) before re-placing onto the live template's
    shardings. With matching layouts it is exactly ``Checkpointer.restore``.
    """
    import jax
    import numpy as np

    from maggy_tpu import telemetry
    from maggy_tpu.parallel import overlap

    live_zero = dict((live_meta or {}).get("zero") or _DENSE_ZERO)
    resolved = int(step) if step is not None else checkpointer.latest_step()
    saved_meta = checkpointer.saved_meta(resolved) if resolved is not None else None
    saved_zero = dict((saved_meta or {}).get("zero") or _DENSE_ZERO)
    if saved_zero == live_zero:
        return checkpointer.restore(
            state_template, step=step, expect_meta=live_meta
        )

    params = state_template.params
    abstract_params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
    )

    def opt_template(zero: Dict[str, Any]):
        if int(zero.get("stage") or 0) == 0:
            abstract = jax.eval_shape(state_template.tx.init, abstract_params)
            return None, abstract
        plan = overlap.plan_buckets(
            abstract_params,
            zero.get("bucket_mb"),
            pad_to=max(1, int(zero.get("shards") or 1)),
        )
        flats = {
            b.name: jax.ShapeDtypeStruct((b.padded_size,), b.dtype)
            for b in plan.buckets
        }
        return plan, jax.eval_shape(state_template.tx.init, flats)

    saved_plan, saved_abstract = opt_template(saved_zero)
    live_shardings = jax.tree.map(
        lambda x: getattr(x, "sharding", None), state_template.opt_state
    )
    # concrete zeros (replicated) stand in for the saved layout: orbax
    # overwrites every leaf, and the conversion below re-places the result
    saved_opt = jax.tree.map(
        lambda s: np.zeros(s.shape, s.dtype), saved_abstract
    )
    restored = checkpointer.restore(
        state_template.replace(opt_state=saved_opt),
        step=step,
        expect_meta=live_meta,
    )
    telemetry.get().count("resilience.ckpt_zero_reshards")
    warnings.warn(
        f"checkpoint step {resolved} holds a {_zero_desc(saved_zero)} "
        f"optimizer-state layout; converting to the live {_zero_desc(live_zero)} "
        "layout during restore",
        stacklevel=2,
    )
    opt = restored.opt_state
    if saved_plan is not None:
        opt = overlap.unflatten_opt_state(opt, saved_plan, params)
    live_plan, _ = opt_template(live_zero)
    if live_plan is not None:
        opt = overlap.flatten_opt_state(opt, live_plan, params)
    if all(s is not None for s in jax.tree.leaves(live_shardings)):
        opt = jax.tree.map(jax.device_put, opt, live_shardings)
    return restored.replace(opt_state=opt)


def _zero_desc(zero: Dict[str, Any]) -> str:
    if int(zero.get("stage") or 0) == 0:
        return "dense (zero_stage=0)"
    return (
        f"ZeRO-1 (shards={zero.get('shards')}, bucket_mb={zero.get('bucket_mb')})"
    )


def load_finalized_trials(exp_dir: str) -> list:
    """Load every persisted trial.json under a previous experiment directory
    (the driver's persistence format, hpo.py _persist_trial). Goes through the
    Env abstraction so gs:// experiment dirs resume too."""
    import json

    from maggy_tpu.core.env import EnvSing
    from maggy_tpu.trial import Trial

    env = EnvSing.get_instance()
    out = []
    if not env.exists(exp_dir):
        raise FileNotFoundError(f"resume_from directory does not exist: {exp_dir}")
    for name in env.listdir(exp_dir):
        path = os.path.join(exp_dir, name, "trial.json")
        if not env.exists(path):
            continue
        try:
            trial = Trial.from_dict(env.load_json(path))
        except (json.JSONDecodeError, KeyError, ValueError):
            continue
        if trial.status in (Trial.FINALIZED, Trial.ERROR):
            out.append(trial)
    return out
