"""Native-backed prefetching batch loader.

The first-party data plane replacing the reference's petastorm/DataLoader
delegation (§2.9): shuffled minibatches are assembled by the C++ gather in
``maggy_tpu/native/batcher.cpp`` (compiled on first use, cached), on a
background thread with a bounded queue — ctypes releases the GIL during the
gather, so host batching genuinely overlaps device step time. Falls back to
numpy fancy indexing when no C++ toolchain is available, with identical
batch order for a given seed (the permutation always comes from the native
RNG when the library is present; the fallback uses numpy's).
"""

from __future__ import annotations

import ctypes
import logging
import os
import queue
import subprocess
import threading
import weakref
from typing import Dict, Iterator, Optional

import numpy as np

logger = logging.getLogger(__name__)

_LIB = None
_LIB_TRIED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    """Compile (once, cached) and load the batcher library; None if impossible."""
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    src = os.path.join(os.path.dirname(__file__), "..", "native", "batcher.cpp")
    src = os.path.abspath(src)
    build_dir = os.path.join(os.path.dirname(src), "_build")
    lib_path = os.path.join(build_dir, "libmaggybatcher.so")
    try:
        if not os.path.exists(lib_path) or os.path.getmtime(lib_path) < os.path.getmtime(src):
            os.makedirs(build_dir, exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
                 src, "-o", lib_path],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(lib_path)
        lib.mtl_version.restype = ctypes.c_int64
        if lib.mtl_version() != 1:
            raise RuntimeError("batcher ABI mismatch")
        lib.mtl_perm.argtypes = [ctypes.c_int64, ctypes.c_uint64, ctypes.c_void_p]
        lib.mtl_gather.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32,
        ]
        _LIB = lib
    except (OSError, subprocess.CalledProcessError, RuntimeError) as e:
        logger.warning("Native batcher unavailable (%s); using numpy fallback", e)
        _LIB = None
    return _LIB


def perm_indices(lib: Optional[ctypes.CDLL], n: int, seed: int) -> np.ndarray:
    """Permutation of [0, n) from the native RNG (numpy fallback)."""
    if lib is not None:
        out = np.empty(n, dtype=np.int64)
        lib.mtl_perm(
            n,
            ctypes.c_uint64(seed & (2**64 - 1)),
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out
    return np.random.default_rng(seed).permutation(n).astype(np.int64)


def gather_rows(
    lib: Optional[ctypes.CDLL],
    arr: np.ndarray,
    idx: np.ndarray,
    threads: int = 4,
) -> np.ndarray:
    """Row gather via the C++ library (GIL released); numpy fallback."""
    if lib is None or not arr.flags.c_contiguous:
        return np.asarray(arr[idx])
    row_bytes = arr.dtype.itemsize * int(np.prod(arr.shape[1:], dtype=np.int64))
    out = np.empty((len(idx),) + arr.shape[1:], dtype=arr.dtype)
    lib.mtl_gather(
        arr.ctypes.data_as(ctypes.c_void_p),
        row_bytes,
        idx.ctypes.data_as(ctypes.c_void_p),
        len(idx),
        out.ctypes.data_as(ctypes.c_void_p),
        threads,
    )
    return out


class NativeBatchLoader:
    """Iterator of shuffled dict batches over host arrays.

    ``for batch in NativeBatchLoader({"tokens": toks}, batch_size=32): ...``
    """

    def __init__(
        self,
        arrays: Dict[str, np.ndarray],
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        loop: bool = True,
        prefetch: int = 2,
        gather_threads: int = 4,
    ):
        if not arrays:
            raise ValueError("arrays must be a non-empty dict")
        self.arrays = {k: np.ascontiguousarray(v) for k, v in arrays.items()}
        lengths = {v.shape[0] for v in self.arrays.values()}
        if len(lengths) != 1:
            raise ValueError(f"All arrays need equal leading dims, got {lengths}")
        self.n = lengths.pop()
        if batch_size > self.n:
            raise ValueError(f"batch_size {batch_size} > dataset size {self.n}")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.loop = loop
        self.gather_threads = gather_threads
        self._lib = _native_lib()
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        self._stop = threading.Event()
        # skip(n) bookkeeping: batches are queued tagged with their global
        # index; the producer skips the gather for indices below _min_index
        # and the consumer discards any already-materialized stragglers, so
        # a resume fast-forward costs index arithmetic, not 10k gathers
        self._next_index = 0  # global index of the next batch the consumer expects
        self._min_index = 0  # first index the consumer still wants
        self.gathers = 0  # row gathers performed (skip test hook)
        # the producer holds only a weakref: an un-closed loader that goes out
        # of scope gets collected, and the thread exits instead of pinning the
        # dataset forever
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(weakref.ref(self),),
            name="maggy-native-loader",
            daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------------ internals

    def _perm(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.n, dtype=np.int64)
        return perm_indices(self._lib, self.n, self.seed * 1_000_003 + epoch)

    def _gather(self, arr: np.ndarray, idx: np.ndarray) -> np.ndarray:
        return gather_rows(self._lib, arr, idx, self.gather_threads)

    # ------------------------------------------------------------------ interface

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        while True:
            item = self._queue.get()
            if item is None:
                raise StopIteration
            idx, batch = item
            if idx < self._min_index:
                continue  # materialized before a skip() landed; discard
            self._next_index = idx + 1
            return batch

    def skip(self, n: int) -> int:
        """Advance the stream ``n`` batches without gathering their rows.
        The producer's permutation stream is untouched (one draw per epoch
        either way), so the post-skip sequence is exactly what ``n`` calls
        of ``next()`` would have left. At most the already-queued/in-flight
        batches (bounded by ``prefetch + 1``) are materialized wastefully.
        """
        if n <= 0:
            return 0
        self._next_index += n
        self._min_index = self._next_index
        return n

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    @property
    def using_native(self) -> bool:
        return self._lib is not None


def _producer_loop(loader_ref: "weakref.ref") -> None:
    """Producer body; re-derefs the loader every batch so collection stops it."""
    epoch = 0
    global_idx = 0  # batch counter across epochs (the skip() coordinate)
    while True:
        loader = loader_ref()
        if loader is None or loader._stop.is_set():
            return
        perm = loader._perm(epoch)
        end = (
            (loader.n // loader.batch_size) * loader.batch_size
            if loader.drop_remainder
            else loader.n
        )
        batch_size, one_epoch = loader.batch_size, not loader.loop
        q = loader._queue
        for i in range(0, end, batch_size):
            loader = loader_ref()
            if loader is None or loader._stop.is_set():
                return
            if global_idx < loader._min_index:
                # skipped range: advance the index, never touch the rows
                # (a slightly stale _min_index read just gathers one batch
                # the consumer will discard — the sequence stays exact)
                global_idx += 1
                continue
            idx = np.ascontiguousarray(perm[i : i + batch_size])
            batch = {k: loader._gather(v, idx) for k, v in loader.arrays.items()}
            loader.gathers += 1
            item = (global_idx, batch)
            global_idx += 1
            stop = loader._stop
            del loader  # do not hold a strong ref while blocked on the queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    if loader_ref() is None:
                        return
            if stop.is_set():
                return
        epoch += 1
        if one_epoch:
            q.put(None)  # end-of-data sentinel
            return
