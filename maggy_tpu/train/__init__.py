from maggy_tpu.train.trainer import Trainer, TrainContext, lm_loss_fn, classification_loss_fn

__all__ = ["Trainer", "TrainContext", "lm_loss_fn", "classification_loss_fn"]
