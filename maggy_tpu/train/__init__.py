from maggy_tpu.train.trainer import Trainer, TrainContext, lm_loss_fn, classification_loss_fn
from maggy_tpu.train.prefetch import DevicePrefetcher, skip_batches
from maggy_tpu.train.sharded_dataset import (
    ParquetShardedDataset,
    ShardedDataset,
    write_parquet,
    write_sharded,
)

__all__ = [
    "Trainer",
    "TrainContext",
    "lm_loss_fn",
    "classification_loss_fn",
    "DevicePrefetcher",
    "skip_batches",
    "ParquetShardedDataset",
    "ShardedDataset",
    "write_parquet",
    "write_sharded",
]
