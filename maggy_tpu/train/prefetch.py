"""Host→device input prefetcher: the training tier's overlap seam.

``Trainer.fit``'s old loop body did ``shard_batch`` (host gather + H2D
``device_put``) synchronously between steps, so the device queue drained
while the host assembled the next batch — the exact serialization the
communication/computation-overlap literature (Lagom, the TPU concurrency
study — PAPERS.md) identifies as the first-order loss. The
:class:`DevicePrefetcher` moves that work onto a background thread that runs
``depth`` batches ahead: H2D transfer of batch ``i+1`` overlaps compute of
batch ``i``, and the consumer's per-step cost collapses to a queue pop.

Design notes:

* **put runs in the producer thread.** ``put`` (normally
  ``Trainer.shard_batch``) issues ``jax.device_put`` against the mesh
  shardings; JAX dispatch is thread-safe and the resulting arrays are
  ordinary global arrays by the time the consumer sees them.
* **Bounded consumption.** ``max_items`` caps how many host batches are ever
  pulled from ``source`` — ``fit`` passes its step budget, so on the happy
  path the prefetcher consumes *exactly* as many batches as the synchronous
  loop would have (iterators shared across consecutive calls keep their
  position). Only early exits (preemption, chaos, early stop) leave up to
  ``depth`` extra batches consumed.
* **Telemetry.** Each placement records a ``shard_batch`` span (same name
  the synchronous path used) into the recorder handed in by the consumer;
  the consumer side records ``input_wait_ms`` (time blocked on the queue —
  ~0 when the pipeline keeps up) and ``prefetch_depth`` (queue occupancy)
  gauges.
* **Collection-safe.** Like :class:`NativeBatchLoader`, the producer holds
  only a weakref to the prefetcher, so an un-closed prefetcher that goes out
  of scope is collected and its thread exits instead of pinning the source.

:func:`skip_batches` is the resume fast path: it routes ``fit``'s
``resume="auto"`` fast-forward through a loader's ``skip(n)`` (index
advance, no data materialization — ``batch_iterator`` and
``NativeBatchLoader`` implement it) and falls back to draining ``next()``
for plain generators.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterator, Optional

from maggy_tpu import telemetry


def skip_batches(source: Any, n: int) -> int:
    """Advance ``source`` by ``n`` batches, preferring its ``skip(n)`` fast
    path (no materialization) over draining ``next()``. Returns how many
    batches were actually skipped (short on exhaustion)."""
    if n <= 0:
        return 0
    src_skip = getattr(source, "skip", None)
    if callable(src_skip):
        out = src_skip(n)
        return n if out is None else int(out)
    skipped = 0
    for _ in range(n):
        try:
            next(source)
        except StopIteration:
            break
        skipped += 1
    return skipped


class _Error:
    """Producer-side exception, relayed to the consumer verbatim."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


_END = object()  # end-of-source sentinel


class DevicePrefetcher:
    """Double-buffered host→device iterator over any batch iterator.

    ``for sharded in DevicePrefetcher(loader, trainer.shard_batch): ...``
    yields device-placed batches in source order while the producer thread
    stays ``depth`` batches ahead.
    """

    def __init__(
        self,
        source: Iterator,
        put: Callable[[Any], Any],
        depth: int = 2,
        max_items: Optional[int] = None,
        telemetry_recorder=None,
        ledger=None,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._source = source
        self._put = put
        self.depth = depth
        self.max_items = max_items
        self._tel = telemetry_recorder or telemetry.get()
        # optional memtrack.MemoryLedger: its "prefetch" account follows the
        # staged-batch bytes (per-batch size x queue occupancy), sized once
        # from the first consumed batch — fit batches are shape-stable
        self._ledger = ledger
        self._batch_bytes: Optional[int] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # _END or _Error once the stream finished. Consumer-thread-confined:
        # the producer never touches it — terminal markers travel through
        # the queue, and __next__ installs them on the consumer side. All
        # producer<->consumer state rides the Queue/Event (no bare shared
        # attrs), which is why check_concurrency needs no waivers here.
        self._terminal: Any = None
        self.wait_ms_total = 0.0
        self.consumed = 0

    # ------------------------------------------------------------------ iterate

    def __iter__(self) -> "DevicePrefetcher":
        return self

    @property
    def started(self) -> bool:
        return self._thread is not None

    def _start(self) -> None:
        if self._thread is not None:
            return
        # lazy start: skip() before the first __next__ still sees the source
        # untouched, so the resume fast-forward never races the producer
        self._thread = threading.Thread(
            target=_prefetch_loop,
            args=(weakref.ref(self),),
            name="maggy-device-prefetch",
            daemon=True,
        )
        self._thread.start()

    def __next__(self):
        if self._terminal is not None:
            if isinstance(self._terminal, _Error):
                raise self._terminal.exc
            raise StopIteration
        self._start()
        self._tel.gauge("prefetch_depth", self._queue.qsize())
        t0 = time.perf_counter()
        item = self._queue.get()
        wait_ms = (time.perf_counter() - t0) * 1e3
        self.wait_ms_total += wait_ms
        self._tel.gauge("input_wait_ms", wait_ms)
        if item is _END:
            self._terminal = item
            raise StopIteration
        if isinstance(item, _Error):
            self._terminal = item
            raise item.exc
        self.consumed += 1
        if self._ledger is not None:
            if self._batch_bytes is None:
                from maggy_tpu.telemetry import memtrack

                self._batch_bytes = memtrack.array_bytes(item)
            self._ledger.register(
                "prefetch", self._batch_bytes * (self._queue.qsize() + 1)
            )
        return item

    # -------------------------------------------------------------------- tune

    def set_depth(self, depth: int) -> None:
        """Live-retune the lookahead depth (the autopilot's
        ``train.prefetch_depth`` safe-live knob). Growing takes effect
        immediately — the producer's bounded put wakes and fills the larger
        queue; shrinking applies lazily as the consumer drains below the
        new bound (already-placed batches are never dropped)."""
        depth = int(depth)
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.depth = depth
        q = self._queue
        with q.mutex:
            q.maxsize = depth
            q.not_full.notify_all()

    # -------------------------------------------------------------------- skip

    def skip(self, n: int) -> int:
        """Fast-forward by ``n`` batches. Before the first ``__next__`` this
        delegates to the source's own ``skip`` (no materialization); after
        the pipeline started it drains already-placed batches."""
        if n <= 0:
            return 0
        if self._thread is None:
            return skip_batches(self._source, n)
        skipped = 0
        for _ in range(n):
            try:
                next(self)
            except StopIteration:
                break
            skipped += 1
        return skipped

    # ------------------------------------------------------------------- close

    def close(self) -> None:
        """Stop the producer and drop buffered batches. Idempotent."""
        if self._ledger is not None:
            self._ledger.unregister("prefetch")
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _enqueue(ref: "weakref.ref", stop, q, item) -> bool:
    """Blocking bounded put that stays responsive to close() and collection.
    Caller must NOT hold a strong prefetcher ref across this call."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            if ref() is None:
                return False
    return False


def _prefetch_loop(ref: "weakref.ref") -> None:
    """Producer body; re-derefs the prefetcher each batch so collection
    stops it (same lifecycle idiom as ``native_loader._producer_loop``)."""
    i = 0
    terminal = _END
    while True:
        pf = ref()
        if pf is None or pf._stop.is_set():
            return
        if pf.max_items is not None and i >= pf.max_items:
            break
        try:
            batch = next(pf._source)
            with pf._tel.span("shard_batch", step=i):
                item = pf._put(batch)
        except StopIteration:
            break
        except BaseException as e:  # noqa: BLE001 - relayed to the consumer
            terminal = _Error(e)
            break
        stop, q = pf._stop, pf._queue
        del pf  # no strong ref while blocked on the bounded queue
        if not _enqueue(ref, stop, q, item):
            return
        i += 1
    pf = ref()
    if pf is not None:
        stop, q = pf._stop, pf._queue
        del pf
        _enqueue(ref, stop, q, terminal)
