"""Sharded trainer: the TPU-native distributed-training engine.

This replaces the reference's entire L1/L2 distributed-training machinery — DDP
/ FairScale / DeepSpeed wrapping (core/patching/modules.py:38-139), the 11 ZeRO
optimizer monkey-patches (core/patching/optim.py:28-117) and the NCCL bootstrap
(core/executors/torch_dist_executor.py:121-285) — with one functional pipeline:

    mesh = make_mesh(spec)                  # ShardingSpec: dp/fsdp/tp/sp/ep
    trainer = Trainer(model, optax.adamw(...), mesh)
    state  = trainer.make_state(rng, sample_batch)   # params born sharded
    state, metrics = trainer.step(state, batch)      # pjit'd, donated, bf16

Parameter/optimizer-state sharding (ZeRO-1/2/3 ≈ fsdp axis) is purely a
placement decision: optax state mirrors the param tree, so the same logical
axis rules shard both, and XLA inserts the all-gathers/reduce-scatters that
DeepSpeed implements by hand. There is nothing to monkey-patch — distribution
transparency comes from what we inject (a mesh-aware context), not from
patching engine classes (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from maggy_tpu.parallel import sharding as shd
from maggy_tpu.parallel.spec import ShardingSpec


class TrainState(train_state.TrainState):
    """flax TrainState; params may carry nn.Partitioned boxes (flax unboxes on
    apply, optax maps through them), so sharding metadata survives the whole
    update loop."""


def lm_loss_fn(logits: jax.Array, batch: Dict[str, jax.Array]) -> jax.Array:
    """Next-token cross entropy over ``batch["tokens"]`` with optional
    ``batch["loss_mask"]``."""
    tokens = batch["tokens"]
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = batch.get("loss_mask")
    if mask is not None:
        mask = mask[:, 1:].astype(jnp.float32)
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return -ll.mean()


def classification_loss_fn(logits: jax.Array, batch: Dict[str, jax.Array]) -> jax.Array:
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()


def _model_inputs(batch: Dict[str, jax.Array]) -> Tuple:
    if "tokens" in batch:
        return (batch["tokens"],)
    if "inputs" in batch:
        return (batch["inputs"],)
    raise KeyError("Batch must contain 'tokens' (LM) or 'inputs' (generic)")


@dataclasses.dataclass
class Trainer:
    """Builds sharded state + compiled train/eval steps for a flax model."""

    model: Any
    optimizer: optax.GradientTransformation
    mesh: Any
    loss_fn: Callable = lm_loss_fn
    rules: Tuple = shd.DEFAULT_RULES
    rngs_in_apply: bool = False

    def __post_init__(self):
        self._train_step = None
        self._eval_step = None
        self._eval_loss_step = None
        self.state_shardings = None

    # ------------------------------------------------------------------ state

    def make_state(self, rng: jax.Array, sample_batch: Dict[str, Any]) -> TrainState:
        """Initialize a TrainState with every leaf born on its target devices
        (jit + out_shardings — no host-side full materialization)."""
        inputs = _model_inputs(sample_batch)

        def init_fn(rng, *ins):
            variables = self.model.init(rng, *ins)
            return TrainState.create(
                apply_fn=self.model.apply, params=variables["params"], tx=self.optimizer
            )

        abstract = jax.eval_shape(init_fn, rng, *inputs)
        self.state_shardings = shd.params_shardings(self.mesh, abstract, self.rules)
        init = jax.jit(init_fn, out_shardings=self.state_shardings)
        import numpy as np

        # np (not jnp): host values enter a multi-process jit as replicated
        # inputs instead of arrays committed to one process's local device
        with self.mesh:
            return init(rng, *jax.tree.map(np.asarray, inputs))

    def batch_shardings(self, batch):
        return jax.tree.map(lambda _: shd.batch_sharding(self.mesh, self.rules), batch)

    def shard_batch(self, batch, *, local: bool = False):
        """Place a host batch onto the mesh, batch axis over (data, fsdp).

        Single-process: a plain sharded device_put. Multi-process (global
        mesh formed via ``initialize_data_plane``): every process passes the
        same *global* batch and this slices out its own rows before assembly
        — so train_fns stay oblivious to the process topology. A loader that
        already rank-shards its stream (petastorm semantics — reference
        dataloader.py:116-131) passes ``local=True`` to skip the slicing.
        """
        shardings = self.batch_shardings(batch)
        if jax.process_count() == 1:
            return jax.device_put(batch, shardings)
        import numpy as np

        pid, n = jax.process_index(), jax.process_count()

        def put(x, s):
            x = np.asarray(x)
            if not local:
                if x.shape[0] % n:
                    raise ValueError(
                        f"Global batch dim {x.shape[0]} not divisible by "
                        f"{n} processes"
                    )
                per = x.shape[0] // n
                x = x[pid * per : (pid + 1) * per]
            return jax.make_array_from_process_local_data(s, x)

        return jax.tree.map(put, batch, shardings)

    # ------------------------------------------------------------------ steps

    def _build_train_step(self):
        def train_step(state: TrainState, batch):
            def loss_of(params):
                # mutable intermediates so modules can sow auxiliary losses
                # (MoE router balancing); "*aux_loss" leaves are added to the
                # objective — without this, flax `sow` is a silent no-op
                logits, mods = state.apply_fn(
                    {"params": params}, *_model_inputs(batch), mutable=["intermediates"]
                )
                loss = self.loss_fn(logits, batch)
                aux = 0.0
                for path, leaf in jax.tree_util.tree_flatten_with_path(
                    mods.get("intermediates", {})
                )[0]:
                    if "aux_loss" in jax.tree_util.keystr(path):
                        aux = aux + jnp.sum(leaf)
                return loss + aux, (loss, aux)

            (total, (loss, aux)), grads = jax.value_and_grad(loss_of, has_aux=True)(
                state.params
            )
            new_state = state.apply_gradients(grads=grads)
            gnorm = optax.global_norm(grads)
            return new_state, {
                "loss": loss,
                "aux_loss": aux,
                "total_loss": total,
                "grad_norm": gnorm,
                "step": state.step,
            }

        return jax.jit(train_step, donate_argnums=(0,))

    def step(self, state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
        if self._train_step is None:
            self._train_step = self._build_train_step()
        with self.mesh:
            return self._train_step(state, batch)

    def eval_logits(self, state: TrainState, batch):
        if self._eval_step is None:
            def eval_step(state, batch):
                return state.apply_fn({"params": state.params}, *_model_inputs(batch))

            self._eval_step = jax.jit(eval_step)
        with self.mesh:
            return self._eval_step(state, batch)

    def evaluate(self, state: TrainState, data_iter, num_batches: int) -> Dict[str, float]:
        """Mean loss over ``num_batches`` held-out batches (no state update).
        The loss is computed inside jit so full logits never leave the device."""
        if num_batches < 1:
            raise ValueError("evaluate needs num_batches >= 1")
        if self._eval_loss_step is None:
            def eval_loss(state, batch):
                logits = state.apply_fn({"params": state.params}, *_model_inputs(batch))
                return self.loss_fn(logits, batch)

            self._eval_loss_step = jax.jit(eval_loss)
        losses = []
        with self.mesh:
            for _ in range(num_batches):
                batch = self.shard_batch(next(data_iter))
                losses.append(self._eval_loss_step(state, batch))
        return {"loss": float(sum(float(l) for l in losses) / num_batches)}

    def fit(
        self,
        state: TrainState,
        data_iter,
        num_steps: int,
        reporter=None,
        report_every: int = 10,
        metric_key: str = "loss",
        metric_sign: float = 1.0,
        checkpointer=None,
        checkpoint_every: int = 0,
        profile_dir: Optional[str] = None,
        profile_steps: Tuple[int, int] = (3, 6),
    ) -> Tuple[TrainState, Dict[str, float]]:
        """Simple host-side loop: shard batch → step → optional reporter
        broadcast at step boundaries (where EarlyStopException can interrupt —
        SURVEY.md §7 'host-callback polling at step boundaries').

        ``profile_dir`` captures a JAX/XLA profiler trace over
        ``profile_steps=(start, stop)`` (reference has no tracer, §5.1);
        ``checkpointer`` + ``checkpoint_every`` save the state periodically.

        Reported values are ``metric_sign * metrics[metric_key]``. Broadcast
        values MUST be the same quantity and orientation as the train_fn's
        returned optimization metric — the driver's early stopping and trial
        ranking compare the two directly. When the experiment runs with
        ``direction="max"`` and the train_fn returns ``-loss``, pass
        ``metric_sign=-1.0`` so live broadcasts match; there is no implicit
        negation.
        """
        metrics = {}
        profiling = False
        prof_start = min(profile_steps[0], max(0, num_steps - 2))
        prof_stop = min(profile_steps[1], num_steps - 1)
        try:
            for i in range(num_steps):
                if profile_dir is not None and not profiling and i == prof_start:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                batch = next(data_iter)
                state, metrics = self.step(state, self.shard_batch(batch))
                if profiling and i >= prof_stop:
                    jax.block_until_ready(metrics)
                    jax.profiler.stop_trace()
                    profiling = False
                    profile_dir = None  # one capture per fit
                if reporter is not None and (i + 1) % report_every == 0:
                    value = metric_sign * float(metrics[metric_key])
                    reporter.broadcast(value, step=int(state.step))
                if checkpointer is not None and checkpoint_every and (
                    (i + 1) % checkpoint_every == 0
                ):
                    checkpointer.save(int(state.step), state)
        finally:
            if profiling:  # loop ended/raised while a trace was active
                jax.profiler.stop_trace()
        return state, {k: float(v) for k, v in metrics.items()}


@dataclasses.dataclass
class TrainContext:
    """What the distributed executor injects into an oblivious train_fn.

    The train_fn can stay framework-high-level (use ``ctx.trainer(...)``) or go
    low-level (use ``ctx.mesh`` + ``ctx.shard`` directly with its own pjit).
    """

    mesh: Any
    spec: ShardingSpec
    process_index: int = 0
    num_processes: int = 1
    rules: Tuple = shd.DEFAULT_RULES
    # "chief" (worker 0) / "worker" / "evaluator" — the reference's TF role
    # assignment (tf_dist_executor.py:138-144); an evaluator is outside the
    # training group and should evaluate checkpoints instead of training
    role: str = "worker"

    @classmethod
    def create(cls, spec_or_preset="fsdp", devices=None, role="worker") -> "TrainContext":
        import jax as _jax

        from maggy_tpu import util
        from maggy_tpu.parallel.mesh import mesh_for

        # one XLA compile per geometry across trials/instances/processes
        util.enable_compilation_cache()
        mesh, spec = mesh_for(sharding=spec_or_preset, devices=devices)
        return cls(
            mesh=mesh,
            spec=spec,
            process_index=_jax.process_index(),
            num_processes=_jax.process_count(),
            role=role,
        )

    def trainer(self, model, optimizer, loss_fn: Callable = lm_loss_fn) -> Trainer:
        return Trainer(model, optimizer, self.mesh, loss_fn=loss_fn, rules=self.rules)

    def shard(self, tree, logical_axes=("batch",)):
        target = shd.named_sharding(self.mesh, logical_axes, self.rules)
        return jax.device_put(tree, target)
