"""Sharded trainer: the TPU-native distributed-training engine.

This replaces the reference's entire L1/L2 distributed-training machinery — DDP
/ FairScale / DeepSpeed wrapping (core/patching/modules.py:38-139), the 11 ZeRO
optimizer monkey-patches (core/patching/optim.py:28-117) and the NCCL bootstrap
(core/executors/torch_dist_executor.py:121-285) — with one functional pipeline:

    mesh = make_mesh(spec)                  # ShardingSpec: dp/fsdp/tp/sp/ep
    trainer = Trainer(model, optax.adamw(...), mesh)
    state  = trainer.make_state(rng, sample_batch)   # params born sharded
    state, metrics = trainer.step(state, batch)      # pjit'd, donated, bf16

Parameter/optimizer-state sharding (ZeRO-1/2/3 ≈ fsdp axis) is purely a
placement decision: optax state mirrors the param tree, so the same logical
axis rules shard both, and XLA inserts the all-gathers/reduce-scatters that
DeepSpeed implements by hand. There is nothing to monkey-patch — distribution
transparency comes from what we inject (a mesh-aware context), not from
patching engine classes (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

from maggy_tpu.parallel import sharding as shd
from maggy_tpu.parallel.spec import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_SLICE,
    AXIS_STAGE,
    AXIS_TENSOR,
    ShardingSpec,
)


class TrainState(train_state.TrainState):
    """flax TrainState; params may carry nn.Partitioned boxes (flax unboxes on
    apply, optax maps through them), so sharding metadata survives the whole
    update loop."""


def _lm_loss_parts(
    logits: jax.Array, batch: Dict[str, jax.Array]
) -> Tuple[jax.Array, jax.Array]:
    """``(masked log-likelihood sum, mask weight)`` for the LM objective —
    the sufficient statistics :func:`lm_loss_fn` normalizes. Split out so the
    bucketed-overlap step can psum the two parts across batch shards and
    reproduce the dense masked mean exactly (sum-of-sums / sum-of-weights),
    instead of averaging per-shard means whose denominators differ."""
    tokens = batch["tokens"]
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = batch.get("loss_mask")
    mask = None if mask is None else mask[:, 1:].astype(jnp.float32)
    seg = batch.get("segment_ids")
    if seg is not None:
        same = (seg[:, 1:] == seg[:, :-1]).astype(jnp.float32)
        mask = same if mask is None else mask * same
    if mask is None:
        return ll.sum(), jnp.float32(ll.size)
    return (ll * mask).sum(), mask.sum()


def lm_loss_fn(logits: jax.Array, batch: Dict[str, jax.Array]) -> jax.Array:
    """Next-token cross entropy over ``batch["tokens"]`` with optional
    ``batch["loss_mask"]``. With ``batch["segment_ids"]`` (packed sequences)
    the boundary positions — where the target token belongs to a different
    segment than its predictor — are masked out automatically."""
    ll_sum, weight = _lm_loss_parts(logits, batch)
    return -ll_sum / jnp.maximum(weight, 1.0)


def classification_loss_fn(logits: jax.Array, batch: Dict[str, jax.Array]) -> jax.Array:
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()


def collect_aux_losses(mods) -> jax.Array:
    """Sum every ``*aux_loss`` intermediate a model sowed (MoE router
    balancing). THE one matching rule — the dense train step, the pipeline
    stage adapter, and tests all collect through here, so models that sow
    and trainers that collect cannot silently desync."""
    aux = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        mods.get("intermediates", {})
    )[0]:
        if "aux_loss" in jax.tree_util.keystr(path):
            aux = aux + jnp.sum(leaf).astype(jnp.float32)
    return aux


def _prefetch_depth(prefetch: Optional[int]) -> int:
    """Resolve an input-prefetch depth: an explicit argument wins, else the
    ``MAGGY_TPU_PREFETCH`` env knob, else 2 (double-buffered). 0 disables."""
    if prefetch is not None:
        return max(0, int(prefetch))
    try:
        return max(0, int(os.environ.get("MAGGY_TPU_PREFETCH", "2")))
    except ValueError:
        return 2


def _model_inputs(batch: Dict[str, jax.Array]) -> Tuple:
    if "tokens" in batch:
        args = [batch["tokens"]]
        # packed sequences: optional positions (restarting per segment) and
        # segment_ids ride through to the model's extra positional args
        if "positions" in batch or "segment_ids" in batch:
            args.append(batch.get("positions"))
            if "segment_ids" in batch:
                args.append(batch["segment_ids"])
        return tuple(args)
    if "inputs" in batch:
        return (batch["inputs"],)
    raise KeyError("Batch must contain 'tokens' (LM) or 'inputs' (generic)")


class _FitAutopilotTarget:
    """In-loop knob holder for ``fit``'s autopilot controller (push-mode
    target: the loop feeds per-step samples, and safe-live moves land on
    the live prefetcher / metrics window immediately)."""

    scope = "train"
    guard_metric = "steps_per_sec"

    def __init__(self, prefetcher, metrics_window: int, trainer=None):
        self.prefetcher = prefetcher
        self.metrics_window = int(metrics_window)
        self.trainer = trainer

    def sample(self):  # push-mode: the loop observes directly
        return {}

    def pending(self) -> bool:
        return False

    def current(self):
        cur = {"train.metrics_window": self.metrics_window}
        if self.prefetcher is not None:
            cur["train.prefetch_depth"] = self.prefetcher.depth
        if self.trainer is not None:
            # startup knobs: the planner proposes them for the NEXT run
            # (memory-bound playbook raises zero_stage before shrinking
            # batch); apply() rightly has no live handler for them
            cur["train.zero_stage"] = int(self.trainer.zero_stage)
            if self.trainer.bucket_mb is not None:
                cur["train.bucket_mb"] = float(self.trainer.bucket_mb)
        return cur

    def apply(self, knob, value) -> bool:
        if knob == "train.prefetch_depth" and self.prefetcher is not None:
            self.prefetcher.set_depth(int(value))
            return True
        if knob == "train.metrics_window":
            self.metrics_window = max(0, int(value))
            return True
        return False


@dataclasses.dataclass
class Trainer:
    """Builds sharded state + compiled train/eval steps for a flax model."""

    model: Any
    optimizer: optax.GradientTransformation
    mesh: Any
    loss_fn: Callable = lm_loss_fn
    rules: Tuple = shd.DEFAULT_RULES
    rngs_in_apply: bool = False
    # pipeline parallelism: microbatches per step when the mesh has a stage
    # axis > 1 (defaults to 2*pp — enough to amortize the 1F1B bubble while
    # staying valid for small test batches); must divide the batch size
    n_microbatches: Optional[int] = None
    # elastic membership (docs/resilience.md): a MembershipMonitor injected
    # by the distributed executor. fit polls it at step boundaries — a
    # pending epoch (or a chaos slice_drop/slice_rejoin) interrupts the loop
    # with a membership exception the executor's reshape loop catches
    membership: Optional[Any] = None
    # device-side comm/compute overlap (docs/distributed.md "Gradient
    # overlap & ZeRO"): zero_stage=1 shards optimizer state over the data
    # axis (each rank updates its shard, then all-gathers params);
    # bucket_mb bounds the gradient-reduction bucket size in MiB so
    # per-bucket collectives overlap the remaining backward. Defaults keep
    # the dense step bit-for-bit. Only pure data/slice meshes are eligible —
    # anything else warns once and stays dense (see _overlap_mode)
    zero_stage: int = 0
    bucket_mb: Optional[float] = None

    def __post_init__(self):
        self._train_step = None
        # trace-time compile counter: the jitted step bodies bump this as a
        # Python side effect, so it counts XLA traces, not calls (the same
        # contract as Engine._decode_traces). _expect_recompile marks a
        # deliberate (re)build so the recompile sentinel stays quiet for it.
        self._step_traces = 0
        self._expect_recompile = False
        self._eval_step = None
        self._eval_loss_step = None
        self.state_shardings = None
        self._pp_parts = None
        self._pp_built_micro = None
        # (shape key, shardings) memo so the per-step hot path never
        # recomputes the batch sharding tree — the spec plumbing runs once
        self._batch_shardings_memo = None
        self._overlap_memo = None  # resolved (mode, manual axes, zero shards)
        if self.zero_stage not in (0, 1):
            raise ValueError(
                f"Trainer.zero_stage must be 0 or 1, got {self.zero_stage!r}"
            )
        if self.bucket_mb is not None and not float(self.bucket_mb) > 0:
            raise ValueError(
                f"Trainer.bucket_mb must be positive (or None), got "
                f"{self.bucket_mb!r}"
            )

    # ---------------------------------------------------------------- pipeline

    @property
    def pp(self) -> int:
        """Pipeline stages = the mesh's ``stage`` axis extent (1 = off)."""
        return dict(self.mesh.shape).get(AXIS_STAGE, 1)

    def _pipeline_parts(self):
        if self._pp_parts is None:
            from maggy_tpu.train.pipeline_adapter import decoder_pipeline_parts

            shape = dict(self.mesh.shape)
            if shape.get(AXIS_SEQ, 1) > 1:
                raise ValueError(
                    "pp>1 does not compose with sp>1: the 1F1B schedule runs "
                    "each stage op under a lax.cond whose predicate varies "
                    "per stage, and a seq-ring collective inside a "
                    "non-uniform cond deadlocks (verified on the CPU mesh). "
                    "Use pp x tp / pp x ep / pp x dp/fsdp, or sp without pp."
                )
            # pp composes with dp/fsdp (manual in the pipeline shard_maps)
            # and with tp/ep: tensor/expert dims of the stage params stay
            # GSPMD-managed, resolved from the model's own logical axes in
            # state_shardings_for
            self._pp_parts = decoder_pipeline_parts(
                self.model, self.pp, tp=shape.get(AXIS_TENSOR, 1),
                mesh=self.mesh, ep=shape.get(AXIS_EXPERT, 1),
            )
        return self._pp_parts

    # ---------------------------------------------------------------- overlap

    def _bucket_mb_eff(self) -> Optional[float]:
        """bucket_mb normalized: None/inf (one bucket per dtype) -> None."""
        if self.bucket_mb is None or not math.isfinite(float(self.bucket_mb)):
            return None
        return float(self.bucket_mb)

    def _overlap_mode(self) -> Tuple[str, Tuple[str, ...], int]:
        """Resolve (once per trainer) which step the config gets:
        ``("off"|"bucket"|"zero", manual batch axes, zero shard count)``.

        ``zero_stage``/``bucket_mb`` request the bucketed-overlap step
        (parallel/overlap.py), which runs the model under a manual
        shard_map over (slice, data). Ineligible configurations — pipeline
        meshes, meshes with non-trivial GSPMD-auto axes (this XLA's SPMD
        partitioner aborts on manual subgroups mixed with auto param
        sharding; under fsdp the optimizer state is sharded by the rule
        table already), or no batch axis to reduce over — warn once and
        fall back to the dense path, so a knob sweep never hard-fails on
        geometry."""
        if self._overlap_memo is not None:
            return self._overlap_memo
        off = ("off", (), 1)
        requested = self.zero_stage > 0 or self._bucket_mb_eff() is not None
        if not requested:
            self._overlap_memo = off
            return off
        from maggy_tpu.train.pipeline_adapter import warn_overlap_unbucketed

        shape = dict(self.mesh.shape)
        manual = tuple(
            a for a in (AXIS_SLICE, AXIS_DATA) if shape.get(a, 1) > 1
        )
        blockers = sorted(
            a
            for a in (AXIS_FSDP, AXIS_TENSOR, AXIS_SEQ, AXIS_EXPERT)
            if shape.get(a, 1) > 1
        )
        mode = off
        if self.pp > 1:
            warn_overlap_unbucketed(
                f"pipeline mesh (stage={self.pp}): per-stage bucketing is "
                "not implemented, the 1F1B schedule keeps its own collectives"
            )
        elif blockers:
            warn_overlap_unbucketed(
                f"mesh axes {blockers} are GSPMD-auto; the overlap step "
                "needs a pure data/slice mesh (fsdp already shards "
                "optimizer state by the rule table)"
            )
        elif not manual:
            warn_overlap_unbucketed("no data/slice mesh axis > 1 to reduce over")
        else:
            dz = shape.get(AXIS_DATA, 1) if self.zero_stage > 0 else 1
            if self.zero_stage > 0 and dz == 1:
                warnings.warn(
                    "zero_stage=1 needs a data-axis extent > 1; optimizer "
                    "states stay replicated (effective zero_stage=0)",
                    stacklevel=3,
                )
                dz = 1
            mode = ("zero" if dz > 1 else "bucket", manual, dz)
        self._overlap_memo = mode
        return mode

    def _build_overlap_train_step(
        self, mode: str, manual: Tuple[str, ...], dz: int,
        comm_axes: Optional[Tuple[str, ...]] = None, donate: bool = True,
    ):
        """The bucketed-collective train step (docs/distributed.md "Gradient
        overlap & ZeRO").

        The whole step runs under a *manual* shard_map over the batch axes,
        so the gradient reduction is spelled per bucket, per mesh axis —
        intra-slice ``data`` (ICI) first, cross-slice ``slice`` (DCN)
        second — in reverse-topological bucket order. Each bucket's
        collective depends only on its own grads, which is what lets XLA's
        latency-hiding scheduler start it while the rest of backward runs
        (``overlap.latency_hiding_flags`` on real TPU backends). Under
        ``mode="zero"`` the data-axis reduction is a reduce-scatter, the
        optimizer update touches only the local shard (the optax state IS
        the flat shard layout — see ``_init_fn``), and an all-gather
        rebuilds the params; optimizer memory per device drops ~1/dz.

        ``comm_axes`` (bench comm-probe only, bucket mode) restricts which
        axes actually reduce — () strips every collective to time pure
        compute; the resulting numerics are wrong on purpose.
        """
        from jax.sharding import PartitionSpec as P

        from maggy_tpu import telemetry
        from maggy_tpu.parallel import overlap
        from maggy_tpu.util import shard_map as _shard_map

        axes_comm = tuple(manual if comm_axes is None else comm_axes)
        assert all(a in manual for a in axes_comm)
        assert mode in ("bucket", "zero") and (mode != "zero" or dz > 1)
        if mode == "zero" and comm_axes is not None:
            raise ValueError("comm-probe variants are bucket-mode only")
        mesh_shape = dict(self.mesh.shape)
        n_manual = 1
        for a in manual:
            n_manual *= mesh_shape[a]
        is_lm = self.loss_fn is lm_loss_fn
        bucket_mb = self._bucket_mb_eff()
        tel = telemetry.get()

        def local_objective(params, batch):
            # per-device objective chosen so psum over the manual axes
            # reproduces the dense objective exactly: LM losses contribute
            # sum/weight parts (global masked mean), generic losses the
            # mean-of-shards (exact for uniform means), aux terms the
            # mean-of-shards (router losses are per-token means)
            logits, mods = self.model.apply(
                {"params": params}, *_model_inputs(batch),
                mutable=["intermediates"],
            )
            aux_dev = collect_aux_losses(mods) / n_manual
            if is_lm:
                ll_sum, weight = _lm_loss_parts(logits, batch)
                w_global = jax.lax.psum(weight, manual)
                data_dev = -ll_sum / jnp.maximum(w_global, 1.0)
            else:
                data_dev = self.loss_fn(logits, batch) / n_manual
            return data_dev + aux_dev, (data_dev, aux_dev)

        def reduce_bucket(vec, scatter: bool):
            # ICI before DCN: the fast intra-slice hop issues first so the
            # slow cross-slice all-reduce overlaps it (and later buckets'
            # backward) independently
            if AXIS_DATA in axes_comm:
                if scatter:
                    vec = jax.lax.psum_scatter(vec, AXIS_DATA, tiled=True)
                elif AXIS_DATA in manual:
                    vec = jax.lax.psum(vec, AXIS_DATA)
            if AXIS_SLICE in axes_comm:
                vec = jax.lax.psum(vec, AXIS_SLICE)
            return vec

        def train_step(state: TrainState, batch):
            self._step_traces += 1  # trace-time: counts compiles, not calls
            # plan from traced shapes: static at trace time, rebuilt free on
            # recompile, never stored host-side
            plan = overlap.plan_buckets(state.params, bucket_mb, pad_to=dz)
            tel.gauge("train.bucket_count", len(plan.buckets))

            def body_bucket(params, batch):
                (_, (data_dev, aux_dev)), grads = jax.value_and_grad(
                    local_objective, has_aux=True
                )(params, batch)
                flats = overlap.flatten_buckets(grads, plan)
                flats = {
                    name: reduce_bucket(vec, scatter=False)
                    for name, vec in flats.items()
                }
                grads = overlap.unflatten_buckets(flats, plan, grads)
                loss = jax.lax.psum(data_dev, manual)
                aux = jax.lax.psum(aux_dev, manual)
                return grads, (loss, aux)

            def body_zero(params, opt_state, batch):
                (_, (data_dev, aux_dev)), grads = jax.value_and_grad(
                    local_objective, has_aux=True
                )(params, batch)
                gflats = overlap.flatten_buckets(grads, plan)
                gshards = {
                    name: reduce_bucket(vec, scatter=True)
                    for name, vec in gflats.items()
                }
                # each rank owns one 1/dz shard of every flat bucket; the
                # optimizer update below runs on shards only
                idx = jax.lax.axis_index(AXIS_DATA)
                pflats = overlap.flatten_buckets(params, plan)
                pshards = {
                    name: jax.lax.dynamic_slice_in_dim(
                        vec, idx * (vec.shape[0] // dz), vec.shape[0] // dz
                    )
                    for name, vec in pflats.items()
                }
                updates, new_opt = self.optimizer.update(
                    gshards, opt_state, pshards
                )
                new_shards = optax.apply_updates(pshards, updates)
                new_flats = {
                    name: jax.lax.all_gather(v, AXIS_DATA, tiled=True)
                    for name, v in new_shards.items()
                }
                new_params = overlap.unflatten_buckets(new_flats, plan, params)
                # shards partition the full (slice-reduced) gradient over
                # data, so the global sq-norm is the data-psum of local ones
                gsq = sum(
                    jnp.sum(jnp.square(v.astype(jnp.float32)))
                    for v in gshards.values()
                )
                gnorm = jnp.sqrt(jax.lax.psum(gsq, AXIS_DATA))
                loss = jax.lax.psum(data_dev, manual)
                aux = jax.lax.psum(aux_dev, manual)
                return new_params, new_opt, (loss, aux, gnorm)

            batch_spec = P(manual)
            if mode == "zero":
                padded = plan.padded_sizes
                opt_spec = jax.tree.map(
                    lambda l: P(AXIS_DATA)
                    if getattr(l, "ndim", 0) == 1 and l.shape[0] in padded
                    else P(),
                    state.opt_state,
                )
                fn = _shard_map(
                    body_zero,
                    mesh=self.mesh,
                    in_specs=(P(), opt_spec, batch_spec),
                    out_specs=(P(), opt_spec, P()),
                    check_vma=False,
                    axis_names=frozenset(manual),
                )
                new_params, new_opt, (loss, aux, gnorm) = fn(
                    state.params, state.opt_state, batch
                )
                new_state = state.replace(
                    step=state.step + 1, params=new_params, opt_state=new_opt
                )
            else:
                fn = _shard_map(
                    body_bucket,
                    mesh=self.mesh,
                    in_specs=(P(), batch_spec),
                    out_specs=(P(), P()),
                    check_vma=False,
                    axis_names=frozenset(manual),
                )
                grads, (loss, aux) = fn(state.params, batch)
                gnorm = optax.global_norm(grads)
                new_state = state.apply_gradients(grads=grads)
            return new_state, {
                "loss": loss,
                "aux_loss": aux,
                "total_loss": loss + aux,
                "grad_norm": gnorm,
                "step": state.step,
            }

        return jax.jit(train_step, donate_argnums=(0,) if donate else ())

    def overlap_step_variant(
        self, comm_axes: Optional[Tuple[str, ...]] = None, donate: bool = True
    ):
        """A compiled bucketed step reducing only over ``comm_axes`` — the
        bench's comm-probe (``()`` strips all collectives to time pure
        compute). Timing-only: skipped reductions make the numerics wrong
        on purpose. Requires an eligible bucket-mode (zero_stage=0)
        trainer."""
        mode, manual, dz = self._overlap_mode()
        if mode != "bucket":
            raise ValueError(
                "overlap_step_variant needs an overlap-eligible "
                f"zero_stage=0 trainer (resolved mode: {mode!r})"
            )
        return self._build_overlap_train_step(
            mode, manual, dz, comm_axes=comm_axes, donate=donate
        )

    # ------------------------------------------------------------------ state

    def _init_fn(self) -> Callable:
        if self.pp > 1:
            parts = self._pipeline_parts()

            def init_fn(rng, *ins):
                variables = self.model.init(rng, *ins)
                stage_params = parts.restack(shd.unbox(variables["params"]))
                return TrainState.create(
                    apply_fn=self.model.apply, params=stage_params, tx=self.optimizer
                )
        elif self._overlap_mode()[0] == "zero":
            bucket_mb = self._bucket_mb_eff()
            dz = self._overlap_mode()[2]

            def init_fn(rng, *ins):
                from maggy_tpu.parallel import overlap

                variables = self.model.init(rng, *ins)
                st = TrainState.create(
                    apply_fn=self.model.apply, params=variables["params"],
                    tx=self.optimizer,
                )
                # ZeRO-1: the optax state mirrors the FLAT bucket vectors
                # (the layout the sharded update consumes), not the param
                # tree — state_shardings_for places them P(data)
                plan = overlap.plan_buckets(st.params, bucket_mb, pad_to=dz)
                return st.replace(
                    opt_state=self.optimizer.init(
                        overlap.flatten_buckets(st.params, plan)
                    )
                )
        else:
            def init_fn(rng, *ins):
                variables = self.model.init(rng, *ins)
                return TrainState.create(
                    apply_fn=self.model.apply, params=variables["params"],
                    tx=self.optimizer,
                )

        return init_fn

    def state_shardings_for(self, sample_batch: Dict[str, Any], rng=None):
        """Compute (and cache) every TrainState leaf's NamedSharding from
        shapes alone — no allocation, no compile. ``make_state`` routes
        through this; it also serves placing foreign states (restored
        checkpoints, possibly re-staged across pp degrees) without a
        throwaway init — see :meth:`adopt_state`."""
        if rng is None:
            rng = jax.random.key(0)  # shapes only; the key value is irrelevant
        inputs = _model_inputs(sample_batch)
        abstract = jax.eval_shape(self._init_fn(), rng, *inputs)
        if self.pp > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            parts = self._pipeline_parts()
            n_stages = parts.n_stages
            mesh_shape = dict(self.mesh.shape)
            # the pipeline shard_maps leave tensor AND expert in GSPMD-auto
            # mode (parallel/pipeline.py _manual_axes), so those two — and
            # only those — may shard stage-param dims (pp x tp, pp x ep)
            auto_axes = {
                a: mesh_shape.get(a, 1) for a in (AXIS_TENSOR, AXIS_EXPERT)
            }

            def tensor_dims(names, shape):
                """Mesh axes for a stage leaf's trailing dims: only the
                GSPMD-auto axes are applied — an fsdp/seq rule resolution
                would contradict the pipeline shard_map's manual in_specs
                (params replicated over data/fsdp) and reshard every step."""
                table = dict(self.rules)
                out = []
                for name, dim in zip(names, shape):
                    ax = table.get(name) if name else None
                    if isinstance(ax, (tuple, list)):
                        # multi-axis rules (e.g. (data, fsdp)) are never
                        # auto axes here; also keeps lists unhashed
                        ax = ax[0] if len(ax) == 1 else None
                    ext = auto_axes.get(ax, 0)
                    out.append(ax if ext > 1 and dim % ext == 0 else None)
                return out

            def shard_of(leaf):
                # every stage-stacked leaf (params and the optax state
                # mirroring them) leads with [n_stages]; the rest (step /
                # adam count) are scalars — leading-dim == pp is exact here
                if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == n_stages:
                    return NamedSharding(self.mesh, P(AXIS_STAGE))
                return NamedSharding(self.mesh, P())

            if parts.stage_names is not None:
                spec_params = jax.tree.map(
                    lambda names, leaf: NamedSharding(
                        self.mesh,
                        P(AXIS_STAGE, *tensor_dims(names[1:], leaf.shape[1:])),
                    ),
                    parts.stage_names,
                    abstract.params,
                    is_leaf=lambda x: isinstance(x, tuple),
                )
                pstruct = jax.tree_util.tree_structure(abstract.params)

                def is_ptree(x):
                    try:
                        return jax.tree_util.tree_structure(x) == pstruct
                    except Exception:
                        return False

                # params and every optax mirror of them (adam mu/nu, ...)
                # get the tensor-resolved specs; loose leaves (step, adam
                # count) fall back to the stage/replicated rule
                self.state_shardings = jax.tree.map(
                    lambda x: spec_params
                    if is_ptree(x)
                    else jax.tree.map(shard_of, x),
                    abstract,
                    is_leaf=is_ptree,
                )
            else:
                self.state_shardings = jax.tree.map(shard_of, abstract)
        else:
            self.state_shardings = shd.params_shardings(
                self.mesh, abstract, self.rules
            )
            mode, _, dz = self._overlap_mode()
            if mode == "zero":
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                from maggy_tpu.parallel import overlap

                # the flat ZeRO bucket vectors (built by _init_fn) live
                # sharded over data; loose leaves (adam count) replicate
                plan = overlap.plan_buckets(
                    abstract.params, self._bucket_mb_eff(), pad_to=dz
                )
                padded = plan.padded_sizes
                self.state_shardings = self.state_shardings.replace(
                    opt_state=jax.tree.map(
                        lambda leaf, cur: NamedSharding(self.mesh, P(AXIS_DATA))
                        if getattr(leaf, "ndim", 0) == 1
                        and leaf.shape[0] in padded
                        else cur,
                        abstract.opt_state,
                        self.state_shardings.opt_state,
                    )
                )
        return self.state_shardings

    def make_state(self, rng: jax.Array, sample_batch: Dict[str, Any]) -> TrainState:
        """Initialize a TrainState with every leaf born on its target devices
        (jit + out_shardings — no host-side full materialization). Under a
        ``stage`` mesh axis > 1 the params are born in the stage-stacked
        pipeline layout (see :mod:`maggy_tpu.train.pipeline_adapter`)."""
        inputs = _model_inputs(sample_batch)
        init = jax.jit(
            self._init_fn(),
            out_shardings=self.state_shardings_for(sample_batch, rng),
        )
        # np (not jnp): host values enter a multi-process jit as replicated
        # inputs instead of arrays committed to one process's local device
        with self.mesh:
            return init(rng, *jax.tree.map(np.asarray, inputs))

    def adopt_state(self, state: TrainState, sample_batch: Dict[str, Any]) -> TrainState:
        """Place a foreign/host TrainState onto THIS trainer's mesh layout —
        e.g. a checkpoint restored elsewhere or re-staged across pp degrees
        via :func:`maggy_tpu.train.pipeline_adapter.convert_pipeline_state`.
        Rebinds apply_fn/optimizer to this trainer's (required for the
        sharding tree's static fields to match) and shards every leaf."""
        shardings = self.state_shardings_for(sample_batch)
        state = state.replace(apply_fn=self.model.apply, tx=self.optimizer)
        with self.mesh:
            return jax.device_put(state, shardings)

    def batch_shardings(self, batch):
        default = shd.batch_sharding(self.mesh, self.rules)
        if not isinstance(batch, dict):
            return jax.tree.map(lambda _: default, batch)
        # packed-sequence side inputs are consumed seq-sharded by the SP
        # attention shard_maps; placing them (batch, seq) up front avoids an
        # XLA full-rematerialization reshard per step. Like params_shardings,
        # degrade to the batch-only placement when the length doesn't divide
        # the seq axis (non-SP attention paths have no divisibility demand).
        # Multi-process meshes place these too: shard_batch slices each
        # process's seq chunk from the sharding's own index map (r5; was a
        # per-step all-gather on the flagship long-context path before).
        seq_keys = ("segment_ids", "positions")
        seq_ext = shd.mesh_extent(
            self.mesh, shd.logical_to_mesh_axes(("activation_seq",), self.rules)[0]
        )
        seq_sharding = shd.named_sharding(
            self.mesh, ("batch", "activation_seq"), self.rules
        )

        def pick(key, leaf):
            if (
                key in seq_keys
                and seq_ext > 1
                and getattr(leaf, "ndim", 0) >= 2
                and leaf.shape[1] % seq_ext == 0
            ):
                return seq_sharding
            return default

        return {
            k: jax.tree.map(lambda leaf, k=k: pick(k, leaf), v)
            for k, v in batch.items()
        }

    def _cached_batch_shardings(self, batch):
        """``batch_shardings`` memoized on the batch's (key, shape, dtype)
        signature — every step of a training run sees the same signature, so
        the sharding tree is computed once instead of per step (the
        shard-spec plumbing the prefetcher keeps off the hot path)."""
        key = None
        if isinstance(batch, dict):
            try:
                key = tuple(
                    sorted(
                        (k, tuple(v.shape), str(v.dtype))
                        for k, v in batch.items()
                    )
                )
            except (AttributeError, TypeError):  # nested/objects: no memo
                key = None
        memo = self._batch_shardings_memo
        if key is not None and memo is not None and memo[0] == key:
            return memo[1]
        shardings = self.batch_shardings(batch)
        if key is not None:
            self._batch_shardings_memo = (key, shardings)
        return shardings

    def shard_batch(self, batch, *, local: bool = False):
        """Place a host batch onto the mesh, batch axis over (data, fsdp).

        Single-process: a plain sharded device_put. Multi-process (global
        mesh formed via ``initialize_data_plane``): every process passes the
        same *global* batch and this slices out its own rows before assembly
        — so train_fns stay oblivious to the process topology. A loader that
        already rank-shards its stream (petastorm semantics — reference
        dataloader.py:116-131) passes ``local=True`` to skip the slicing.
        """
        shardings = self._cached_batch_shardings(batch)
        if jax.process_count() == 1:
            return jax.device_put(batch, shardings)
        import numpy as np

        default = shd.batch_sharding(self.mesh, self.rules)

        def process_block(s, shape):
            """This process's contiguous [start, stop) block per array dim,
            straight from the sharding's own index map — correct for any
            mesh/process layout, including a seq axis that spans processes."""
            idx_map = s.addressable_devices_indices_map(shape)
            block = []
            for d in range(len(shape)):
                starts = [sl[d].start or 0 for sl in idx_map.values()]
                stops = [
                    shape[d] if sl[d].stop is None else sl[d].stop
                    for sl in idx_map.values()
                ]
                block.append(slice(min(starts), max(stops)))
            return tuple(block)

        def put(x, s):
            x = np.asarray(x)
            if local:
                # a rank-sharding loader pre-slices ROWS only; it cannot also
                # slice a process-spanning seq chunk — keep batch placement
                # for inner-sharded leaves
                spec = getattr(s, "spec", ())
                if len(spec) > 1 and any(a is not None for a in spec[1:]):
                    s = default
                return jax.make_array_from_process_local_data(s, x)
            # every process passes the same GLOBAL array; carve out exactly
            # this process's block per the sharding's own index map. This is
            # the general rule the old rows/process_count slicing was a
            # special case of — and unlike it, stays correct when the batch
            # axis does NOT span processes (e.g. an sp-only mesh, where every
            # process must supply the full replicated batch) and when the
            # seq axis DOES (each process carves its seq chunk).
            return jax.make_array_from_process_local_data(
                s, np.ascontiguousarray(x[process_block(s, x.shape)]), x.shape
            )

        return jax.tree.map(put, batch, shardings)

    # ------------------------------------------------------------------ steps

    def _pp_batch_parts(self, batch, parts, n_micro: int, dpf: int):
        """Shared pipeline plumbing for the 1F1B train step AND the
        forward-only eval sweep: microbatch the batch, build the raw channel
        stream (packed side inputs ride as int channels so every stage can
        mask/position its attention), and close over the last-stage loss —
        including the packed/masked rescale that keeps per-microbatch masked
        means equal to the dense global mask-weighted mean.
        Returns ``(raw_microbatches, targets, loss_pp)``."""
        tokens = _model_inputs(batch)[0]
        bsz = tokens.shape[0]
        if bsz % n_micro:
            raise ValueError(
                f"batch size {bsz} not divisible by n_microbatches "
                f"{n_micro}; set Trainer(n_microbatches=...) to a divisor"
            )
        if (bsz // n_micro) % dpf:
            raise ValueError(
                f"each of the {n_micro} microbatches has {bsz // n_micro} "
                f"rows, which must divide the mesh's data x fsdp extent "
                f"({dpf}); grow the batch or lower n_microbatches"
            )

        def split(a):
            return a.reshape((n_micro, bsz // n_micro) + a.shape[1:])

        def eff_mask(b):
            """lm_loss_fn's effective target mask for a (sub)batch:
            loss_mask AND same-segment — must mirror lm_loss_fn exactly
            so the rescale below cancels its local denominator."""
            m = None
            lm = b.get("loss_mask")
            if lm is not None:
                m = lm[:, 1:].astype(jnp.float32)
            sg = b.get("segment_ids")
            if sg is not None:
                same = (sg[:, 1:] == sg[:, :-1]).astype(jnp.float32)
                m = same if m is None else m * same
            return m

        tgts = jax.tree.map(split, batch)
        mask_norm = None
        if self.loss_fn is lm_loss_fn and isinstance(batch, dict):
            m = eff_mask(batch)
            if m is not None:
                # global effective-mask sum, for rescaling per-microbatch
                # masked means back to the dense objective — segment
                # boundaries count too, or microbatches with uneven packing
                # would be mis-weighted
                mask_norm = jnp.maximum(m.sum(), 1.0)

        def loss_pp(stage_params, y, tgt):
            loss = self.loss_fn(parts.head_fn(stage_params, y), tgt)
            if mask_norm is not None:
                local = jnp.maximum(eff_mask(tgt).sum(), 1.0)
                # the schedule divides the psum of these by dpf*n_micro;
                # this rescale makes the total sum(ll*mask)/global_sum
                loss = loss * local * (dpf * n_micro) / mask_norm
            return loss

        if isinstance(batch, dict) and (
            "segment_ids" in batch or "positions" in batch
        ):
            # positions-only batches stack 2 channels — a zeros segment-id
            # channel would needlessly disable the flash kernel's
            # segment_ids-is-None fast path
            positions = batch.get("positions")
            if positions is None:
                positions = jnp.broadcast_to(
                    jnp.arange(tokens.shape[1], dtype=tokens.dtype),
                    tokens.shape,
                )
            channels = [tokens, positions.astype(tokens.dtype)]
            seg = batch.get("segment_ids")
            if seg is not None:
                channels.append(seg.astype(tokens.dtype))
            raw = jnp.stack(channels, axis=-1)
        else:
            raw = tokens
        return split(raw), tgts, loss_pp

    def _build_pp_train_step(self):
        """1F1B pipeline training step (mesh has stage>1): microbatch the
        batch axis, run :func:`pipeline_grads_1f1b` with the Decoder stage
        adapter, apply gradients in the stage-stacked layout.

        Loss semantics: the schedule averages per-microbatch losses. For the
        built-in :func:`lm_loss_fn` with a ``loss_mask`` that would differ
        from the dense path's global mask-weighted mean (sparse microbatches
        would be up-weighted), so that case is rescaled to the exact global
        mean. Custom loss_fns keep plain microbatch-mean averaging.
        """
        from maggy_tpu.parallel.pipeline import pipeline_grads_1f1b

        parts = self._pipeline_parts()
        n_micro = self.n_microbatches or 2 * parts.n_stages
        self._pp_built_micro = n_micro
        shape = dict(self.mesh.shape)
        dpf = shape.get(shd.AXIS_DATA, 1) * shape.get(shd.AXIS_FSDP, 1)

        def train_step(state: TrainState, batch):
            self._step_traces += 1  # trace-time: counts compiles, not calls
            split_raw, tgts, loss_pp = self._pp_batch_parts(
                batch, parts, n_micro, dpf
            )
            out = pipeline_grads_1f1b(
                parts.stage_fn,
                loss_pp,
                state.params,
                split_raw,
                tgts,
                mesh=self.mesh,
                first_fn=parts.first_fn,
                stage_takes_raw=True,
                stage_has_aux=parts.stage_has_aux,
            )
            if parts.stage_has_aux:
                loss, grads, aux = out
            else:
                (loss, grads), aux = out, jnp.zeros((), jnp.float32)
            new_state = state.apply_gradients(grads=grads)
            # same metric semantics as the dense path: loss = data only,
            # aux_loss = router terms, total = optimized objective
            return new_state, {
                "loss": loss,
                "aux_loss": aux,
                "total_loss": loss + aux,
                "grad_norm": optax.global_norm(grads),
                "step": state.step,
            }

        return jax.jit(train_step, donate_argnums=(0,))

    def _build_train_step(self):
        if self.pp > 1:
            self._overlap_mode()  # zero/bucket on a pp mesh: one-time warning
            return self._build_pp_train_step()
        mode, manual, dz = self._overlap_mode()
        if mode != "off":
            return self._build_overlap_train_step(mode, manual, dz)

        def train_step(state: TrainState, batch):
            self._step_traces += 1  # trace-time: counts compiles, not calls

            def loss_of(params):
                # mutable intermediates so modules can sow auxiliary losses
                # (MoE router balancing); "*aux_loss" leaves are added to the
                # objective — without this, flax `sow` is a silent no-op
                logits, mods = state.apply_fn(
                    {"params": params}, *_model_inputs(batch), mutable=["intermediates"]
                )
                loss = self.loss_fn(logits, batch)
                aux = collect_aux_losses(mods)
                return loss + aux, (loss, aux)

            (total, (loss, aux)), grads = jax.value_and_grad(loss_of, has_aux=True)(
                state.params
            )
            new_state = state.apply_gradients(grads=grads)
            gnorm = optax.global_norm(grads)
            return new_state, {
                "loss": loss,
                "aux_loss": aux,
                "total_loss": total,
                "grad_norm": gnorm,
                "step": state.step,
            }

        return jax.jit(train_step, donate_argnums=(0,))

    def checkpoint_meta(self) -> Dict[str, Any]:
        """The active system configuration, recorded by ``Checkpointer.save``
        alongside every state this trainer checkpoints: non-trivial mesh
        axes, microbatch setting, and the model's compute dtype. Restores
        compare it against the live trainer's and warn on mismatch."""
        mesh_axes = {k: v for k, v in dict(self.mesh.shape).items() if v > 1}
        cfg = getattr(self.model, "cfg", None)
        mode, _, dz = self._overlap_mode()
        return {
            "mesh_axes": mesh_axes,
            "num_devices": int(self.mesh.size),
            # world-size provenance: restores compare these against the live
            # topology and warn-and-reshard instead of silently mis-sharding
            # when a checkpoint crosses mesh widths (elastic reshape,
            # pod-size changes)
            "n_processes": int(jax.process_count()),
            "n_microbatches": self.n_microbatches,
            "dtype": str(getattr(cfg, "dtype", None)) if cfg is not None else None,
            # EFFECTIVE ZeRO layout (not the requested knobs): what
            # restore_zero_compat needs to rebuild the saved optimizer-state
            # layout when zero_stage / bucket_mb / data width change between
            # save and restore
            "zero": {
                "stage": 1 if mode == "zero" else 0,
                "bucket_mb": self._bucket_mb_eff() if mode == "zero" else None,
                "shards": dz,
            },
        }

    def _membership_check(self, state, step: int, checkpointer, chaos, tel) -> None:
        """Elastic-membership step-boundary seam (docs/resilience.md).

        Raises one of the membership control-flow exceptions when the mesh
        must reshape; the distributed executor's elastic loop catches them,
        negotiates the new view with the driver, and re-enters the train_fn
        (which resumes from the latest complete checkpoint).

        * A **pending epoch** (another member's event, delivered via the
          heartbeat RESHAPE reply) and a chaos **slice_rejoin** are
          graceful: the current step is checkpointed synchronously first,
          so all members converge on a checkpoint that includes every step
          taken here and nothing re-runs.
        * A chaos **slice_drop** is abrupt — the slice's devices (and any
          state since the last retained checkpoint) are gone, exactly like
          a real preemption, so nothing is saved: the reshaped run falls
          back to the last periodic checkpoint.
        """
        from maggy_tpu.resilience.membership import (
            MembershipChanged,
            SliceLost,
            SliceRejoin,
        )

        mem = self.membership
        event: Optional[BaseException] = None
        pending = mem.pending_epoch()
        if pending is not None:
            event = MembershipChanged(pending)
        elif chaos is not None:
            # sim mode hosts every active slice, so any of them may drop
            # here; a worker-mode process IS one slice and only its own
            # loss can originate locally
            self_slice = getattr(mem, "self_slice", None)
            candidates = mem.active if self_slice is None else (self_slice,)
            dropped = chaos.slice_drop(candidates, step=step)
            if dropped is not None:
                raise SliceLost(dropped, step=step)
            if self_slice is None:
                joined = chaos.slice_rejoin(mem.inactive, step=step)
                if joined is not None:
                    event = SliceRejoin(joined, step=step)
        if event is None:
            return
        if checkpointer is not None:
            # the reshape barrier's convergence point: one synchronous save
            # at the current step (same discipline as the preemption hook)
            checkpointer.save(step, state, meta=self.checkpoint_meta())
            checkpointer.wait()
            tel.count("resilience.reshape_checkpoints")
        raise event

    def step(self, state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
        if (
            self._train_step is not None
            and self.pp > 1
            and (self.n_microbatches or 2 * self.pp) != self._pp_built_micro
        ):
            self._train_step = None  # n_microbatches changed: recompile
        if self._train_step is None:
            self._expect_recompile = True  # deliberate build: sentinel-sanctioned
            self._train_step = self._build_train_step()
        with self.mesh:
            return self._train_step(state, batch)

    @property
    def compile_counts(self) -> Dict[str, int]:
        """Compile count per jitted program (recompile-sentinel input): a
        bump without a preceding deliberate rebuild means XLA silently
        retraced — usually a drifting batch shape."""
        return {"train_step": self._step_traces}

    def eval_logits(self, state: TrainState, batch):
        """Full logits for one batch.

        MEMORY CAVEAT under pp>1: the stage-stacked params are unstacked and
        the whole model runs replicated per device — fine for tests/small
        models, an HBM spike at the scale pipeline parallelism exists for.
        Prefer :meth:`evaluate` there (forward-only pipelined loss, live
        bytes bounded by ~1 stage); full-logit extraction at scale should go
        through a checkpoint into a non-pp serving mesh."""
        if self._eval_step is None:
            if self.pp > 1:
                parts = self._pipeline_parts()

                def eval_step(state, batch):
                    params = parts.unstack(state.params)
                    return self.model.apply({"params": params}, *_model_inputs(batch))
            else:
                def eval_step(state, batch):
                    return state.apply_fn({"params": state.params}, *_model_inputs(batch))

            self._eval_step = jax.jit(eval_step)
        with self.mesh:
            return self._eval_step(state, batch)

    def evaluate(
        self,
        state: TrainState,
        data_iter,
        num_batches: int,
        prefetch: Optional[int] = None,
    ) -> Dict[str, float]:
        """Mean loss over ``num_batches`` held-out batches (no state update).
        The loss is computed inside jit so full logits never leave the
        device. Under pp>1 the loss flows through the pipeline stages
        (forward-only GPipe sweep, VERDICT r4 item 9) — per-device live
        bytes stay bounded by one stage's params + a microbatch activation,
        never the unstacked full model.

        Host overlap (docs/performance.md): input batches flow through a
        :class:`~maggy_tpu.train.prefetch.DevicePrefetcher` (``prefetch``
        batches ahead; ``MAGGY_TPU_PREFETCH`` sets the default, 0 disables)
        capped at ``num_batches`` so the iterator is never over-consumed,
        and the per-batch losses accumulate ON DEVICE — one host sync at
        the end instead of a pipeline drain per batch."""
        if num_batches < 1:
            raise ValueError("evaluate needs num_batches >= 1")
        if self._eval_loss_step is None:
            if self.pp > 1:
                from maggy_tpu.parallel.pipeline import pipeline_forward_loss

                parts = self._pipeline_parts()
                n_micro = self.n_microbatches or 2 * parts.n_stages
                shape = dict(self.mesh.shape)
                dpf = shape.get(shd.AXIS_DATA, 1) * shape.get(shd.AXIS_FSDP, 1)

                def eval_loss(state, batch):
                    split_raw, tgts, loss_pp = self._pp_batch_parts(
                        batch, parts, n_micro, dpf
                    )
                    loss, _aux = pipeline_forward_loss(
                        parts.stage_fn,
                        loss_pp,
                        state.params,
                        split_raw,
                        tgts,
                        mesh=self.mesh,
                        first_fn=parts.first_fn,
                        stage_takes_raw=True,
                        stage_has_aux=parts.stage_has_aux,
                    )
                    return loss
            else:
                def eval_loss(state, batch):
                    logits = state.apply_fn({"params": state.params}, *_model_inputs(batch))
                    return self.loss_fn(logits, batch)

            self._eval_loss_step = jax.jit(eval_loss)
        from maggy_tpu import telemetry
        from maggy_tpu.train.prefetch import DevicePrefetcher

        depth = _prefetch_depth(prefetch)
        prefetcher = (
            DevicePrefetcher(
                data_iter,
                self.shard_batch,
                depth=depth,
                max_items=num_batches,
                telemetry_recorder=telemetry.get(),
            )
            if depth > 0
            else None
        )
        total = None
        try:
            with self.mesh:
                for _ in range(num_batches):
                    if prefetcher is not None:
                        batch = next(prefetcher)
                    else:
                        batch = self.shard_batch(next(data_iter))
                    loss = self._eval_loss_step(state, batch)
                    # accumulate on device: no per-batch float() pipeline
                    # drain — the single conversion below is the only sync
                    total = loss if total is None else total + loss
        finally:
            if prefetcher is not None:
                prefetcher.close()
        return {"loss": float(total) / num_batches}

    def fit(
        self,
        state: TrainState,
        data_iter,
        num_steps: int,
        reporter=None,
        report_every: int = 10,
        metric_key: str = "loss",
        metric_sign: float = 1.0,
        checkpointer=None,
        checkpoint_every: int = 0,
        profile_dir: Optional[str] = None,
        profile_steps: Tuple[int, int] = (3, 6),
        resume: Optional[Any] = None,
        prefetch: Optional[int] = None,
        metrics_window: int = 2,
        autopilot: Optional[Any] = None,
    ) -> Tuple[TrainState, Dict[str, float]]:
        """Simple host-side loop: shard batch → step → optional reporter
        broadcast at step boundaries (where EarlyStopException can interrupt —
        SURVEY.md §7 'host-callback polling at step boundaries').

        ``profile_dir`` captures a JAX/XLA profiler trace over
        ``profile_steps=(start, stop)`` (reference has no tracer, §5.1);
        ``checkpointer`` + ``checkpoint_every`` save the state periodically.

        Resilience (docs/resilience.md): ``resume="auto"`` restores the
        checkpointer's latest retained step over ``state`` (an explicit int
        restores that step) and fast-forwards ``data_iter`` by the number of
        steps already completed, so the loss trajectory continues exactly
        where the interrupted run left off; ``num_steps`` stays the TOTAL
        step budget for the run — only the remainder executes. With no
        checkpoint on disk, ``resume="auto"`` is a fresh run. When a
        checkpointer is present, fit also installs the SIGTERM/preemption
        hook (:mod:`maggy_tpu.resilience.preemption`): on notice it performs
        one final *synchronous* save at the current step and returns early
        with ``metrics["preempted"] = 1.0``.

        Reported values are ``metric_sign * metrics[metric_key]``. Broadcast
        values MUST be the same quantity and orientation as the train_fn's
        returned optimization metric — the driver's early stopping and trial
        ranking compare the two directly. When the experiment runs with
        ``direction="max"`` and the train_fn returns ``-loss``, pass
        ``metric_sign=-1.0`` so live broadcasts match; there is no implicit
        negation.

        Host overlap (docs/performance.md): with ``prefetch > 0`` (default
        2; ``MAGGY_TPU_PREFETCH`` overrides, 0 disables) batches flow
        through a :class:`~maggy_tpu.train.prefetch.DevicePrefetcher` — a
        background thread runs ``shard_batch`` (host gather + H2D transfer)
        ``prefetch`` batches ahead, so the device queue never waits on the
        host input pipeline. Consumption is capped at ``num_steps`` batches,
        so a shared iterator keeps its position across calls; only early
        exits (preemption/early stop) may leave up to ``prefetch`` extra
        batches consumed, and data-wait timing shifts accordingly (a
        preemption notice raised as a loader side effect fires when the
        PREFETCHER pulls that batch, up to ``prefetch`` steps early).

        Lagged metrics drain: reporter broadcasts read the metrics ref that
        just LEFT a ``metrics_window``-deep in-flight window (so the
        ``float()`` touches a value ``metrics_window`` steps old and never
        drains the XLA dispatch pipeline), stamped with the step it was
        measured at. Broadcast values are therefore up to ``metrics_window``
        steps stale and driver-side early stopping fires up to that many
        steps later; ``metrics_window=0`` restores synchronous broadcasts.
        The ``metrics_lag`` gauge records the realized lag.

        Autopilot (docs/autotune.md "Continuous tuning"): ``autopilot=True``
        (or an :class:`~maggy_tpu.autopilot.AutopilotConfig`) attaches an
        online controller that diagnoses each window of steps
        (input/drain/compute-bound), live-retunes the safe knobs — prefetch
        depth, metrics window — behind a measured before/after guard with
        automatic rollback, journals every decision as ``autopilot.*``
        telemetry, and shares committed knobs through the tune cache keyed
        by workload fingerprint.

        Telemetry: each step records a ``train_step`` span plus
        ``step_time_ms`` / ``tokens_per_sec`` / ``mfu_est`` gauges into the
        ambient recorder (:func:`maggy_tpu.telemetry.get`; executors install
        a per-worker one), and the first step — synced once to cover the XLA
        compile — lands in ``compile_time_ms``. The prefetcher adds
        ``input_wait_ms`` (host time blocked waiting for an input batch) and
        ``prefetch_depth`` (queue occupancy) gauges, plus the ``shard_batch``
        spans the synchronous path used to record inline. The returned
        metrics dict always carries the measured ``steps_per_sec``
        regardless of the telemetry flag. Host wall-clock per later step is
        measured without extra device syncs (dispatch overlaps; the device
        queue's backpressure makes the mean converge to true step time).
        """
        from maggy_tpu import telemetry
        from maggy_tpu.resilience import chaos as _chaos
        from maggy_tpu.resilience import preemption as _preemption
        from maggy_tpu.telemetry import flightrec as _flightrec
        from maggy_tpu.telemetry import tracing as _tracing

        tel = telemetry.get()
        resumed_from = None
        skipped = 0
        if resume is not None:
            if checkpointer is None:
                raise ValueError("fit(resume=...) requires a checkpointer")
            target = (
                checkpointer.latest_step() if resume == "auto" else int(resume)
            )
            if target is not None and target > int(state.step):
                from maggy_tpu.train.checkpoint import restore_zero_compat

                start = int(state.step)
                # zero-layout-aware restore: a checkpoint written under a
                # different zero_stage/bucket/data-width gets its optimizer
                # state converted (warn-and-reshard) instead of failing on
                # the flat-vs-dense tree mismatch
                state = restore_zero_compat(
                    checkpointer,
                    state,
                    step=None if resume == "auto" else target,
                    live_meta=self.checkpoint_meta(),
                )
                resumed_from = int(state.step)
                skipped = resumed_from - start
                # fast-forward: the interrupted run consumed one batch per
                # completed step — skip them so the data stream (and the loss
                # trajectory) continues where it left off. Loaders with a
                # skip(n) fast path (batch_iterator, NativeBatchLoader)
                # advance by index; plain generators drain next().
                from maggy_tpu.train.prefetch import skip_batches

                skip_batches(data_iter, skipped)
                tel.count("resilience.auto_resumes")
                tel.gauge("resumed_step", resumed_from)
        # num_steps is the TOTAL budget for this fit call; a resumed fit only
        # executes the remainder
        num_steps = max(0, num_steps - skipped)
        # preemption notice -> one final synchronous save + early return;
        # only armed when there is a checkpointer to save into
        hook = _preemption.install() if checkpointer is not None else None
        chaos = _chaos.get()
        # host-side step base: every in-loop "current step" below derives
        # from this + the loop index, so nothing int()s the device-resident
        # state.step (which would drain the dispatch pipeline)
        step0 = int(state.step)
        preempted = False
        metrics = {}
        profiling = False
        prof_start = min(profile_steps[0], max(0, num_steps - 2))
        prof_stop = min(profile_steps[1], num_steps - 1)
        # capacity ledger (docs/observability.md "Capacity"): the training
        # tier's HBM accounts — params, optimizer state (the ZeRO shards on
        # a sharded mesh), and the prefetcher's staged batches — reconciled
        # against reported device memory on the series-sample cadence
        from maggy_tpu.telemetry import memtrack as _memtrack

        ledger = _memtrack.MemoryLedger()
        ledger.register("params", _memtrack.array_bytes(state.params))
        ledger.register("optimizer", _memtrack.array_bytes(state.opt_state))
        depth = _prefetch_depth(prefetch)
        prefetcher = None
        if depth > 0 and num_steps > 0:
            from maggy_tpu.train.prefetch import DevicePrefetcher

            prefetcher = DevicePrefetcher(
                data_iter,
                self.shard_batch,
                depth=depth,
                max_items=num_steps,
                telemetry_recorder=tel,
                ledger=ledger,
            )
        window = max(0, int(metrics_window))
        # autopilot: an in-loop controller fed one sample per step; its
        # safe-live moves land on the prefetcher depth / metrics window of
        # THIS run (built lazily at step 0, once the batch signature that
        # names the workload fingerprint is known)
        ap = None
        ap_target = None
        ap_cfg = None
        if autopilot is not None and autopilot is not False:
            from maggy_tpu.autopilot import AutopilotConfig as _ApConfig

            ap_cfg = (
                autopilot if isinstance(autopilot, _ApConfig) else _ApConfig()
            )
            ap_target = _FitAutopilotTarget(prefetcher, window, trainer=self)
        ap_wait_total = prefetcher.wait_ms_total if prefetcher is not None else 0.0
        pending: deque = deque()  # (loop index, in-flight device metrics)
        ready = None  # newest entry aged OUT of the window: safe to sync
        last_bcast = -1  # last loop index broadcast (monotonic step guard)
        fit_t0 = time.perf_counter()
        tokens_per_batch = 0
        step_ms_sum = 0.0
        # one trace per fit run: every span/gauge the loop records carries
        # it, and the run's start/end land as lifecycle events — the
        # training-side analogue of a serving request's lane
        run_trace = _tracing.new_trace_id()
        trace_prev = _tracing.current()
        _tracing.set_current(run_trace)
        tel.event(
            "train.run_start", trace=run_trace, num_steps=num_steps,
            resumed_from=resumed_from, step0=step0,
        )
        # stall watchdog: the loop beats per step; a wedged device/step
        # dumps the flight recorder (docs/observability.md). The threshold
        # is far above any healthy step — a long first-step compile only
        # risks a harmless diagnostic dump.
        wd = _flightrec.get()
        wd.begin("train.step", detail=step0)
        # recompile sentinel + time-series sampling (docs/observability.md):
        # the jitted step bumps a trace-time counter; a bump without a
        # deliberate rebuild means XLA silently retraced (usually a drifting
        # batch shape) and costs a full compile mid-run — alert, don't guess.
        # The store samples the recorder on its ~1 s tick (one clock compare
        # per step otherwise).
        from maggy_tpu.telemetry import timeseries as _timeseries
        from maggy_tpu.telemetry.alerts import RecompileSentinel as _Sentinel

        ts_store = _timeseries.SeriesStore()
        sentinel = _Sentinel(ts_store, tel, scope="worker", steady=("train_step",))
        try:
            for i in range(num_steps):  # hot-loop (tools/check_host_sync.py)
                wd.beat("train.step", detail=step0 + i)
                if self.membership is not None:
                    # elastic membership (docs/resilience.md): a pending
                    # epoch or a chaos slice event interrupts the loop at
                    # this step boundary; graceful transitions checkpoint
                    # the current step first so no step re-runs
                    self._membership_check(
                        state, step0 + i, checkpointer, chaos, tel
                    )
                if chaos is not None:
                    # deterministic fault injection (chaos harness): a
                    # matching kill rule raises WorkerLost here
                    chaos.kill(tel.worker, step=step0 + i)
                if hook is not None and hook.requested():
                    checkpointer.save(
                        step0 + i, state, meta=self.checkpoint_meta()
                    )
                    checkpointer.wait()
                    tel.count("resilience.preempt_saves")
                    preempted = True
                    break
                if profile_dir is not None and not profiling and i == prof_start:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                ap_drain_ms = 0.0  # this step's measured broadcast drain
                t_in0 = time.perf_counter() if ap_target is not None else 0.0
                if prefetcher is not None:
                    # sharded batches arrive pre-placed; H2D transfer of this
                    # batch overlapped compute of the previous step
                    sharded = next(prefetcher)
                else:
                    batch = next(data_iter)
                    with tel.span("shard_batch", step=i):
                        sharded = self.shard_batch(batch)
                if ap_target is not None:
                    if prefetcher is not None:
                        # queue-wait delta: the prefetcher already measures
                        # exactly the blocked portion of this pull
                        step_wait_ms = prefetcher.wait_ms_total - ap_wait_total
                        ap_wait_total = prefetcher.wait_ms_total
                    else:
                        step_wait_ms = (time.perf_counter() - t_in0) * 1e3
                if i == 0 and isinstance(sharded, dict) and "tokens" in sharded:
                    tokens_per_batch = int(  # sync: ok — shape metadata, not device data
                        getattr(sharded["tokens"], "size", 0)
                    )
                t0 = time.perf_counter()
                with tel.span("train_step", step=i):
                    state, metrics = self.step(state, sharded)
                    if i == 0 and tel.active:
                        # one deliberate sync so the first sample covers the
                        # XLA compile; later steps stay fully async
                        jax.block_until_ready(metrics)  # sync: ok — compile timing
                dt_ms = (time.perf_counter() - t0) * 1e3
                if i == 0:
                    tel.gauge("compile_time_ms", dt_ms)
                else:
                    step_ms_sum += dt_ms
                    tel.gauge("step_time_ms", dt_ms)
                if self._expect_recompile:
                    sentinel.expect("train_step")
                    self._expect_recompile = False
                sentinel.observe(self.compile_counts, watchdog=wd)
                if ts_store.maybe_sample(tel):
                    # same ~1 s cadence as the series sample: reconcile the
                    # HBM accounts and export headroom (params/optimizer
                    # re-read so adopted/replaced state stays honest)
                    ledger.register(
                        "params", _memtrack.array_bytes(state.params)
                    )
                    ledger.register(
                        "optimizer", _memtrack.array_bytes(state.opt_state)
                    )
                    ledger.tick(store=ts_store, telemetry=tel, now=time.time())
                # lagged metrics window: refs sit here `window` steps before
                # anything host-reads them, so broadcasts touch only results
                # the device has long finished — never the dispatch frontier
                pending.append((i, metrics))
                while len(pending) > max(1, window):
                    ready = pending.popleft()
                if profiling and i >= prof_stop:
                    jax.block_until_ready(metrics)  # sync: ok — trace boundary
                    jax.profiler.stop_trace()
                    profiling = False
                    profile_dir = None  # one capture per fit
                if reporter is not None and (i + 1) % report_every == 0:
                    # window 0 = synchronous broadcasts (fresh value, full
                    # pipeline drain); otherwise read the entry that aged
                    # out of the window
                    src = pending[-1] if window == 0 else ready
                    if (src is None or src[0] <= last_bcast) and i == num_steps - 1:
                        src = pending[0]  # final boundary: window not primed
                    if src is not None and src[0] > last_bcast:
                        j, lagged = src
                        last_bcast = j
                        tel.gauge("metrics_lag", i - j)
                        t_drain = time.perf_counter()
                        value = metric_sign * float(lagged[metric_key])  # sync: ok — ref aged out of the window
                        # host time blocked in this read: the per-step
                        # drain cost analyze_trace attributes
                        ap_drain_ms = (time.perf_counter() - t_drain) * 1e3
                        tel.gauge("metrics_drain_ms", ap_drain_ms)
                        reporter.broadcast(value, step=step0 + j + 1)
                if checkpointer is not None and checkpoint_every and (
                    (i + 1) % checkpoint_every == 0
                ):
                    checkpointer.save(
                        step0 + i + 1, state, meta=self.checkpoint_meta()
                    )
                if ap_target is not None:
                    if ap is None:
                        # the first batch names the workload: (model config
                        # + system config) x traffic shape -> the fleet-
                        # shared decision-cache key
                        from maggy_tpu.autopilot import (
                            Controller as _ApController,
                        )
                        from maggy_tpu.autopilot import plan as _ap_plan

                        bsz = seq = 0
                        if isinstance(sharded, dict) and "tokens" in sharded:
                            shape = getattr(sharded["tokens"], "shape", (0, 0))
                            bsz, seq = int(shape[0]), int(shape[-1])  # sync: ok — shape metadata, not device data
                        workload = _ap_plan.workload_fingerprint(
                            repr(getattr(self.model, "cfg", type(self.model).__name__)),
                            self.checkpoint_meta(),
                            _ap_plan.traffic_shape("train", batch=bsz, seq=seq),
                        )
                        ap = _ApController(
                            ap_target,
                            config=ap_cfg,
                            telemetry_recorder=tel,
                            workload=workload,
                        )
                    elif i > 0:  # the compile step would poison the window
                        # the guard is the TRUE per-step rate — compute plus
                        # the input wait and broadcast drain a move targets
                        wall_ms = dt_ms + step_wait_ms + ap_drain_ms
                        ap.observe(
                            {
                                "step_time_ms": dt_ms,
                                "input_wait_ms": step_wait_ms,
                                "metrics_drain_ms": ap_drain_ms,
                                "steps_per_sec": (
                                    1e3 / wall_ms if wall_ms > 0 else 0.0
                                ),
                            }
                        )
                        window = max(0, ap_target.metrics_window)
        finally:
            wd.end("train.step")
            _tracing.set_current(trace_prev)
            if prefetcher is not None:
                prefetcher.close()
            if profiling:  # loop ended/raised while a trace was active
                jax.profiler.stop_trace()
        tel.event(
            "train.run_end", trace=run_trace, steps=num_steps,
            preempted=preempted,
        )
        out = {k: float(v) for k, v in metrics.items()}
        if resumed_from is not None:
            out["resumed_from"] = float(resumed_from)
        if preempted:
            out["preempted"] = 1.0
        # measured AFTER the float() conversions above — those force the
        # device->host sync that makes the wall time honest
        wall = time.perf_counter() - fit_t0
        if num_steps > 0 and wall > 0:
            out["steps_per_sec"] = num_steps / wall
            tel.gauge("steps_per_sec", out["steps_per_sec"])
            if num_steps > 1 and step_ms_sum > 0:
                tel.gauge("step_time_ms_mean", step_ms_sum / (num_steps - 1))
            if tokens_per_batch and tel.active:
                tok_per_sec = tokens_per_batch * num_steps / wall
                tel.gauge("tokens_per_sec", tok_per_sec)
                from maggy_tpu.telemetry import flops as _flops

                mfu = _flops.estimate_mfu(
                    tok_per_sec,
                    _flops.param_count(state.params),
                    list(self.mesh.devices.flat),
                )
                if mfu is not None:
                    tel.gauge("mfu_est", mfu)
        return state, out


@dataclasses.dataclass
class TrainContext:
    """What the distributed executor injects into an oblivious train_fn.

    The train_fn can stay framework-high-level (use ``ctx.trainer(...)``) or go
    low-level (use ``ctx.mesh`` + ``ctx.shard`` directly with its own pjit).
    """

    mesh: Any
    spec: ShardingSpec
    process_index: int = 0
    num_processes: int = 1
    rules: Tuple = shd.DEFAULT_RULES
    # "chief" (worker 0) / "worker" / "evaluator" — the reference's TF role
    # assignment (tf_dist_executor.py:138-144); an evaluator is outside the
    # training group and should evaluate checkpoints instead of training
    role: str = "worker"
    # elastic membership (docs/resilience.md): the worker's MembershipMonitor
    # and, for multi-slice meshes, the SliceTopology the mesh was built for
    membership: Any = None
    topology: Any = None

    @classmethod
    def create(
        cls, spec_or_preset="fsdp", devices=None, role="worker", membership=None
    ) -> "TrainContext":
        import jax as _jax

        from maggy_tpu import util
        from maggy_tpu.parallel.mesh import mesh_for

        # one XLA compile per geometry across trials/instances/processes
        util.enable_compilation_cache()
        mesh, spec = mesh_for(sharding=spec_or_preset, devices=devices)
        return cls(
            mesh=mesh,
            spec=spec,
            process_index=_jax.process_index(),
            num_processes=_jax.process_count(),
            role=role,
            membership=membership,
        )

    @classmethod
    def create_sliced(
        cls,
        spec_or_preset="fsdp",
        total_slices: int = 1,
        active=None,
        devices=None,
        role="worker",
        membership=None,
    ) -> "TrainContext":
        """A context over a multi-slice mesh (docs/distributed.md "Slice
        topology"): the device lease splits into ``total_slices`` contiguous
        simulated slices, ``active`` (default: all) selects which are in the
        mesh, and each runs ``spec_or_preset`` internally under an outer
        ``slice`` data axis. Batch placement spans (slice, data, fsdp) via
        :func:`maggy_tpu.parallel.sharding.slice_rules`; params never shard
        over ``slice``, so the gradient sync decomposes into intra-slice
        reduce-scatter (ICI) + cross-slice all-reduce (DCN). Elastic
        membership rebuilds this context with the surviving ``active`` set
        on every epoch change."""
        import jax as _jax

        from maggy_tpu import util
        from maggy_tpu.parallel.mesh import make_slice_mesh, slice_device_groups
        from maggy_tpu.parallel.spec import SliceTopology

        util.enable_compilation_cache()
        devices = list(devices) if devices else list(_jax.devices())
        groups = slice_device_groups(total_slices, devices)
        active = tuple(sorted(active if active is not None else range(total_slices)))
        if not active:
            raise ValueError("create_sliced needs at least one active slice")
        mesh_devices = [d for s in active for d in groups[s]]
        per_slice = len(groups[0])
        if isinstance(spec_or_preset, ShardingSpec):
            spec = (
                spec_or_preset
                if spec_or_preset.num_devices == per_slice
                else spec_or_preset.scaled_to(per_slice)
            )
        else:
            spec = ShardingSpec.preset(spec_or_preset, per_slice)
        topology = SliceTopology(n_slices=len(active), slice_spec=spec)
        return cls(
            mesh=make_slice_mesh(topology, mesh_devices),
            spec=spec,
            process_index=_jax.process_index(),
            num_processes=_jax.process_count(),
            rules=shd.slice_rules(shd.DEFAULT_RULES),
            role=role,
            membership=membership,
            topology=topology,
        )

    def trainer(
        self,
        model,
        optimizer,
        loss_fn: Callable = lm_loss_fn,
        n_microbatches: Optional[int] = None,
        zero_stage: Optional[int] = None,
        bucket_mb: Optional[float] = None,
    ) -> Trainer:
        # overlap knobs default to the spec's (config/distributed.py plumbs
        # them there); explicit arguments win
        if zero_stage is None:
            zero_stage = getattr(self.spec, "zero_stage", 0)
        if bucket_mb is None:
            bucket_mb = getattr(self.spec, "bucket_mb", None)
        return Trainer(
            model,
            optimizer,
            self.mesh,
            loss_fn=loss_fn,
            rules=self.rules,
            n_microbatches=n_microbatches,
            membership=self.membership,
            zero_stage=int(zero_stage),
            bucket_mb=bucket_mb,
        )

    def shard(self, tree, logical_axes=("batch",)):
        target = shd.named_sharding(self.mesh, logical_axes, self.rules)
        return jax.device_put(tree, target)
