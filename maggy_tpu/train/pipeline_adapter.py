"""Decoder ⇄ 1F1B pipeline adapter: stage functions for the flagship model.

The reference explicitly rejects pipeline modules
(core/patching/modules.py:106-109); here pipeline parallelism is first-class:
this module maps the scanned :class:`~maggy_tpu.models.Decoder` parameter tree
onto the uniform per-stage layout :func:`maggy_tpu.parallel.pipeline.
pipeline_grads_1f1b` wants — embedding ingested on stage 0 (``first_fn``),
``n_layers/n_stages`` decoder layers per stage (``stage_fn``), final norm +
LM head folded into the last stage's loss (``head_fn``).

Layout: every leaf of the stage tree carries a leading ``[n_stages]`` axis
sharded over the ``stage`` mesh axis, so each device holds one layer chunk
plus ONE copy of the embedding and head (the same per-device memory as
replicating them; only stage 0's embedding slice and the last stage's head
slice receive gradients — the others stay at their initial values and are
never read).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from maggy_tpu.util import shard_map
from maggy_tpu.models.transformer import (
    REMAT_POLICIES,
    Decoder,
    RMSNorm,
    _dense,
    _ScannedLayer,
    default_attention,
)


# gradient-overlap seam (docs/distributed.md "Gradient overlap & ZeRO"):
# pp-composed configs do NOT get per-stage bucketing yet — the 1F1B schedule
# already interleaves its stage collectives, and re-bucketing inside the
# stage shard_maps is future work. A zero_stage/bucket_mb request on a pp
# mesh (or any other overlap-ineligible geometry) lands here: one explicit
# process-wide warning, then the dense/pipeline path runs unchanged.
_overlap_fallback_warned = False


def warn_overlap_unbucketed(reason: str) -> None:
    """Warn once per process that a requested gradient-overlap config falls
    back to the unbucketed path; training proceeds unchanged."""
    global _overlap_fallback_warned
    if _overlap_fallback_warned:
        return
    _overlap_fallback_warned = True
    import warnings

    warnings.warn(
        f"gradient overlap disabled: {reason}; training continues on the "
        "unbucketed path",
        stacklevel=3,
    )


def _pp_local_attention(q, k, v, *, causal: bool = True, segment_ids=None):
    """Attention inside the pipeline's shard_map must be device-local (the
    stage/data/fsdp axes are manual): the single-device Pallas flash kernel
    on TPU when the geometry tiles onto the MXU, the XLA dense path
    otherwise — the same dispatch as auto_attention minus the mesh logic."""
    from maggy_tpu.ops.flash import flash_attention  # late: import cycle

    b, s, h, d = q.shape
    if (
        jax.default_backend() == "tpu"
        and segment_ids is None
        and d % 128 == 0
        and s % 128 == 0
    ):
        return flash_attention(q, k, v, causal=causal)
    return default_attention(q, k, v, causal=causal, segment_ids=segment_ids)


def _make_pp_tp_attention(tp: int):
    """Stage-local attention for pp x tp: a NESTED shard_map manual over the
    `tensor` axis (legal inside the pipeline's partial-manual region, where
    `tensor` is GSPMD-auto) splits the head axis so each tensor shard runs
    the single-device kernel — the Pallas flash path on TPU — on its own
    H/tp heads. Attention is embarrassingly parallel over heads, so there is
    no collective to insert and nothing for GSPMD to partition through an
    opaque custom call. Head-count divisibility (q AND GQA kv) is enforced
    by decoder_pipeline_parts before this is ever installed."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from maggy_tpu.parallel.spec import AXIS_TENSOR

    def attn(q, k, v, *, causal: bool = True, segment_ids=None):
        head_spec = P(None, None, AXIS_TENSOR, None)
        segmented = segment_ids is not None

        def local(q, k, v, seg):
            return _pp_local_attention(
                q, k, v, causal=causal, segment_ids=seg if segmented else None
            )

        seg_in = (
            segment_ids
            if segmented
            else jnp.zeros(q.shape[:2], jnp.int32)  # placeholder, never read
        )
        # mesh=None: inherit the CONTEXT mesh — inside the pipeline's
        # partial-manual region that is the abstract mesh with
        # stage/data/fsdp already Manual; passing the concrete Mesh there
        # is rejected ("context mesh should match")
        return shard_map(
            local,
            in_specs=(head_spec, head_spec, head_spec, P()),
            out_specs=head_spec,
            axis_names=frozenset({AXIS_TENSOR}),
            check_vma=False,
        )(q, k, v, seg_in)

    return attn


@dataclasses.dataclass(frozen=True)
class DecoderPipelineParts:
    """Everything the Trainer needs to run a Decoder under 1F1B."""

    n_stages: int
    layers_per_stage: int
    first_fn: Callable  # (stage_params, raw [mb,S] | [mb,S,3]) -> x [mb,S,D]
    stage_fn: Callable  # (stage_params, x, raw) -> x  (or (x, aux))
    head_fn: Callable   # (stage_params, x) -> logits [mb,S,V] fp32
    restack: Callable   # canonical decoder params -> stage-stacked tree
    unstack: Callable   # stage-stacked tree -> canonical decoder params
    # stage_fn returns (y, aux_scalar): per-stage router losses (MoE) join
    # the objective at each stage's backward tick
    stage_has_aux: bool = False
    # logical-axis names per stage-tree leaf ((None, ...canonical names) —
    # leading dim is the stage axis). The Trainer resolves these against its
    # rules to place tensor-parallel dims (attn heads / mlp hidden / vocab)
    # over the mesh's `tensor` axis inside each stage (pp x tp; the pipeline
    # shard_map stays manual over stage/data/fsdp and leaves `tensor` to
    # GSPMD). None for non-Decoder flows that build parts by hand.
    stage_names: Any = None


def decoder_pipeline_parts(
    model: Any, n_stages: int, tp: int = 1, mesh=None, ep: int = 1
) -> DecoderPipelineParts:
    """Build the 1F1B parts for a :class:`Decoder`.

    Raises loudly for anything the pipeline path cannot honor — a silently
    replicated stage axis is the failure mode this replaces (VERDICT r3
    item 2)."""
    from maggy_tpu.models.moe import MoEDecoder, _ScannedMoELayer

    is_moe = isinstance(model, MoEDecoder)
    if not isinstance(model, Decoder) and not is_moe:
        raise ValueError(
            "Pipeline parallelism (pp>1) currently supports the Decoder/"
            f"MoEDecoder families only, got {type(model).__name__}. Drop pp "
            "from the ShardingSpec or use parallel.pipeline primitives "
            "directly."
        )
    cfg = model.cfg
    if not cfg.scan_layers:
        raise ValueError("pp>1 needs scan_layers=True (stage chunks slice the scanned stack)")
    if cfg.decode:
        raise ValueError("pp>1 is a training path; decode=True has no pipeline support")
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={n_stages} stages"
        )
    if getattr(cfg, "ablated", None):
        raise ValueError(
            "pp>1 with cfg.ablated is not supported: the stage chunks would "
            "silently ignore the LOCO gates. Ablate without pipeline stages."
        )
    if cfg.tie_embeddings:
        raise ValueError(
            "tie_embeddings=True is not supported with pp>1: the input "
            "embedding lives on stage 0 and the head on the last stage, and "
            "each would only receive its own partial gradient — the copies "
            "would silently untie. Use tie_embeddings=False under pp."
        )
    l_per = cfg.n_layers // n_stages
    if tp > 1 and (cfg.n_heads % tp or cfg.n_kv_heads % tp):
        raise ValueError(
            f"n_heads={cfg.n_heads} / n_kv_heads={cfg.n_kv_heads} not "
            f"divisible by tp={tp}: the stage-local attention shards BOTH "
            "head axes over the tensor mesh axis (GQA kv heads included)"
        )
    if ep > 1 and not is_moe:
        raise ValueError(
            f"ep={ep} under pp>1 needs an MoE model (got "
            f"{type(model).__name__}): a dense model has no expert dims, so "
            "the expert axis would silently replicate every stage param and "
            "waste ep-1 of every ep devices (VERDICT r3 item 2 failure mode)"
        )
    if ep > 1 and getattr(cfg, "n_experts", 0) % ep:
        raise ValueError(
            f"n_experts={cfg.n_experts} not divisible by ep={ep}: the "
            "expert axis would silently replicate instead of sharding the "
            "expert FFNs (pp x ep)"
        )
    # under pp x tp the stage body runs with the tensor axis in GSPMD-auto
    # mode; the Pallas flash kernel is an opaque custom call XLA cannot
    # partition over the sharded head axis, so a nested tensor-manual
    # shard_map splits heads explicitly and runs the single-device kernel
    # per shard (falls back to the GSPMD einsum path without a mesh)
    if tp > 1:
        # the nested map inherits the context mesh, but only Trainer-driven
        # flows guarantee one — bare parts built without a mesh keep GSPMD
        local_attn = (
            _make_pp_tp_attention(tp) if mesh is not None else default_attention
        )
    else:
        local_attn = _pp_local_attention
    stage_cfg = dataclasses.replace(
        cfg,
        n_layers=l_per,
        attention_fn=cfg.attention_fn or local_attn,
        # no logical-axis boxes inside the shard_map: placement is manual
        # (P('stage') on the stacked tree), and flax would otherwise try to
        # resolve names like 'embed' against the physical mesh mid-region
        partition_params=False,
    )

    layer_cls = _ScannedMoELayer if is_moe else _ScannedLayer
    if cfg.remat:
        layer_cls = nn.remat(
            layer_cls, prevent_cse=False, policy=REMAT_POLICIES[cfg.remat_policy]
        )
    chunk = nn.scan(
        layer_cls,
        variable_axes=(
            {"params": 0, "intermediates": 0} if is_moe else {"params": 0}
        ),
        split_rngs={"params": True},
        in_axes=nn.broadcast,
        length=l_per,
        metadata_params={nn.PARTITION_NAME: None},
    )(stage_cfg)

    # raw microbatch layouts (decided per-trace by ndim/width): [mb, S]
    # plain tokens; [mb, S, 2] (tokens, positions); [mb, S, 3] (tokens,
    # positions, segment_ids) — the 1F1B stream is stage-replicated, so
    # every stage derives its side inputs from `raw` without widening the
    # activation hand-offs

    def first_fn(params, raw):
        tokens = raw[..., 0] if raw.ndim == 3 else raw
        return jnp.asarray(params["embedding"], cfg.dtype)[tokens]

    def _side_inputs(x, raw):
        if raw.ndim == 3:
            positions = raw[..., 1]
            segment_ids = raw[..., 2] if raw.shape[-1] >= 3 else None
        else:
            positions = jnp.broadcast_to(
                jnp.arange(x.shape[1], dtype=jnp.int32), x.shape[:2]
            )
            segment_ids = None
        return positions, segment_ids

    if is_moe:
        def stage_fn(params, x, raw):
            from maggy_tpu.train.trainer import collect_aux_losses

            positions, segment_ids = _side_inputs(x, raw)
            (y, _), mods = chunk.apply(
                {"params": params["layers"]}, x, positions, segment_ids,
                mutable=["intermediates"],
            )
            # this stage's router balancing losses (shared collection rule)
            return y, collect_aux_losses(mods)
    else:
        def stage_fn(params, x, raw):
            positions, segment_ids = _side_inputs(x, raw)
            y, _ = chunk.apply(
                {"params": params["layers"]}, x, positions, segment_ids
            )
            return y

    # the head reuses the SAME modules as Decoder (single source of truth):
    # final_norm RMSNorm and the lm_head DenseGeneral applied functionally on
    # the stage-local param subtrees
    final_norm = RMSNorm(stage_cfg, name="final_norm")
    lm_head = _dense(cfg.vocab_size, ("embed", "vocab"), stage_cfg, "lm_head")

    def head_fn(params, x):
        xn = final_norm.apply({"params": params["final_norm"]}, x)
        logits = lm_head.apply({"params": params["lm_head"]}, xn)
        if cfg.logits_softcap:
            logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
        return logits.astype(jnp.float32)

    def _bcast(p):
        import numpy as np

        if isinstance(p, np.ndarray):
            # host path (checkpoint re-staging): zero-copy view, never
            # n_stages materialized copies on the default device
            return np.broadcast_to(p[None], (n_stages,) + p.shape)
        return jnp.broadcast_to(p[None], (n_stages,) + p.shape)

    def restack(params):
        """Canonical (unboxed) Decoder params -> uniform stage tree."""
        out = {
            "embedding": _bcast(params["embedding"]),
            "layers": jax.tree.map(
                lambda p: p.reshape((n_stages, l_per) + p.shape[1:]),
                params["layers"],
            ),
            "final_norm": jax.tree.map(_bcast, params["final_norm"]),
            "lm_head": jax.tree.map(_bcast, params["lm_head"]),
        }
        return out

    def unstack(stage_params):
        """Stage tree -> canonical Decoder params (each leaf from its owning
        stage: embedding from 0, norm/head from -1), e.g. for checkpoint
        export into generate()/eval."""
        out = {
            "embedding": stage_params["embedding"][0],
            "layers": jax.tree.map(
                lambda p: p.reshape((n_stages * l_per,) + p.shape[2:]),
                stage_params["layers"],
            ),
            "final_norm": jax.tree.map(lambda p: p[-1], stage_params["final_norm"]),
            "lm_head": jax.tree.map(lambda p: p[-1], stage_params["lm_head"]),
        }
        return out

    # logical axes per stage leaf, for pp x tp placement: the canonical
    # model's own nn.Partitioned names (same source params_shardings reads on
    # the dense path), pushed through restack's layout — every stage leaf
    # gains a leading stage axis, so names gain a leading None. Only built
    # when a tensor axis is real: at tp=1 the resolution could only ever
    # return the plain P('stage') placement, so skip the extra abstract init
    stage_names = None
    if tp > 1 or ep > 1:
        pmodel = type(model)(dataclasses.replace(cfg, partition_params=True))
        abstract = jax.eval_shape(
            pmodel.init, jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )
        canonical_names = jax.tree.map(
            lambda l: tuple(l.names)
            if isinstance(l, nn.Partitioned)
            else (None,) * getattr(l, "ndim", 0),
            abstract["params"],
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )
        stage_names = jax.tree.map(
            lambda n: (None,) + n,
            canonical_names,
            is_leaf=lambda x: isinstance(x, tuple),
        )

    return DecoderPipelineParts(
        n_stages=n_stages,
        layers_per_stage=l_per,
        first_fn=first_fn,
        stage_fn=stage_fn,
        head_fn=head_fn,
        restack=restack,
        unstack=unstack,
        stage_has_aux=is_moe,
        stage_names=stage_names,
    )


def convert_pipeline_state(state, old_parts, new_parts):
    """Re-stage a pipeline TrainState across pp degrees (checkpoint
    portability, SURVEY §5.4): every stage-stacked tree in the state —
    params and the optax mirrors (adam mu/nu, ...) — goes through
    ``old_parts.unstack`` → ``new_parts.restack``; scalars (step, adam
    count) pass through. Run the result through the NEW Trainer's
    ``make_state``-born shardings — ``Trainer.adopt_state`` does both —
    before stepping."""
    pstruct = jax.tree_util.tree_structure(state.params)

    def is_param_tree(x):
        try:
            return jax.tree_util.tree_structure(x) == pstruct
        except Exception:
            return False

    def convert(x):
        if is_param_tree(x):
            return new_parts.restack(old_parts.unstack(x))
        return x

    new_params = convert(state.params)
    new_opt = jax.tree.map(convert, state.opt_state, is_leaf=is_param_tree)
    return state.replace(params=new_params, opt_state=new_opt)
