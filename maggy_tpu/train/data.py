"""Host-side data pipeline utilities.

The TPU-native replacement for the reference's data-loader patching
(MaggyDataLoader's forced DistributedSampler + petastorm RANK/WORLD_SIZE
sharding, core/patching/dataloader.py:33-144): explicit, functional shards —
each host process takes its ``process_index`` slice, batches it, and
``Trainer.shard_batch`` places it onto the mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np


def host_shard(arrays: Dict[str, np.ndarray], process_index: int, num_processes: int):
    """Slice every array's leading axis into this host's contiguous shard."""
    if num_processes <= 1:
        return arrays
    out = {}
    for k, v in arrays.items():
        n = v.shape[0]
        per = n // num_processes
        out[k] = v[process_index * per : (process_index + 1) * per]
    return out


class BatchIterator:
    """Infinite (or one-epoch) minibatch iterator over array dicts, with an
    index-only ``skip(n)`` fast path.

    Batch-for-batch identical to the generator it replaced: one permutation
    is drawn per epoch from a single seeded RNG stream, so ``skip`` (which
    advances epoch/offset counters and draws the skipped epochs'
    permutations WITHOUT gathering any rows) lands on exactly the batch a
    ``next()`` drain would have — the ``fit(resume="auto")`` fast-forward
    no longer materializes thousands of throwaway batches.
    """

    def __init__(
        self,
        arrays: Dict[str, np.ndarray],
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        loop: bool = True,
    ):
        self.arrays = dict(arrays)
        self.n = min(v.shape[0] for v in self.arrays.values())
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.loop = loop
        self._rng = np.random.default_rng(seed)
        self._end = (self.n // batch_size) * batch_size if drop_remainder else self.n
        self._idx: Optional[np.ndarray] = None  # current epoch's permutation
        self._pos = 0  # row offset into the current epoch
        self._exhausted = False
        self.batches_materialized = 0  # gathers performed (skip test hook)

    def __iter__(self) -> "BatchIterator":
        return self

    def _ensure_epoch(self) -> None:
        if self._idx is None:
            self._idx = (
                self._rng.permutation(self.n)
                if self.shuffle
                else np.arange(self.n)
            )
            self._pos = 0

    def _advance(self) -> None:
        """Move past the batch at ``_pos``, rolling the epoch as needed."""
        self._pos += self.batch_size
        if self._pos >= self._end:
            self._idx = None
            if not self.loop:
                self._exhausted = True

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._exhausted:
            raise StopIteration
        self._ensure_epoch()
        take = self._idx[self._pos : self._pos + self.batch_size]
        batch = {k: v[take] for k, v in self.arrays.items()}
        self.batches_materialized += 1
        self._advance()
        return batch

    def skip(self, n: int) -> int:
        """Advance ``n`` batches by index arithmetic only — no row gathers.
        Returns how many were skipped (short only on exhaustion)."""
        skipped = 0
        while skipped < n and not self._exhausted:
            self._ensure_epoch()
            # batches remaining in this epoch from the current offset
            remaining = len(range(self._pos, self._end, self.batch_size))
            take = min(n - skipped, remaining)
            if take < remaining:
                self._pos += take * self.batch_size
            else:
                # cross the epoch boundary through _advance so the loop /
                # exhaustion rules stay identical to the next() path
                self._pos += (take - 1) * self.batch_size
                self._advance()
            skipped += take
        return skipped


def batch_iterator(
    arrays: Dict[str, np.ndarray],
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    drop_remainder: bool = True,
    loop: bool = True,
) -> Iterator[Dict[str, np.ndarray]]:
    """Minibatch iterator over array dicts (see :class:`BatchIterator`)."""
    return BatchIterator(
        arrays,
        batch_size,
        shuffle=shuffle,
        seed=seed,
        drop_remainder=drop_remainder,
        loop=loop,
    )


def synthetic_lm_batches(
    vocab_size: int,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    structured: bool = True,
) -> Iterator[Dict[str, np.ndarray]]:
    """Synthetic token streams for benchmarks/tests; ``structured=True`` yields
    learnable arithmetic sequences (loss can actually decrease)."""
    rng = np.random.default_rng(seed)
    while True:
        if structured:
            start = rng.integers(0, vocab_size, (batch_size, 1))
            step = rng.integers(1, 7, (batch_size, 1))
            toks = (start + step * np.arange(seq_len)[None, :]) % vocab_size
        else:
            toks = rng.integers(0, vocab_size, (batch_size, seq_len))
        yield {"tokens": toks.astype(np.int32)}
