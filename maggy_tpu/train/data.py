"""Host-side data pipeline utilities.

The TPU-native replacement for the reference's data-loader patching
(MaggyDataLoader's forced DistributedSampler + petastorm RANK/WORLD_SIZE
sharding, core/patching/dataloader.py:33-144): explicit, functional shards —
each host process takes its ``process_index`` slice, batches it, and
``Trainer.shard_batch`` places it onto the mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np


def host_shard(arrays: Dict[str, np.ndarray], process_index: int, num_processes: int):
    """Slice every array's leading axis into this host's contiguous shard."""
    if num_processes <= 1:
        return arrays
    out = {}
    for k, v in arrays.items():
        n = v.shape[0]
        per = n // num_processes
        out[k] = v[process_index * per : (process_index + 1) * per]
    return out


def batch_iterator(
    arrays: Dict[str, np.ndarray],
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    drop_remainder: bool = True,
    loop: bool = True,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite (or one-epoch) minibatch iterator over array dicts."""
    n = min(v.shape[0] for v in arrays.values())
    rng = np.random.default_rng(seed)
    while True:
        idx = rng.permutation(n) if shuffle else np.arange(n)
        end = (n // batch_size) * batch_size if drop_remainder else n
        for i in range(0, end, batch_size):
            take = idx[i : i + batch_size]
            yield {k: v[take] for k, v in arrays.items()}
        if not loop:
            return


def synthetic_lm_batches(
    vocab_size: int,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    structured: bool = True,
) -> Iterator[Dict[str, np.ndarray]]:
    """Synthetic token streams for benchmarks/tests; ``structured=True`` yields
    learnable arithmetic sequences (loss can actually decrease)."""
    rng = np.random.default_rng(seed)
    while True:
        if structured:
            start = rng.integers(0, vocab_size, (batch_size, 1))
            step = rng.integers(1, 7, (batch_size, 1))
            toks = (start + step * np.arange(seq_len)[None, :]) % vocab_size
        else:
            toks = rng.integers(0, vocab_size, (batch_size, seq_len))
        yield {"tokens": toks.astype(np.int32)}
