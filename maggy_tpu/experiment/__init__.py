"""Experiment front door: ``experiment.lagom(train_fn, config)``.

Parity with the reference's lagom dispatch (experiment/experiment.py:21-45,
experiment_python.py:48-197): a single-experiment-at-a-time guard, app/run-id
bookkeeping, and driver selection by singledispatch on the config type. There is
no Spark/Python backend fork — the TPU build has one execution substrate with
local (threads) and pod (multi-host RPC) worker placement chosen by the driver.

"Lagom" (Swedish): not too little, not too much — the reference's name for
running experiments with just the right amount of resources.
"""

from __future__ import annotations

import threading
from functools import singledispatch
from typing import Any, Callable, Optional

from maggy_tpu import util
from maggy_tpu.config import (
    AblationConfig,
    BaseConfig,
    DistributedConfig,
    HyperparameterOptConfig,
)
from maggy_tpu.config.base import LagomConfig

APP_ID: Optional[str] = None
RUN_ID: int = 0
_running_lock = threading.Lock()
_running = False
_env_run_id_used = False
# the driver currently executing (monitoring/launcher introspection)
CURRENT_DRIVER = None


def lagom(train_fn: Callable, config: LagomConfig) -> Any:
    """Launch an experiment and block until its result is available.

    :param train_fn: the oblivious training function.
    :param config: a LagomConfig subclass instance selecting the experiment kind.
    :returns: experiment result — best/worst/avg dict for HPO, the train_fn
        outputs for single runs, per-worker results for distributed training.
    """
    global APP_ID, RUN_ID, _running
    if isinstance(train_fn, LagomConfig) and callable(config):
        raise TypeError(
            "lagom(train_fn, config): arguments look swapped — got a config "
            "first and a callable second."
        )
    if not callable(train_fn):
        raise TypeError(f"train_fn must be callable, got {type(train_fn).__name__}")
    with _running_lock:
        if _running:
            raise RuntimeError(
                "An experiment is already running; maggy runs one experiment "
                "at a time (reference experiment_pyspark.py:43-64 guard)."
            )
        _running = True
    try:
        worker_result = _maybe_run_as_pod_worker(train_fn, config)
        if worker_result is not None:
            return worker_result
        import os

        global _env_run_id_used
        if APP_ID is None:
            # the elastic launcher pins app/run ids so every restart
            # generation shares one experiment dir (and its checkpoints)
            APP_ID = os.environ.get("MAGGY_TPU_APP_ID") or util.new_app_id()
        run_id_env = os.environ.get("MAGGY_TPU_RUN_ID")
        if run_id_env and not _env_run_id_used:
            # the pin applies to the process's FIRST experiment only; later
            # lagom() calls in the same script get fresh run dirs after it
            _env_run_id_used = True
            RUN_ID = int(run_id_env)
            util.RUNS.observe(APP_ID, RUN_ID)
        else:
            RUN_ID = util.RUNS.next_run_id(APP_ID)
        driver = lagom_driver(config, APP_ID, RUN_ID)
        global CURRENT_DRIVER
        CURRENT_DRIVER = driver
        try:
            return driver.run_experiment(train_fn)
        finally:
            CURRENT_DRIVER = None
    finally:
        with _running_lock:
            _running = False


@singledispatch
def lagom_driver(config, app_id: str, run_id: int):
    raise TypeError(
        f"Unsupported config type {type(config).__name__}; expected a "
        "LagomConfig subclass (BaseConfig, HyperparameterOptConfig, "
        "AblationConfig, DistributedConfig)."
    )


@lagom_driver.register(BaseConfig)
def _(config: BaseConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.hpo import BaseDriver

    return BaseDriver(config, app_id, run_id)


@lagom_driver.register(HyperparameterOptConfig)
def _(config: HyperparameterOptConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.hpo import HyperparameterOptDriver

    return HyperparameterOptDriver(config, app_id, run_id)


@lagom_driver.register(AblationConfig)
def _(config: AblationConfig, app_id: str, run_id: int):
    try:
        from maggy_tpu.core.driver.ablation import AblationDriver
    except ImportError as e:
        raise NotImplementedError(f"Ablation driver unavailable: {e}") from e

    return AblationDriver(config, app_id, run_id)


@lagom_driver.register(DistributedConfig)
def _(config: DistributedConfig, app_id: str, run_id: int):
    try:
        from maggy_tpu.core.driver.distributed import DistributedTrainingDriver
    except ImportError as e:
        raise NotImplementedError(f"Distributed driver unavailable: {e}") from e

    return DistributedTrainingDriver(config, app_id, run_id)


def _maybe_run_as_pod_worker(train_fn: Callable, config) -> Optional[Any]:
    """Pod mode: non-zero hosts run a worker against the process-0 driver
    instead of their own driver (core/pod.py). DistributedConfig workers join
    the collective training run; HPO/ablation workers run a remote TRIAL
    executor loop — the reference's Spark-executor trial placement
    (spark_driver.py:136-145), elastic here: workers may join late, die, and
    re-register (``maggy_tpu.run --respawn``) without aborting the study."""
    import os

    distributed = isinstance(config, DistributedConfig)
    if not distributed and not (
        os.environ.get("MAGGY_TPU_ROLE") == "worker"
        or getattr(config, "driver_addr", None)
        or os.environ.get("MAGGY_TPU_DRIVER")
    ):
        # plain single-process HPO/ablation: never touch worker_role (it may
        # consult jax.process_index, pointlessly initializing a backend)
        return None
    from maggy_tpu.core import pod

    role = pod.worker_role(config)
    if role is None:
        return None
    run = pod.run_worker if distributed else pod.run_trial_worker
    return run(
        train_fn, config, role.host, role.port, role.secret,
        via_registry=role.via_registry,
    )
