"""Training-loop callbacks bridging user frameworks to the Reporter.

Capability parity with the reference ``maggy/callbacks.py`` (callbacks.py:20-66
KerasBatchEnd/KerasEpochEnd): hooks that forward a chosen metric to
``reporter.broadcast`` so early stopping and the driver's monitoring plane work
without the user writing broadcast calls. The JAX-native variant is a plain
callable for step loops; Keras variants are provided when TF is importable.
"""

from __future__ import annotations



class ReporterCallback:
    """JAX-native: call ``cb(metrics_dict, step)`` at step/epoch boundaries."""

    def __init__(self, reporter, metric: str = "loss", negate: bool = False,
                 every: int = 1):
        self.reporter = reporter
        self.metric = metric
        self.negate = negate
        self.every = max(1, int(every))

    def __call__(self, metrics, step: int) -> None:
        if step % self.every:
            return
        value = float(metrics[self.metric])
        self.reporter.broadcast(-value if self.negate else value, step=int(step))


def KerasBatchEnd(reporter, metric: str = "loss"):
    """Keras callback broadcasting at batch end (reference callbacks.py:20)."""
    keras = _keras()

    class _BatchEnd(keras.callbacks.Callback):
        def __init__(self):
            super().__init__()
            self._step = 0

        def on_train_batch_end(self, batch, logs=None):
            if logs and metric in logs:
                reporter.broadcast(float(logs[metric]), step=self._step)
            self._step += 1

    return _BatchEnd()


def KerasEpochEnd(reporter, metric: str = "val_loss"):
    """Keras callback broadcasting at epoch end (reference callbacks.py:45)."""
    keras = _keras()

    class _EpochEnd(keras.callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            if logs and metric in logs:
                reporter.broadcast(float(logs[metric]), step=int(epoch))

    return _EpochEnd()


def _keras():
    try:
        from tensorflow import keras  # pragma: no cover - needs TF installed

        return keras
    except ImportError as e:
        raise ImportError(
            "Keras callbacks require tensorflow; use ReporterCallback for "
            "JAX training loops."
        ) from e
