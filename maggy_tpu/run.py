"""Multi-process experiment launcher.

    python -m maggy_tpu.run --workers 3 my_script.py [script args...]

Starts ``my_script.py`` once as the driver (process 0) and ``workers - 1``
times as pod workers, wiring MAGGY_TPU_ROLE / DRIVER / SECRET / PARTITION /
BIND_PORT so the script's ``lagom(train_fn, DistributedConfig(...))`` call
forms one experiment across the processes (core/pod.py execution model). On a
real pod, run the equivalent: start the same script on every host with these
variables pointing at host 0.

The script must pass ``num_executors=<workers>`` (or leave it to default to
``jax.process_count()``) and may use ``data_plane="local"`` for independent
per-host replicas or initialize ``jax.distributed`` up front for one global
mesh.

Elastic training (``--elastic MAX_RESTARTS``): when any rank dies, the
launcher tears the generation down and respawns every rank — the TPU-native
recovery model, since a lost host wedges the surviving hosts' collectives
exactly like a lost NCCL rank (the reference can only retry whole Spark
tasks, rpc.py:415-437; slice-level restart is new here). App/run ids are
pinned across generations so every generation lands in the same experiment
directory, and training scripts resume from their latest checkpoint
(``Checkpointer.latest_step`` + ``Trainer.fit(checkpointer=...)``). The
generation number reaches scripts as ``MAGGY_TPU_GENERATION``.
"""

from __future__ import annotations

import argparse
import os
import secrets
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_rank(args, env_gen, generation: int, rank: int, port: int, tag=""):
    """Start one rank's process with the generation's wiring."""
    env = dict(env_gen)
    env["MAGGY_TPU_ROLE"] = "driver" if rank == 0 else "worker"
    env["MAGGY_TPU_PARTITION"] = str(rank)
    if rank == 0:
        env["MAGGY_TPU_BIND_PORT"] = str(port)
    stdout = stderr = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        stdout = open(
            os.path.join(args.log_dir, f"rank{rank}.g{generation}{tag}.out"), "wb"
        )
        stderr = open(
            os.path.join(args.log_dir, f"rank{rank}.g{generation}{tag}.err"), "wb"
        )
    proc = subprocess.Popen(
        [sys.executable, args.script, *args.script_args],
        env=env,
        stdout=stdout,
        stderr=stderr,
    )
    if stdout is not None:
        stdout.close()
        stderr.close()
    return proc


def _spawn_generation(args, base_env, generation: int):
    """Start all ranks for one generation. Fresh driver/coordinator ports per
    generation: the previous generation's sockets may linger in TIME_WAIT.
    Returns (procs, env_gen, port) so single ranks can be respawned into the
    same generation (--respawn)."""
    port = _free_port()
    env_gen = dict(base_env)
    env_gen.update(
        {
            "MAGGY_TPU_DRIVER": f"{args.host}:{port}",
            "MAGGY_TPU_GENERATION": str(generation),
        }
    )
    if args.global_mesh:
        env_gen["MAGGY_TPU_COORDINATOR"] = f"{args.host}:{_free_port()}"

    procs = {}
    for rank in range(args.workers):
        procs[rank] = _spawn_rank(args, env_gen, generation, rank, port)
    return procs, env_gen, port


def _terminate_all(procs, grace: float = 5.0) -> None:
    """SIGTERM then SIGKILL — ranks blocked in a wedged collective (their peer
    just died) may never reach a Python signal handler."""
    for proc in procs.values():
        if proc.poll() is None:
            proc.terminate()
    deadline = time.time() + grace
    for proc in procs.values():
        if proc.poll() is None:
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
    for proc in procs.values():
        if proc.poll() is None:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--workers", type=int, default=2, help="total processes")
    parser.add_argument("--host", default="127.0.0.1", help="driver host")
    parser.add_argument(
        "--global-mesh",
        action="store_true",
        help="export a jax.distributed coordinator so the script can call "
        "maggy_tpu.initialize_data_plane() and form ONE mesh over all "
        "processes (the multi-host data plane); without it each process "
        "keeps a host-local backend",
    )
    parser.add_argument(
        "--elastic",
        type=int,
        default=0,
        metavar="MAX_RESTARTS",
        help="on any rank death, restart the whole generation (all ranks, "
        "same experiment dir) up to MAX_RESTARTS times; scripts resume "
        "from their latest checkpoint",
    )
    parser.add_argument(
        "--respawn",
        type=int,
        default=0,
        metavar="MAX_RESPAWNS",
        help="on a WORKER rank death, respawn just that rank into the live "
        "experiment (up to MAX_RESPAWNS total) — worker capacity recovery "
        "for HPO/ablation trial workers, which re-register with the "
        "running driver and keep serving trials. Driver (rank 0) death "
        "still tears the run down (or restarts it under --elastic).",
    )
    parser.add_argument(
        "--log-dir",
        default=None,
        help="capture each rank's stdout/stderr to "
        "LOG_DIR/rank<r>.g<generation>.{out,err} instead of inheriting "
        "the launcher's streams",
    )
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if args.workers < 1:
        parser.error("--workers must be >= 1")
    if args.elastic < 0:
        parser.error("--elastic must be >= 0")

    base_env = dict(os.environ)
    base_env.update(
        {
            "MAGGY_TPU_SECRET": secrets.token_hex(16),
            "MAGGY_TPU_NUM_EXECUTORS": str(args.workers),
        }
    )
    if args.elastic:
        # every generation must land in the same experiment directory or
        # checkpoints written by generation g are invisible to g+1
        base_env.setdefault(
            "MAGGY_TPU_APP_ID", f"application_{int(time.time())}_0001"
        )
        base_env.setdefault("MAGGY_TPU_RUN_ID", "1")

    generation = 0
    procs, env_gen, port = _spawn_generation(args, base_env, generation)
    exit_code = 0
    respawns_used = 0
    try:
        remaining = dict(procs)
        while remaining:
            restart = failed = False
            for rank in list(remaining):
                if rank not in remaining:
                    continue  # removed by the driver-done wind-down below
                code = remaining[rank].poll()
                if code is None:
                    continue
                del remaining[rank]
                if code == 0:
                    if rank == 0:
                        # the driver finished the experiment: workers have
                        # nothing left to serve (a respawned trial worker may
                        # even be stuck in its connect-retry window against
                        # the now-closed server) — wind them down
                        deadline = time.time() + 10
                        while remaining and time.time() < deadline:
                            for r in list(remaining):
                                if remaining[r].poll() is not None:
                                    del remaining[r]
                            time.sleep(0.1)
                        if remaining:
                            print(
                                f"[maggy_tpu.run] driver done; terminating "
                                f"lingering worker rank(s) {sorted(remaining)}",
                                file=sys.stderr,
                            )
                            _terminate_all(remaining)
                            remaining = {}
                    continue
                if rank != 0 and respawns_used < args.respawn and 0 in remaining:
                    # the driver is still up: put this worker's capacity back
                    # (it re-registers with a fresh attempt nonce; the driver
                    # frees any trial it was holding). With the driver gone
                    # there is nothing to rejoin — fall through to teardown.
                    respawns_used += 1
                    print(
                        f"[maggy_tpu.run] worker rank {rank} exited with "
                        f"{code}; respawning into the live experiment "
                        f"({args.respawn - respawns_used} respawn(s) left)",
                        file=sys.stderr,
                    )
                    proc = _spawn_rank(
                        args, env_gen, generation, rank, port,
                        tag=f".r{respawns_used}",
                    )
                    procs[rank] = proc
                    remaining[rank] = proc
                    continue
                if generation < args.elastic:
                    print(
                        f"[maggy_tpu.run] rank {rank} exited with {code}; "
                        f"restarting generation {generation} -> {generation + 1} "
                        f"({args.elastic - generation} restart(s) left)",
                        file=sys.stderr,
                    )
                    restart = True
                else:
                    # fail fast: a dead driver would otherwise leave workers
                    # spinning in their connect-retry window (and surviving
                    # ranks of a global mesh wedged in collectives)
                    print(
                        f"[maggy_tpu.run] rank {rank} exited with {code}; "
                        "terminating remaining ranks",
                        file=sys.stderr,
                    )
                    exit_code = exit_code or code
                    failed = True
                break
            if failed:
                break
            if restart:
                _terminate_all(procs)
                generation += 1
                procs, env_gen, port = _spawn_generation(args, base_env, generation)
                remaining = dict(procs)
                continue
            time.sleep(0.1)
    except KeyboardInterrupt:
        exit_code = 130
    finally:
        _terminate_all(procs, grace=5.0)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
