"""Multi-process experiment launcher.

    python -m maggy_tpu.run --workers 3 my_script.py [script args...]

Starts ``my_script.py`` once as the driver (process 0) and ``workers - 1``
times as pod workers, wiring MAGGY_TPU_ROLE / DRIVER / SECRET / PARTITION /
BIND_PORT so the script's ``lagom(train_fn, DistributedConfig(...))`` call
forms one experiment across the processes (core/pod.py execution model). On a
real pod, run the equivalent: start the same script on every host with these
variables pointing at host 0.

The script must pass ``num_executors=<workers>`` (or leave it to default to
``jax.process_count()``) and may use ``data_plane="local"`` for independent
per-host replicas or initialize ``jax.distributed`` up front for one global
mesh.
"""

from __future__ import annotations

import argparse
import os
import secrets
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--workers", type=int, default=2, help="total processes")
    parser.add_argument("--host", default="127.0.0.1", help="driver host")
    parser.add_argument(
        "--global-mesh",
        action="store_true",
        help="export a jax.distributed coordinator so the script can call "
        "maggy_tpu.initialize_data_plane() and form ONE mesh over all "
        "processes (the multi-host data plane); without it each process "
        "keeps a host-local backend",
    )
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if args.workers < 1:
        parser.error("--workers must be >= 1")

    port = _free_port()
    secret = secrets.token_hex(16)
    base_env = dict(os.environ)
    base_env.update(
        {
            "MAGGY_TPU_DRIVER": f"{args.host}:{port}",
            "MAGGY_TPU_SECRET": secret,
            "MAGGY_TPU_NUM_EXECUTORS": str(args.workers),
        }
    )
    if args.global_mesh:
        base_env["MAGGY_TPU_COORDINATOR"] = f"{args.host}:{_free_port()}"

    procs = []
    for rank in range(args.workers):
        env = dict(base_env)
        env["MAGGY_TPU_ROLE"] = "driver" if rank == 0 else "worker"
        env["MAGGY_TPU_PARTITION"] = str(rank)
        if rank == 0:
            env["MAGGY_TPU_BIND_PORT"] = str(port)
        procs.append(
            subprocess.Popen(
                [sys.executable, args.script, *args.script_args], env=env
            )
        )

    exit_code = 0
    try:
        remaining = dict(enumerate(procs))
        while remaining:
            import time

            for rank in list(remaining):
                code = remaining[rank].poll()
                if code is None:
                    continue
                del remaining[rank]
                if code != 0:
                    print(
                        f"[maggy_tpu.run] rank {rank} exited with {code}; "
                        "terminating remaining ranks",
                        file=sys.stderr,
                    )
                    exit_code = exit_code or code
                    # fail fast: a dead driver would otherwise leave workers
                    # spinning in their connect-retry window
                    for other in remaining.values():
                        other.terminate()
            time.sleep(0.1)
    except KeyboardInterrupt:
        exit_code = 130
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
