"""Cross-cutting utilities.

Covers the reference ``maggy/util.py`` capabilities the TPU build needs:
return-value validation/persistence (util.py:159-199), signature-based kwarg
injection (trial_executor.py:166-179 semantics, hoisted here so every executor
shares it), run-id bookkeeping, and an ASCII progress bar (util.py:79-94).
"""

from __future__ import annotations

import inspect
import json
import logging
import os
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from maggy_tpu import constants, exceptions


def force_cpu() -> None:
    """Pin JAX to the CPU backend (env var + config + dropping the
    accelerator plugin's backend factory — belt and braces against plugins
    that re-assert their platform). Must run before any backend use.

    Dropping the factory matters on this image: the tunnel plugin registers
    at interpreter start and its backend *init* can hang forever when the
    transport is wedged — observed even in env/config-pinned CPU processes.
    With the factory gone, backends() cannot touch it at all."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # very old jax without the option — env var still set
        pass
    try:
        from jax._src import xla_bridge as _xb

        if not _xb.backends_are_initialized():
            _xb._backend_factories.pop("axon", None)
    except Exception:  # private API drift: env+config pins still apply
        pass


def pin_cpu_if_requested() -> None:
    """Honor ``JAX_PLATFORMS=cpu`` even on images whose accelerator plugin
    overrides the env var. Must run before any JAX backend use; examples and
    bench call it right after import."""
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        force_cpu()


def backend_alive(probe_timeout: float = 120.0) -> bool:
    """Probe whether JAX backend init completes, in a subprocess so a wedged
    accelerator transport cannot hang the caller. Bounded even against a child
    stuck in uninterruptible I/O (kill + short bounded wait, then give up).
    Returns True without probing when CPU is already requested."""
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        return True
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return proc.wait(timeout=probe_timeout) == 0
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass  # D-state child; abandon it rather than block
        return False


_compile_cache_enabled = False


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Turn on JAX's persistent compilation cache (idempotent).

    Every Trainer instance jits its own step closure, so N same-geometry HPO
    trials would otherwise pay N full XLA compiles (~25s each on a TPU
    tunnel). The persistent cache collapses those to one compile per
    geometry, shared across trials, Trainer instances, AND processes — the
    TPU-native analogue of the reference reusing one hot torch module across
    trials. Called from TrainContext.create; MAGGY_TPU_COMPILE_CACHE_DIR
    overrides the location, MAGGY_TPU_COMPILE_CACHE=0 disables.

    Returns the cache dir when enabled, else None."""
    global _compile_cache_enabled
    forced = os.environ.get("MAGGY_TPU_COMPILE_CACHE")
    if forced in ("0", "false"):
        return None
    cache_dir = cache_dir or os.environ.get(
        "MAGGY_TPU_COMPILE_CACHE_DIR",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "maggy_tpu", "xla_cache",
        ),
    )
    if _compile_cache_enabled:
        # report the ACTIVE directory — a later call with a different request
        # does not reconfigure a live cache
        import jax

        return jax.config.jax_compilation_cache_dir
    try:
        import jax

        # TPU only by default: XLA:CPU AOT cache reloads warn about machine-
        # feature mismatches (possible SIGILL); MAGGY_TPU_COMPILE_CACHE=1
        # force-enables for other backends (tests)
        if forced != "1" and jax.default_backend() != "tpu":
            return None
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _compile_cache_enabled = True
        return cache_dir
    except Exception as e:  # noqa: BLE001 - cache is an optimization, never fatal
        logging.getLogger(__name__).warning(
            "Could not enable the persistent compilation cache: %s", e
        )
        return None


def shard_map(
    f: Callable,
    *,
    mesh=None,
    in_specs,
    out_specs,
    check_vma: bool = True,
    axis_names=None,
):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map`` (``check_vma``, manual axes given
    positively via ``axis_names``); older releases (<= 0.4.x) only ship
    ``jax.experimental.shard_map.shard_map`` where the flag is spelled
    ``check_rep`` and partial-manual mode is the complement ``auto=`` set.
    Every manual-collective site in the codebase (pipeline 1F1B, ring /
    ulysses attention, sharded flash) routes through here so the whole
    parallel tier works on both."""
    import jax

    if hasattr(jax, "shard_map"):
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        if mesh is not None:
            kwargs["mesh"] = mesh
        return jax.shard_map(
            f, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma,
            **kwargs,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    if mesh is None:
        # mesh=None means "inherit the context mesh" on new jax; the old
        # API requires it explicitly, so recover the ambient one
        from jax._src.mesh import thread_resources

        mesh = thread_resources.env.physical_mesh
        if mesh.empty:
            raise TypeError(
                "shard_map(mesh=None) needs an ambient mesh on this jax "
                "version (enter `with mesh:` first)"
            )
    auto = (
        frozenset()
        if axis_names is None
        else frozenset(mesh.axis_names) - frozenset(axis_names)
    )
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, auto=auto,
    )


def set_mesh(mesh):
    """Context manager making ``mesh`` ambient: ``jax.set_mesh`` on new jax,
    the Mesh's own context manager on older releases."""
    import jax

    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def inject_kwargs(fn: Callable, available: Dict[str, Any]) -> Dict[str, Any]:
    """Inspect ``fn``'s signature and return only the kwargs it asks for.

    This is the mechanism behind the "oblivious training function": the same
    ``train_fn`` may request any subset of ``{model, dataset, hparams, reporter,
    mesh, train_ctx, ...}`` and runs unchanged in every execution mode
    (reference trial_executor.py:166-179).
    """
    sig = inspect.signature(fn)
    params = sig.parameters
    fn_name = getattr(fn, "__name__", "train_fn")
    # positional-only params can never be injected (we always call with
    # keywords), whether or not the name matches something available
    pos_only = [
        n for n, p in params.items() if p.kind == inspect.Parameter.POSITIONAL_ONLY
    ]
    if pos_only:
        raise exceptions.BadArgumentsError(
            fn_name,
            f"has positional-only parameter(s) {pos_only}; the framework "
            "injects arguments by keyword — drop the '/' marker.",
        )
    missing = [
        name
        for name, p in params.items()
        if p.default is inspect.Parameter.empty
        and p.kind
        in (inspect.Parameter.POSITIONAL_OR_KEYWORD, inspect.Parameter.KEYWORD_ONLY)
        and name not in available
    ]
    if missing:
        raise exceptions.BadArgumentsError(
            fn_name,
            f"asks for parameter(s) {missing} the framework does not inject "
            f"here; available: {sorted(available)}.",
        )
    if any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values()):
        return dict(available)
    return {k: v for k, v in available.items() if k in params}


def normalize_return_val(
    return_val: Any,
    optimization_key: str,
    require_metric: bool = True,
) -> tuple:
    """Map a train_fn return value to ``(metric, outputs)``.

    Numeric returns are used directly; dict returns must contain the
    optimization key with a numeric value. ``require_metric=False``
    (evaluator role: free-form evaluation outputs) additionally accepts dicts
    without the key, non-dict non-numeric values (persisted as
    ``{"value": ...}``), and None — metric is then None.
    """
    if isinstance(return_val, constants.USER_FCT.NUMERIC_TYPES) and not isinstance(
        return_val, bool
    ):
        return float(return_val), {optimization_key: float(return_val)}
    if isinstance(return_val, dict):
        if optimization_key not in return_val:
            if require_metric:
                raise exceptions.ReturnTypeError(optimization_key, return_val)
            return None, return_val
        metric = return_val[optimization_key]
        if not isinstance(metric, constants.USER_FCT.NUMERIC_TYPES) or isinstance(
            metric, bool
        ):
            raise exceptions.MetricTypeError(optimization_key, metric)
        return float(metric), return_val
    if not require_metric:
        # free-form evaluation artifacts (lists, strings, None) persist as-is
        return None, ({} if return_val is None else {"value": return_val})
    raise exceptions.ReturnTypeError(optimization_key, return_val)


def persist_outputs(
    outputs: dict, metric: Optional[float], log_dir: Optional[str]
) -> None:
    """Write ``.outputs.json`` (+ ``.metric`` when one exists) into a trial/
    worker dir; best-effort. Routed through the env seam so remote roots
    (gs://, memory://) receive the artifacts instead of a literal local
    'gs:/...' directory."""
    if not log_dir:
        return
    import posixpath

    from maggy_tpu.core.env import EnvSing

    env = EnvSing.get_instance()
    try:
        env.mkdir(log_dir)
        env.dump(
            json.dumps(_jsonify(outputs), sort_keys=True),
            posixpath.join(log_dir, constants.OUTPUTS_FILE),
        )
        if metric is not None:
            env.dump(repr(metric), posixpath.join(log_dir, constants.METRIC_FILE))
    except Exception as e:  # noqa: BLE001 - cloud FS raise non-OSError types
        logging.getLogger(__name__).warning(
            "Could not persist trial outputs to %s: %s", log_dir, e
        )


def handle_return_val(
    return_val: Any,
    log_dir: Optional[str],
    optimization_key: str,
    log_file: Optional[str] = None,
    require_metric: bool = True,
) -> Optional[float]:
    """Validate a train_fn return value and persist outputs (reference
    util.py:159-199): :func:`normalize_return_val` + :func:`persist_outputs`."""
    metric, outputs = normalize_return_val(return_val, optimization_key, require_metric)
    persist_outputs(outputs, metric, log_dir)
    return metric


def _jsonify(obj: Any) -> Any:
    """Best-effort conversion of numpy/jax scalars and arrays for JSON dumps."""
    if isinstance(obj, dict):
        return {str(k): _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def progress_bar(done: int, total: int, width: int = 30) -> str:
    """ASCII progress bar (reference util.py:79-94)."""
    total = max(total, 1)
    frac = min(done / total, 1.0)
    filled = int(width * frac)
    return "[" + "=" * filled + ">" + "-" * (width - filled) + f"] {done}/{total}"


def new_app_id() -> str:
    """Fabricate an application id in the reference's format
    (experiment_python.py:71-72)."""
    return "application_{}_0001".format(int(time.time()))


def seed_everything(seed: int) -> np.random.Generator:
    """Return a seeded numpy Generator; JAX randomness is functional (jax.random.key)
    so nothing global needs patching — the idiomatic replacement for the reference's
    torch/np/random/cudnn seeding (torch_dist_executor.py:247-285)."""
    return np.random.default_rng(seed)


class RunRegistry:
    """Per-process experiment run-id bookkeeping (reference util.py:216-290)."""

    def __init__(self):
        self._run_ids: Dict[str, int] = {}

    def next_run_id(self, app_id: str) -> int:
        rid = self._run_ids.get(app_id, 0) + 1
        self._run_ids[app_id] = rid
        return rid

    def observe(self, app_id: str, run_id: int) -> None:
        """Record an externally-assigned run id (env-pinned by the elastic
        launcher) so later next_run_id calls continue after it."""
        self._run_ids[app_id] = max(self._run_ids.get(app_id, 0), int(run_id))


RUNS = RunRegistry()
