"""Worker-side metric/log reporter.

Capability parity with the reference ``maggy/core/reporter.py`` (reporter.py:30-170):
a thread-safe store that the user's ``train_fn`` broadcasts metrics into, that the
heartbeat thread drains toward the driver, and that turns a driver-issued STOP into an
``EarlyStopException`` raised at the next ``broadcast()`` call — the mechanism that
lets early stopping interrupt a Python-level training loop between jitted steps
(SURVEY.md §7 "Early stopping inside jitted training loops").
"""

from __future__ import annotations

import builtins
import contextlib
import threading
from typing import Any, List, Optional

import numpy as np

from maggy_tpu import exceptions

# ---------------------------------------------------------------- print capture
#
# Reference parity: the trial executor hijacks ``print`` so a train_fn's
# prints ship to the driver with the heartbeat logs
# (trial_executor.py:93-103). The reference swaps builtins.print per Spark
# task PROCESS; our executors are THREADS in one process, so the tee is
# installed once and routes through a thread-local — concurrent trials
# capture into their own reporters without racing on builtins.

_print_local = threading.local()
_tee_lock = threading.Lock()
_active_captures = 0
_saved_print = None  # whatever print was when the tee went in (install time)
# recursion-proof fallback if a foreign hook holds a stale tee reference
_builtin_print = builtins.print


def _tee_print(*args, **kwargs):
    # reentrancy guard: if a foreign wrapper captured a stale tee reference
    # and a NEW capture saved that wrapper as _saved_print, the chain
    # tee -> wrapper -> stale tee would recurse forever without this
    if getattr(_print_local, "in_tee", False):
        _builtin_print(*args, **kwargs)
        return
    _print_local.in_tee = True
    try:
        reporter = getattr(_print_local, "reporter", None)
        if reporter is not None and kwargs.get("file") is None:
            try:
                reporter.log(
                    kwargs.get("sep", " ").join(str(a) for a in args), verbose=False
                )
            except Exception:  # noqa: BLE001 - printing must never raise
                pass
        (_saved_print or _builtin_print)(*args, **kwargs)
    finally:
        _print_local.in_tee = False


@contextlib.contextmanager
def capture_prints(reporter: "Reporter"):
    """Route this thread's ``print()`` calls into ``reporter.log`` (they
    still reach stdout). Used around train_fn execution.

    Scope note vs the reference's process-wide swap: only THIS thread's
    prints are captured — threads a train_fn spawns itself (data loaders,
    callbacks) go to stdout only. That's the price of running executors as
    threads in one process; spawned workers should log via ``reporter``.

    Install/uninstall is reference-counted: the tee wraps whatever
    ``builtins.print`` is when the FIRST capture enters (so a hook installed
    before us keeps working), and ``builtins.print`` is restored when the
    LAST capture exits — unless someone wrapped the tee in the meantime, in
    which case their chain is left untouched."""
    global _active_captures, _saved_print
    with _tee_lock:
        if _active_captures == 0:
            _saved_print = builtins.print
            builtins.print = _tee_print
        _active_captures += 1
    prev = getattr(_print_local, "reporter", None)
    _print_local.reporter = reporter
    try:
        yield
    finally:
        _print_local.reporter = prev
        with _tee_lock:
            _active_captures -= 1
            if _active_captures == 0 and builtins.print is _tee_print:
                # only on an ACTUAL restore: if a foreign hook wrapped the
                # tee we leave their chain alone — including _saved_print,
                # which the orphaned tee still forwards through (dropping it
                # would silently bypass any hook installed before us)
                builtins.print = _saved_print
                _saved_print = None


class Reporter:
    """Thread-safe metric and log buffer for one executor."""

    def __init__(self, log_file: Optional[str] = None, partition_id: int = 0, print_hook=None):
        self._lock = threading.RLock()
        self._metric: Optional[float] = None
        self._step: int = -1
        self._early_stop = False
        self._logs: List[str] = []
        self._log_file = log_file
        # remote roots (gs://, memory://): object stores can't append, so
        # buffer the whole log and publish once at close() via the env seam
        self._remote_log = bool(log_file) and "://" in str(log_file)
        self._log_history: List[str] = []
        self._remote_truncated = 0
        self._remote_logged = 0
        # publish sequencing: snapshots are taken under self._lock but
        # DUMPED outside it (network IO must not stall broadcasts); the seq
        # guard stops a preempted older snapshot from overwriting a newer one
        self._publish_lock = threading.Lock()
        self._publish_seq = 0
        self._published_seq = 0
        self._remote_closed = False
        self._log_fd = (
            open(log_file, "a", buffering=1)
            if log_file and not self._remote_log
            else None
        )
        self.partition_id = partition_id
        self.trial_id: Optional[str] = None
        self._print_hook = print_hook

    # ------------------------------------------------------------------ metrics

    def broadcast(self, metric: Any, step: Optional[int] = None) -> None:
        """Record a metric observation for the current trial.

        Validates metric and step types, enforces monotonically increasing steps,
        and raises :class:`EarlyStopException` when the driver flagged this trial
        (reference reporter.py:77-101).
        """
        with self._lock:
            if not isinstance(metric, (int, float, np.number)) or isinstance(metric, bool):
                raise exceptions.BroadcastMetricTypeError(metric)
            if step is not None and (not isinstance(step, (int, np.integer)) or isinstance(step, bool)):
                raise exceptions.BroadcastStepTypeError(metric, step)
            if step is None:
                step = self._step + 1
            step = int(step)
            if step <= self._step:
                raise exceptions.BroadcastStepValueError(metric, step, self._step)
            self._metric = float(metric)
            self._step = step
            if self._early_stop:
                # The flag stays set (cleared only by reset()) so a train_fn that
                # swallows the exception keeps being interrupted at every broadcast.
                raise exceptions.EarlyStopException(metric=self._metric)

    def get_data(self):
        """Drain pending logs and return ``(trial_id, metric, step, logs)`` for a
        heartbeat (reference reporter.py:137-142). One atomic read: trial_id and
        metric/step must come from the same trial, or a beat racing a trial
        boundary would attribute the old trial's metrics to the new one."""
        with self._lock:
            logs, self._logs = self._logs, []
            return self.trial_id, self._metric, self._step, logs

    def get_metric(self):
        with self._lock:
            return self._metric

    # ------------------------------------------------------------------ early stop

    def early_stop(self) -> None:
        with self._lock:
            self._early_stop = True

    def reset(self, trial_id: Optional[str] = None) -> None:
        """Reset per-trial state before a new trial starts (reference reporter.py:56-74)."""
        with self._lock:
            self._metric = None
            self._step = -1
            self._early_stop = False
            self.trial_id = trial_id

    # ------------------------------------------------------------------ logging

    # object stores can't append: the remote log republishes the accumulated
    # buffer every _REMOTE_FLUSH_EVERY lines (so a crashed executor loses at
    # most one window, not the whole log) and caps memory at
    # _REMOTE_MAX_LINES with an explicit truncation notice
    _REMOTE_FLUSH_EVERY = 256
    _REMOTE_MAX_LINES = 20_000

    def log(self, message: str, verbose: bool = True) -> None:
        """Buffer a log line for shipping to the driver; optionally echo locally."""
        line = str(message)
        snapshot = None
        with self._lock:
            self._logs.append(line)
            if self._log_fd:
                self._log_fd.write(line.rstrip("\n") + "\n")
            elif self._remote_log and not self._remote_closed:
                self._log_history.append(line.rstrip("\n"))
                self._remote_logged += 1  # monotonic: the capped buffer's
                # length pins at MAX_LINES, which would otherwise stop the
                # periodic flush condition from ever firing again
                if len(self._log_history) > self._REMOTE_MAX_LINES:
                    dropped = len(self._log_history) - self._REMOTE_MAX_LINES
                    self._log_history = self._log_history[dropped:]
                    self._remote_truncated += dropped
                if self._remote_logged % self._REMOTE_FLUSH_EVERY == 0:
                    snapshot = self._remote_snapshot()
        if snapshot is not None:
            self._publish_remote(*snapshot)  # network IO outside the lock
        if verbose and self._print_hook:
            self._print_hook(line)

    def _remote_snapshot(self):
        """(seq, content) under self._lock; seq orders concurrent publishes."""
        head = (
            [f"... [{self._remote_truncated} earlier lines truncated] ..."]
            if self._remote_truncated
            else []
        )
        self._publish_seq += 1
        return self._publish_seq, "\n".join(head + self._log_history) + "\n"

    def _publish_remote(self, seq: int, content: str) -> None:
        from maggy_tpu.core.env import EnvSing

        with self._publish_lock:
            if seq <= self._published_seq:
                return  # a newer snapshot already landed; never regress
            try:
                EnvSing.get_instance().dump(content, self._log_file)
                self._published_seq = seq
            except Exception:  # noqa: BLE001 - logs are best-effort
                pass

    def close(self) -> None:
        with self._lock:
            if self._log_fd:
                self._log_fd.close()
                self._log_fd = None
            snapshot = (
                self._remote_snapshot()
                if self._remote_log and self._log_history
                else None
            )
            self._log_history = []
            self._remote_closed = True  # later flushes must not republish a
            # near-empty buffer over the complete final log
        if snapshot is not None:
            self._publish_remote(*snapshot)
