"""Host-overlap subsystem (ISSUE 5, docs/performance.md): DevicePrefetcher
semantics + the fit overlap win, skip(n) resume fast paths, the lagged
metrics drain's broadcast contract, evaluate's single host sync, and the
check_host_sync lint."""

import textwrap
import time

import jax
import numpy as np
import optax
import pytest

from maggy_tpu import telemetry
from maggy_tpu.exceptions import EarlyStopException
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.reporter import Reporter
from maggy_tpu.train import DevicePrefetcher, TrainContext, skip_batches
from maggy_tpu.train.data import batch_iterator, synthetic_lm_batches


def _tiny_trainer(seed=0):
    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=seed)
    state = trainer.make_state(jax.random.key(0), next(
        synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=seed)
    ))
    return trainer, state, data


# ------------------------------------------------------------ DevicePrefetcher


def test_prefetcher_preserves_order_and_caps_consumption():
    pulled = {"n": 0}

    def src():
        i = 0
        while True:
            pulled["n"] += 1
            yield i
            i += 1

    pf = DevicePrefetcher(src(), put=lambda x: x * 10, depth=2, max_items=5)
    out = [next(pf) for _ in range(5)]
    assert out == [0, 10, 20, 30, 40]
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()
    # max_items bounds SOURCE consumption exactly: a shared iterator keeps
    # its position across consecutive fit calls
    assert pulled["n"] == 5


def test_prefetcher_relays_source_and_put_errors():
    def exploding():
        yield 1
        raise RuntimeError("loader died")

    pf = DevicePrefetcher(exploding(), put=lambda x: x, depth=2)
    assert next(pf) == 1
    with pytest.raises(RuntimeError, match="loader died"):
        next(pf)
    with pytest.raises(RuntimeError, match="loader died"):
        next(pf)  # terminal: the error sticks, no hang on an empty queue
    pf.close()

    pf2 = DevicePrefetcher(iter([1, 2]), put=lambda x: 1 / 0, depth=2)
    with pytest.raises(ZeroDivisionError):
        next(pf2)
    pf2.close()


def test_prefetcher_skip_delegates_before_start():
    it = batch_iterator({"x": np.arange(120).reshape(30, 4)}, 5, seed=2)
    pf = DevicePrefetcher(it, put=lambda b: b, depth=2)
    assert pf.skip(7) == 7
    assert it.batches_materialized == 0  # index advance, nothing gathered
    ref = batch_iterator({"x": np.arange(120).reshape(30, 4)}, 5, seed=2)
    skip_batches(ref, 7)
    np.testing.assert_array_equal(next(pf)["x"], next(ref)["x"])
    pf.close()


def test_prefetcher_records_telemetry():
    tel = telemetry.Telemetry(worker="t")
    pf = DevicePrefetcher(
        iter(range(4)), put=lambda x: x, depth=2, telemetry_recorder=tel
    )
    for _ in range(4):
        next(pf)
    pf.close()
    g = tel.snapshot()["gauges"]
    assert "input_wait_ms" in g and "prefetch_depth" in g
    spans = [e["name"] for e in tel.drain_events() if e["kind"] == "span"]
    assert spans.count("shard_batch") == 4


# --------------------------------------------------------------- skip(n) paths


def test_skip_batches_falls_back_to_next_for_generators():
    def gen():
        yield from range(10)

    g = gen()
    assert skip_batches(g, 3) == 3
    assert next(g) == 3
    assert skip_batches(g, 100) == 6  # short on exhaustion


def test_batch_iterator_skip_matches_next_across_epochs():
    arrays = {"x": np.arange(80).reshape(20, 4)}
    a = batch_iterator(arrays, 8, seed=7)  # 2 batches/epoch
    b = batch_iterator(arrays, 8, seed=7)
    for _ in range(11):
        next(a)
    assert b.skip(11) == 11
    assert b.batches_materialized == 0
    for _ in range(4):
        np.testing.assert_array_equal(next(a)["x"], next(b)["x"])


def test_native_loader_skip_avoids_gathers():
    from maggy_tpu.train.native_loader import NativeBatchLoader

    arrays = {"x": np.arange(4000).reshape(1000, 4)}
    a = NativeBatchLoader(arrays, 10, seed=3)
    b = NativeBatchLoader(arrays, 10, seed=3)
    try:
        for _ in range(250):
            next(a)
        assert b.skip(250) == 250
        for _ in range(3):
            np.testing.assert_array_equal(next(a)["x"], next(b)["x"])
        time.sleep(0.2)  # let the producer run ahead to its bound
        # only the pre-skip in-flight/queued batches plus the 3 consumed and
        # the refilled prefetch window were ever gathered — not 250
        assert b.gathers <= 12, b.gathers
    finally:
        a.close()
        b.close()


def test_fit_resume_skips_without_materializing(tmp_path):
    """ACCEPTANCE (satellite): fit(resume="auto") fast-forwards a skip()-
    capable loader by index — the skipped range is never gathered."""
    from maggy_tpu.train.checkpoint import Checkpointer

    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create("dp")
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (64, 16)).astype(np.int32)

    trainer, state, _ = _tiny_trainer()
    ckpt = Checkpointer(str(tmp_path / "ck"), async_save=False)
    loader = batch_iterator({"tokens": toks}, 8, seed=1)
    state, _ = trainer.fit(
        state, loader, num_steps=4, checkpointer=ckpt, checkpoint_every=2
    )
    assert ckpt.latest_step() == 4

    trainer2, state2, _ = _tiny_trainer()
    fresh = batch_iterator({"tokens": toks}, 8, seed=1)
    state2, out = trainer2.fit(
        state2, fresh, num_steps=10, checkpointer=ckpt, resume="auto"
    )
    ckpt.close()
    assert out["resumed_from"] == 4.0
    assert int(state2.step) == 10
    # 6 remaining steps materialized; the 4 skipped batches never were
    assert fresh.batches_materialized == 6, fresh.batches_materialized


# ------------------------------------------------------------- fit overlap win


def test_fit_overlap_wall_clock_is_max_not_sum():
    """ACCEPTANCE: with a sleep-based loader, fit through the prefetcher
    approaches max(loader, step) per step instead of loader + step."""
    trainer, state, data = _tiny_trainer()
    # compile once so neither timed run pays it
    state, _ = trainer.fit(state, data, num_steps=1, prefetch=0)

    sleep_s = 0.04

    def slow(src):
        while True:
            time.sleep(sleep_s)
            yield next(src)

    n = 10
    t0 = time.perf_counter()
    state, _ = trainer.fit(state, slow(data), num_steps=n, prefetch=0)
    t_sync = time.perf_counter() - t0
    t0 = time.perf_counter()
    state, _ = trainer.fit(state, slow(data), num_steps=n, prefetch=2)
    t_over = time.perf_counter() - t0
    # sync pays sleep + step serially every step; overlapped pays ~max of
    # the two. Demand a 1.25x margin — loose enough for CI noise, far above
    # anything a non-overlapping implementation can produce when the sleep
    # alone is >= 40ms/step of the budget.
    assert t_over < t_sync / 1.25, (t_sync, t_over)
    assert t_over < n * sleep_s * 1.8, (t_sync, t_over)


# -------------------------------------------------------- lagged metrics drain


class _RecordingReporter:
    def __init__(self):
        self.calls = []

    def broadcast(self, value, step=None):
        self.calls.append((value, step))


def test_fit_broadcasts_lag_bounded_and_monotonic():
    trainer, state, data = _tiny_trainer()
    rep = _RecordingReporter()
    tel = telemetry.Telemetry(worker="t")
    with telemetry.current(tel):
        state, _ = trainer.fit(
            state, data, num_steps=12, reporter=rep,
            report_every=2, metrics_window=2,
        )
    steps = [s for _, s in rep.calls]
    assert steps == sorted(steps) and len(set(steps)) == len(steps)
    assert all(np.isfinite(v) for v, _ in rep.calls)
    # every broadcast carries the step its metric was measured at, at most
    # `window` behind the boundary it was emitted from
    boundaries = [i + 1 for i in range(12) if (i + 1) % 2 == 0]
    assert len(rep.calls) >= len(boundaries) - 1  # first may defer (priming)
    lag = tel.snapshot()["gauges"]["metrics_lag"]
    assert 0 <= lag <= 2


def test_fit_window_zero_restores_synchronous_broadcasts():
    trainer, state, data = _tiny_trainer()
    rep = _RecordingReporter()
    state, _ = trainer.fit(
        state, data, num_steps=6, reporter=rep,
        report_every=2, metrics_window=0,
    )
    # fresh value at every boundary: steps are exactly the boundary steps
    assert [s for _, s in rep.calls] == [2, 4, 6]


def test_fit_early_stop_fires_through_lagged_drain():
    """ACCEPTANCE: the driver's early-stop flag still interrupts fit at a
    broadcast boundary with the lagged drain (the flag is what HPO
    executors set via heartbeat; EarlyStopException is the interrupt)."""
    trainer, state, data = _tiny_trainer()
    reporter = Reporter()
    reporter.early_stop()
    with pytest.raises(EarlyStopException):
        trainer.fit(
            state, data, num_steps=30, reporter=reporter,
            report_every=1, metrics_window=2,
        )
    # the interrupt landed within the lag bound of the first boundary that
    # had an aged ref: a 30-step run never completes
    _, metric, step, _ = reporter.get_data()
    assert step <= 2 + 2  # first primed boundary + window


# ----------------------------------------------------- evaluate's single sync


class _CountingScalar:
    """Device-scalar stand-in whose float() conversions are counted —
    on-device adds must NOT sync."""

    def __init__(self, val, counter):
        self.val = val
        self.counter = counter

    def __add__(self, other):
        return _CountingScalar(
            self.val + getattr(other, "val", other), self.counter
        )

    __radd__ = __add__

    def __float__(self):
        self.counter["n"] += 1
        return float(self.val)


def test_evaluate_accumulates_on_device_single_conversion():
    trainer, state, data = _tiny_trainer()
    trainer.evaluate(state, data, 1)  # compile
    real_step = trainer._eval_loss_step
    counter = {"n": 0}

    def wrapped(s, b):
        return _CountingScalar(np.asarray(real_step(s, b)), counter)

    trainer._eval_loss_step = wrapped
    try:
        res = trainer.evaluate(state, data, 5)
    finally:
        trainer._eval_loss_step = real_step
    assert np.isfinite(res["loss"])
    # regression guard: the old loop float()ed every batch (5 syncs)
    assert counter["n"] == 1, counter


# -------------------------------------------------------- check_host_sync lint


def _lint():
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_host_sync", os.path.join(repo, "tools", "check_host_sync.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_host_sync_lint_flags_and_allowlists():
    lint = _lint()
    bad = textwrap.dedent(
        """
        def f(xs, m):
            for x in xs:  # hot-loop
                a = float(x)
                b = int(x)
                c = np.asarray(x)
                d = x.item()
        """
    )
    hits = lint.find_violations(bad, "<bad>")
    assert len(hits) == 4, hits

    ok = textwrap.dedent(
        """
        def f(xs):  # hot-loop
            for x in xs:
                a = float(x)  # sync: ok — lagged ref
            return np.asarray(xs)  # sync: ok — outside-loop epilogue
        """
    )
    assert lint.find_violations(ok, "<ok>") == []

    unmarked = "def f(xs):\n    return [float(x) for x in xs]\n"
    assert lint.find_violations(unmarked, "<unmarked>") == []

    assert lint.has_hot_region(ok, "<ok>", "f")
    assert not lint.has_hot_region(unmarked, "<unmarked>", "f")


def test_host_sync_lint_tree_clean():
    """tools/check_host_sync.py runs clean over maggy_tpu/ (wired into
    tier-1, beside the exception-hygiene / bare-print / docs-nav lints) —
    and the required hot-loop regions are present."""
    import os

    lint = _lint()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint.check_tree(os.path.join(repo, "maggy_tpu"))
    assert violations == [], violations


def test_host_sync_lint_detects_missing_required_region(tmp_path):
    lint = _lint()
    fake = tmp_path / "maggy_tpu" / "serve"
    fake.mkdir(parents=True)
    (fake / "engine.py").write_text("def step(self):\n    return 1\n")
    violations = lint.check_tree(str(tmp_path / "maggy_tpu"))
    assert any("required hot-loop marker" in what for _, _, what in violations)
