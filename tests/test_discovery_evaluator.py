"""Driver discovery registry + dedicated evaluator role.

Discovery: the driver advertises {host, port, secret} under the experiment
root so pod workers with only an app id and shared storage can connect — the
storage-seam analogue of the reference registering its driver with Hopsworks
REST (environment/hopsworks.py:136-190). Evaluator: the last worker becomes a
dedicated evaluation role outside the training group (reference
tf_dist_executor.py:138-144).
"""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from maggy_tpu import experiment
from maggy_tpu.config import DistributedConfig
from maggy_tpu.core.env.base import BaseEnv

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# --------------------------------------------------------------------- registry


def test_registry_round_trip(tmp_path):
    env = BaseEnv(str(tmp_path))
    assert env.lookup_driver("app_x") is None
    env.register_driver("app_x", 3, "host-a", 4242, secret="s3cr3t")
    rec = env.lookup_driver("app_x")
    assert rec["host"] == "host-a" and rec["port"] == 4242
    assert rec["secret"] == "s3cr3t" and rec["run_id"] == 3
    # re-registration (driver restart) overwrites
    env.register_driver("app_x", 4, "host-b", 4343, secret="new")
    assert env.lookup_driver("app_x")["port"] == 4343
    env.unregister_driver("app_x")
    assert env.lookup_driver("app_x") is None


def test_worker_role_from_registry(tmp_env, monkeypatch):
    """A worker with only MAGGY_TPU_APP_ID resolves address AND secret from
    the registry; with MAGGY_TPU_DRIVER set it still pulls the secret."""
    from maggy_tpu.core import pod

    tmp_env.register_driver("app_d", 1, "driverhost", 5151, secret="tops")
    cfg = DistributedConfig(num_executors=2)

    monkeypatch.setenv("MAGGY_TPU_ROLE", "worker")
    monkeypatch.setenv("MAGGY_TPU_APP_ID", "app_d")
    monkeypatch.delenv("MAGGY_TPU_DRIVER", raising=False)
    monkeypatch.delenv("MAGGY_TPU_SECRET", raising=False)
    role = pod.worker_role(cfg)
    assert role[:3] == ("driverhost", 5151, "tops") and role.via_registry

    # explicit address + registry secret (review finding: the env-var address
    # path must not disable the registry secret fallback)
    monkeypatch.setenv("MAGGY_TPU_DRIVER", "10.0.0.9:6161")
    assert pod.worker_role(cfg)[:3] == ("10.0.0.9", 6161, "tops")


def test_explicit_worker_without_driver_raises(tmp_env, monkeypatch):
    """MAGGY_TPU_ROLE=worker with no address and no registry record must fail
    loudly instead of silently becoming a second driver (review finding)."""
    from maggy_tpu.core import pod

    monkeypatch.setenv("MAGGY_TPU_ROLE", "worker")
    monkeypatch.setenv("MAGGY_TPU_APP_ID", "app_missing")
    monkeypatch.setenv("MAGGY_TPU_CONNECT_TIMEOUT", "0.5")
    monkeypatch.delenv("MAGGY_TPU_DRIVER", raising=False)
    monkeypatch.delenv("MAGGY_TPU_SECRET", raising=False)
    with pytest.raises(RuntimeError, match="no driver address"):
        pod.worker_role(DistributedConfig(num_executors=2))


def test_local_records_excluded_from_worker_bootstrap(tmp_env, monkeypatch):
    """Non-pod drivers register scope='local' (for monitor auto-attach); pod
    worker discovery must ignore those records — a loopback address would
    misdirect a remote worker to its own machine."""
    from maggy_tpu.core import pod

    tmp_env.register_driver("app_l", 1, "127.0.0.1", 7777, secret="s",
                            scope="local")
    assert pod.discover_driver("app_l") is None  # worker bootstrap: ignored
    # ...and an explicit worker that only has this local record fails loudly
    monkeypatch.setenv("MAGGY_TPU_ROLE", "worker")
    monkeypatch.setenv("MAGGY_TPU_APP_ID", "app_l")
    monkeypatch.setenv("MAGGY_TPU_CONNECT_TIMEOUT", "0.5")
    monkeypatch.delenv("MAGGY_TPU_DRIVER", raising=False)
    monkeypatch.delenv("MAGGY_TPU_SECRET", raising=False)
    with pytest.raises(RuntimeError, match="no driver address"):
        pod.worker_role(DistributedConfig(num_executors=2))


def test_local_run_registers_for_monitor_and_cleans_up(tmp_env):
    """Every driver advertises itself while running (monitor auto-attach) and
    unregisters on stop."""
    from maggy_tpu import monitor as monitor_mod

    seen = {}

    def train(ctx, reporter):
        # mid-run: the registry record exists and resolve_target finds it
        recs = tmp_env.list_drivers()
        seen["recs"] = recs
        if recs:
            seen["target"] = monitor_mod.resolve_target(tmp_env)
        return {"metric": 1.0}

    experiment.lagom(
        train,
        DistributedConfig(
            num_executors=1, sharding="dp", data_plane="local", hb_interval=0.05
        ),
    )
    assert seen["recs"] and seen["recs"][0]["scope"] == "local"
    host, port, secret = seen["target"]
    assert host == "127.0.0.1" and port > 0 and secret
    # unregistered after the experiment
    assert tmp_env.list_drivers() == []


DISCOVERY_WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig

    def train(hparams, reporter, ctx):
        return {{"metric": 2.0}}

    result = experiment.lagom(
        train,
        DistributedConfig(num_executors=2, sharding="dp",
                          data_plane="local", hb_interval=0.05),
    )
    print("WORKER-DONE", result)
    """
).format(repo=REPO)


def test_pod_worker_discovers_driver(tmp_env, tmp_path):
    """Full flow: pod driver registers; a second process finds it with ONLY
    MAGGY_TPU_APP_ID + the shared root — no address/secret env vars."""
    result_holder = {}

    def train(hparams, reporter, ctx):
        return {"metric": 2.0}

    config = DistributedConfig(
        num_executors=2,
        sharding="dp",
        data_plane="local",
        driver_addr="127.0.0.1:auto",  # placeholder: flags pod mode
        hb_interval=0.05,
    )

    t = threading.Thread(
        target=lambda: result_holder.update(result=experiment.lagom(train, config))
    )
    t.start()
    deadline = time.time() + 30
    driver = None
    while time.time() < deadline:
        driver = experiment.CURRENT_DRIVER
        if driver is not None and driver.server is not None and driver.server.port:
            break
        time.sleep(0.05)
    assert driver is not None and driver.pod_mode

    # the driver advertised itself; wait for the record
    deadline = time.time() + 10
    while time.time() < deadline and tmp_env.lookup_driver(driver.app_id) is None:
        time.sleep(0.05)
    rec = tmp_env.lookup_driver(driver.app_id)
    assert rec is not None and rec["secret"] == driver.server.secret

    script = tmp_path / "worker.py"
    script.write_text(DISCOVERY_WORKER)
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("MAGGY_TPU_DRIVER", "MAGGY_TPU_SECRET")
    }
    env.update(
        {
            "MAGGY_TPU_ROLE": "worker",
            "MAGGY_TPU_APP_ID": driver.app_id,
            "MAGGY_TPU_PARTITION": "1",
            # shared storage: same experiment root as the driver's Env
            "MAGGY_TPU_LOG_ROOT": tmp_env.root,
        }
    )
    # the registry records gethostname(); map it to loopback for the connect
    env["MAGGY_TPU_DRIVER"] = f"127.0.0.1:{rec['port']}"
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "WORKER-DONE" in proc.stdout

    t.join(timeout=60)
    assert not t.is_alive()
    assert result_holder["result"]["num_workers"] == 2
    # driver unregisters on stop
    assert tmp_env.lookup_driver(driver.app_id) is None


# -------------------------------------------------------------------- evaluator


def test_evaluator_role_e2e(tmp_env):
    """num_executors=2 with evaluator=True: partition 1 sees
    ctx.role == 'evaluator' and its outputs are reported separately, never
    averaged into the training mean."""
    seen_roles = {}

    def train(ctx, reporter):
        seen_roles[ctx.role] = True
        if ctx.role == "evaluator":
            return {"eval_loss": 0.5}
        return {"metric": 1.0}

    result = experiment.lagom(
        train,
        DistributedConfig(
            num_executors=2,
            sharding="dp",
            data_plane="local",
            evaluator=True,
            hb_interval=0.05,
        ),
    )
    assert seen_roles == {"chief": True, "evaluator": True}
    assert result["num_workers"] == 1  # evaluator not in the training group
    assert result["metric"] == pytest.approx(1.0)
    assert result["evaluator"]["eval_loss"] == pytest.approx(0.5)
    # evaluator outputs are persisted like every training worker's
    import glob
    import json

    outs = glob.glob(os.path.join(tmp_env.root, "*", "*", "worker_1", ".outputs.json"))
    assert outs and json.load(open(outs[0]))["eval_loss"] == pytest.approx(0.5)


def test_evaluator_free_form_returns(tmp_env):
    """Evaluator returns need not be numeric or dict — a string/list persists
    as {'value': ...} instead of killing the run (review finding)."""

    def train(ctx, reporter):
        if ctx.role == "evaluator":
            return "checkpoint-500 looks best"
        return {"metric": 2.0}

    result = experiment.lagom(
        train,
        DistributedConfig(
            num_executors=2, sharding="dp", data_plane="local",
            evaluator=True, hb_interval=0.05,
        ),
    )
    assert result["metric"] == pytest.approx(2.0)
    assert result["evaluator"]["value"] == "checkpoint-500 looks best"


def test_evaluator_needs_two_workers(tmp_env):
    def train(ctx):
        return {"metric": 0.0}

    with pytest.raises(ValueError, match="num_executors >= 2"):
        experiment.lagom(
            train,
            DistributedConfig(num_executors=1, evaluator=True, data_plane="local"),
        )


def test_registry_no_secret_opt_out(tmp_env, monkeypatch):
    """MAGGY_TPU_REGISTRY_NO_SECRET=1 registers address-only records (shared
    buckets: read access to the root must not grant control-plane access);
    the monitor then resolves the secret from MAGGY_TPU_SECRET."""
    from maggy_tpu import monitor as monitor_mod

    monkeypatch.setenv("MAGGY_TPU_REGISTRY_NO_SECRET", "1")
    seen = {}

    def train(ctx, reporter):
        seen["recs"] = tmp_env.list_drivers()
        return {"metric": 1.0}

    experiment.lagom(
        train,
        DistributedConfig(
            num_executors=1, sharding="dp", data_plane="local", hb_interval=0.05
        ),
    )
    assert seen["recs"] and "secret" not in seen["recs"][0]
    # re-register a record to resolve against (driver unregistered on stop)
    tmp_env.register_driver("app_ns", 1, "127.0.0.1", 4141, secret=None,
                            scope="local")
    monkeypatch.setenv("MAGGY_TPU_SECRET", "oob-secret")
    host, port, secret = monitor_mod.resolve_target(tmp_env, "app_ns")
    assert secret == "oob-secret"
