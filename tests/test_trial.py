"""Trial tests — mirrors reference maggy/tests/test_trial.py:24-48 (deterministic
id, json round-trip) plus state machine and metric dedup."""

import json

from maggy_tpu import Trial


def test_deterministic_id():
    t1 = Trial({"lr": 0.01, "layers": 3})
    t2 = Trial({"layers": 3, "lr": 0.01})  # key order must not matter
    assert t1.trial_id == t2.trial_id
    assert len(t1.trial_id) == 16
    t3 = Trial({"lr": 0.02, "layers": 3})
    assert t3.trial_id != t1.trial_id


def test_id_matches_reference_scheme():
    """Bit-identical to the reference's own unit-test expectation
    (maggy/tests/test_trial.py:24-48 asserts this exact hash)."""
    assert Trial({"param1": 5, "param2": "ada"}).trial_id == "3d1cc9fdb1d4d001"


def test_state_machine():
    t = Trial({"x": 1})
    assert t.status == Trial.PENDING
    t.schedule(partition_id=2)
    assert t.status == Trial.SCHEDULED and t.assigned_to == 2
    t.begin()
    assert t.status == Trial.RUNNING and t.start is not None
    t.finalize(0.97)
    assert t.status == Trial.FINALIZED
    assert t.final_metric == 0.97
    assert t.duration is not None and t.duration >= 0


def test_append_metric_dedup_by_step():
    t = Trial({"x": 1})
    assert t.append_metric(0.5, step=0)
    assert t.append_metric(0.6, step=1)
    assert not t.append_metric(0.7, step=1)  # duplicate step dropped
    assert not t.append_metric(0.7, step=0)  # regression dropped
    assert t.append_metric(0.7)  # auto-increment to 2
    assert t.metrics == [0.5, 0.6, 0.7]
    assert t.step_history == [0, 1, 2]


def test_running_avg():
    t = Trial({"x": 1})
    for s, m in enumerate([1.0, 2.0, 3.0, 4.0]):
        t.append_metric(m, step=s)
    assert t.running_avg() == 2.5
    assert t.running_avg(up_to_step=1) == 1.5
    assert Trial({"y": 0}).running_avg() is None


def test_json_roundtrip():
    t = Trial({"lr": 0.1, "act": "relu"}, info_dict={"budget": 9})
    t.append_metric(0.3, step=0)
    t.begin()
    t.finalize(0.9)
    payload = t.to_json()
    json.loads(payload)  # valid json
    t2 = Trial.from_json(payload)
    assert t2.trial_id == t.trial_id
    assert t2.status == Trial.FINALIZED
    assert t2.final_metric == 0.9
    assert t2.metric_history == [0.3]
    assert t2.info_dict == {"budget": 9}


def test_early_stop_flag():
    t = Trial({"x": 1})
    assert not t.get_early_stop()
    t.set_early_stop()
    assert t.get_early_stop()
