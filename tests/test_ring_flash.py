"""Pallas ring attention (in-kernel RDMA rotation) vs the ppermute ring and
the dense reference, on the CPU mesh via the TPU interpret machine (remote
DMAs and semaphores are simulated faithfully; VERDICT r1 item 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from maggy_tpu.models.transformer import default_attention
from maggy_tpu.ops.ring_flash import ring_flash_attention
from maggy_tpu.parallel.ringattention import ring_attention
from maggy_tpu.util import set_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the 8-device CPU mesh"
)

# the TPU interpret machine (faithful remote-DMA/semaphore simulation on CPU)
# only exists on newer jax; without it the RDMA kernel cannot run off-TPU
_HAS_INTERPRET_MACHINE = hasattr(
    __import__("jax.experimental.pallas.tpu", fromlist=["tpu"]),
    "InterpretParams",
)
needs_interpret_machine = pytest.mark.skipif(
    not _HAS_INTERPRET_MACHINE,
    reason="jax too old for the pallas TPU interpret machine",
)


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def _qkv(B=2, S=128, H=4, KH=2, D=16):
    q = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.float32)
    k = jax.random.normal(jax.random.key(2), (B, S, KH, D), jnp.float32)
    v = jax.random.normal(jax.random.key(3), (B, S, KH, D), jnp.float32)
    return q, k, v


@needs_interpret_machine
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow
def test_ring_flash_matches_dense(causal):
    mesh = _mesh(4)
    q, k, v = _qkv()
    ref = default_attention(q, k, v, causal=causal)
    with set_mesh(mesh):
        out = ring_flash_attention(
            q, k, v, mesh=mesh, causal=causal, q_tile=16, interpret=True
        )
    assert float(jnp.abs(out - ref).max()) < 2e-5


@needs_interpret_machine
def test_ring_flash_gqa_matches_xla_ring():
    """sp=4 mesh, grouped KV heads: the RDMA kernel and the ppermute ring are
    the same computation distributed two different ways."""
    mesh = _mesh(4)
    q, k, v = _qkv(B=1, S=64, H=4, KH=1, D=8)
    with set_mesh(mesh):
        xla = ring_attention(q, k, v, mesh=mesh, causal=True, impl="xla")
        pallas = ring_attention(
            q, k, v, mesh=mesh, causal=True, impl="pallas", interpret=True
        )
    assert float(jnp.abs(pallas - xla).max()) < 2e-5


@needs_interpret_machine
def test_ring_flash_backward_kernel_parity():
    """The RDMA backward ring (rotating dk/dv accumulators, probabilities
    recomputed from the saved LSE) must give the same gradients as the
    differentiable XLA ppermute ring. Kept in the fast tier (small 2-device
    S=32 case) so 'not slow' still catches backward-kernel regressions."""
    mesh = _mesh(2)
    q, k, v = _qkv(B=1, S=32, H=2, KH=2, D=8)

    def loss_pallas(q, k, v):
        out = ring_attention(
            q, k, v, mesh=mesh, causal=True, impl="pallas", interpret=True
        )
        return (out**2).sum()

    def loss_xla(q, k, v):
        out = ring_attention(q, k, v, mesh=mesh, causal=True, impl="xla")
        return (out**2).sum()

    with set_mesh(mesh):
        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        assert float(jnp.abs(a - b).max()) < 5e-5


def test_auto_impl_gates_pallas_off_tpu(monkeypatch):
    """impl='auto' must resolve from the mesh's device platform and the
    MAGGY_TPU_RING_PALLAS opt-in: on a CPU mesh it always takes the XLA ring,
    even with the opt-in set (ADVICE r3; VERDICT r3 item 6)."""
    from maggy_tpu.parallel import ringattention as ra

    def boom(*a, **k):
        raise AssertionError("pallas path selected on a CPU mesh")

    monkeypatch.setattr(ra, "_pallas_ring", boom)
    monkeypatch.setenv("MAGGY_TPU_RING_PALLAS", "1")
    mesh = _mesh(2)
    q, k, v = _qkv(B=1, S=32, H=2, KH=2, D=8)
    with set_mesh(mesh):
        out = ring_attention(q, k, v, mesh=mesh, causal=True, impl="auto")
    assert out.shape == q.shape


@needs_interpret_machine
@pytest.mark.slow
def test_ring_flash_backward_gqa_four_ring():
    """4-device ring, grouped KV heads, several q tiles per chunk — the dK/dV
    group-sum and multi-tile dQ read-modify-write paths."""
    mesh = _mesh(4)
    q, k, v = _qkv(B=2, S=128, H=4, KH=2, D=16)

    def loss_pallas(q, k, v):
        out = ring_attention(
            q, k, v, mesh=mesh, causal=True, impl="pallas", interpret=True
        )
        return (out * jnp.cos(out)).sum()

    def loss_xla(q, k, v):
        out = ring_attention(q, k, v, mesh=mesh, causal=True, impl="xla")
        return (out * jnp.cos(out)).sum()

    with set_mesh(mesh):
        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        assert float(jnp.abs(a - b).max()) < 5e-5
