"""GP / TPE surrogate tests with simulated oracles (deterministic seeds)."""

import numpy as np
import pytest

from maggy_tpu import Searchspace
from maggy_tpu.optimizer import IDLE, get_optimizer
from maggy_tpu.optimizer.bayes.gp import GP, _FittedGP, _matern52
from maggy_tpu.optimizer.bayes.tpe import TPE


def space():
    return Searchspace(x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0]))


def drive(opt, oracle, direction="max", num=30):
    opt.setup(space(), num, {}, [], direction=direction)
    finished = []
    while True:
        s = opt.get_suggestion()
        if s is None:
            break
        if s == IDLE:
            assert opt.trial_store
            break
        opt.trial_store[s.trial_id] = s
        s.begin()
        s.finalize(oracle(s.params))
        del opt.trial_store[s.trial_id]
        opt.final_store.append(s)
        finished.append(s)
    return finished


def test_matern_kernel_properties():
    X = np.random.default_rng(0).random((10, 3))
    K = _matern52(X, X, np.ones(3))
    np.testing.assert_allclose(np.diag(K), 1.0, atol=1e-9)
    np.testing.assert_allclose(K, K.T, atol=1e-12)
    assert (np.linalg.eigvalsh(K + 1e-8 * np.eye(10)) > 0).all()


def test_gp_predict_interpolates():
    rng = np.random.default_rng(1)
    X = rng.random((20, 2))
    y = np.sin(3 * X[:, 0]) + X[:, 1] ** 2
    gp = _FittedGP(X, y, amp2=1.0, lengthscales=np.array([0.3, 0.3]), noise2=1e-6)
    mu, sigma = gp.predict(X)
    np.testing.assert_allclose(mu, y, atol=1e-2)
    # uncertainty grows away from data
    far = np.array([[5.0, 5.0]])
    _, s_far = gp.predict(far)
    assert s_far[0] > sigma.mean()


@pytest.mark.parametrize("name", ["gp", "tpe"])
def test_bo_beats_random_on_smooth_objective(name):
    """On a smooth unimodal objective the surrogate should find a better
    optimum than pure random search with the same trial budget."""

    def oracle(p):  # max at (0.7, 0.3)
        return -((p["x"] - 0.7) ** 2) - (p["y"] - 0.3) ** 2

    budget = 40
    bo = get_optimizer(name, seed=0, num_warmup_trials=10)
    bo_best = max(t.final_metric for t in drive(bo, oracle, num=budget))

    rnd = get_optimizer("randomsearch", seed=0)
    rnd_best = max(t.final_metric for t in drive(rnd, oracle, num=budget))
    assert bo_best >= rnd_best - 1e-3, (bo_best, rnd_best)
    assert bo_best > -0.01  # close to the optimum


def test_gp_direction_min():
    def oracle(p):
        return (p["x"] - 0.2) ** 2 + (p["y"] - 0.8) ** 2

    gp = GP(seed=3, num_warmup_trials=8)
    finished = drive(gp, oracle, direction="min", num=30)
    best = min(t.final_metric for t in finished)
    assert best < 0.02


def test_model_proposals_are_used():
    gp = GP(seed=5, num_warmup_trials=5, random_fraction=0.0)
    finished = drive(gp, lambda p: p["x"], num=25)
    kinds = {t.info_dict["sample_type"] for t in finished}
    assert "model" in kinds
    assert len(finished) == 25
    assert len({t.trial_id for t in finished}) == 25  # all unique


def test_busy_imputation_training_set():
    gp = GP(seed=0, imputation="cl_mean")
    gp.setup(space(), 10, {}, [], direction="max")
    # 4 finalized + 2 busy
    for i in range(4):
        t = gp.create_trial({"x": 0.1 * i, "y": 0.5})
        t.finalize(float(i))
        gp.final_store.append(t)
    for i in range(2):
        t = gp.create_trial({"x": 0.9, "y": 0.05 * i})
        gp.trial_store[t.trial_id] = t
    X, y = gp._training_set()
    assert X.shape == (6, 2)
    # imputed values equal the mean of observed (negated) metrics
    np.testing.assert_allclose(y[-2:], y[:4].mean())


def test_asy_ts_beats_random_on_smooth_objective():
    """Thompson sampling converges on a smooth objective at least as well as
    random search (reference gp.py:158-162 asy_ts strategy)."""

    def oracle(p):  # max at (0.7, 0.3)
        return -((p["x"] - 0.7) ** 2) - (p["y"] - 0.3) ** 2

    budget = 40
    ts = GP(seed=0, acq_fun="asy_ts", num_warmup_trials=10)
    ts_best = max(t.final_metric for t in drive(ts, oracle, num=budget))

    rnd = get_optimizer("randomsearch", seed=0)
    rnd_best = max(t.final_metric for t in drive(rnd, oracle, num=budget))
    assert ts_best >= rnd_best - 1e-3, (ts_best, rnd_best)
    assert ts_best > -0.02


def test_kriging_believer_imputes_posterior_mean():
    """imputation='kb': busy configs get the believer GP's mean at their
    location, not a constant — near an observed point the imputed value is
    close to that observation, and distinct busy points differ."""
    gp = GP(seed=0, imputation="kb")
    gp.setup(space(), 10, {}, [], direction="max")
    for i in range(6):
        t = gp.create_trial({"x": 0.15 * i, "y": 0.5})
        t.finalize(float(i))
        gp.final_store.append(t)
    # one busy trial right on top of the best observation, one far away
    near = gp.create_trial({"x": 0.75, "y": 0.5})
    far = gp.create_trial({"x": 0.02, "y": 0.98})
    gp.trial_store[near.trial_id] = near
    gp.trial_store[far.trial_id] = far
    X, y = gp._training_set()
    assert X.shape == (8, 2)
    # rows follow trial_store (insertion) order; metrics negated (direction max)
    vals = y[-2:]
    assert abs(vals[0] - (-5.0)) < 1.0, vals  # near x=0.75 -> ~best metric 5
    assert abs(vals[0] - vals[1]) > 0.5, vals  # believer varies over space


def test_kb_converges():
    def oracle(p):
        return -((p["x"] - 0.4) ** 2) - (p["y"] - 0.6) ** 2

    gp = GP(seed=1, imputation="kb", num_warmup_trials=8)
    best = max(t.final_metric for t in drive(gp, oracle, num=30))
    assert best > -0.05


@pytest.mark.parametrize("name", ["gp", "tpe"])
def test_multi_fidelity_augment_with_hyperband(name, tmp_env):
    """Single [x, budget]-augmented surrogate drives a hyperband run e2e."""
    from maggy_tpu import experiment
    from maggy_tpu.config import HyperparameterOptConfig

    def train(hparams, budget, reporter):
        for step in range(int(budget)):
            reporter.broadcast(-((hparams["x"] - 0.7) ** 2), step=step)
        return -((hparams["x"] - 0.7) ** 2) - 0.01 / budget

    cfg = HyperparameterOptConfig(
        num_trials=1,
        optimizer=get_optimizer(
            name, seed=0, num_warmup_trials=4, multi_fidelity="augment",
            random_fraction=0.1,
        ),
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0])),
        direction="max",
        num_executors=4,
        es_policy="none",
        hb_interval=0.05,
        pruner="hyperband",
        pruner_config={"eta": 3, "resource_min": 1, "resource_max": 9},
        seed=0,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 22  # 9+3+1 + 5+1 + 3
    assert result["errors"] == 0
    assert result["best"]["metric"] > -0.05  # converged near x=0.7


def test_augment_training_set_shapes():
    gp = GP(seed=0, multi_fidelity="augment")
    gp.setup(space(), 20, {}, [], direction="max")
    for i, b in enumerate([1, 1, 3, 9]):
        t = gp.create_trial({"x": 0.2 * i, "y": 0.5}, budget=b)
        t.finalize(float(i))
        gp.final_store.append(t)
    busy = gp.create_trial({"x": 0.9, "y": 0.9}, budget=3)
    gp.trial_store[busy.trial_id] = busy
    X, y, b_norm = gp._augmented_training_set(target_budget=9)
    assert X.shape == (5, 3)  # 2 hparams + budget column, 4 observed + 1 busy
    assert y.shape == (5,)
    np.testing.assert_allclose(X[:4, -1], [1 / 9, 1 / 9, 3 / 9, 1.0])
    assert b_norm == 1.0
    # proposal excludes the budget coordinate
    params = gp._model_proposal(budget=9)
    if params is not None:
        assert set(params) == {"x", "y"}


def test_augment_interim_rows():
    gp = GP(seed=0, multi_fidelity="augment", interim_rows=2)
    gp.setup(space(), 20, {}, [], direction="max")
    for i, b in enumerate([4, 4]):
        t = gp.create_trial({"x": 0.3 * i, "y": 0.4}, budget=b)
        for s, m in enumerate([0.1, 0.2, 0.3, 0.4]):
            t.append_metric(m + i, step=s)
        t.finalize(0.4 + i)
        gp.final_store.append(t)
    X, y, _ = gp._augmented_training_set(target_budget=4)
    # 2 final rows + 2 trials x 2 interim rows
    assert X.shape == (6, 3) and y.shape == (6,)
    # interim budget fractions in (0, 1]; first subsampled point is step 0 -> 1/4
    assert set(np.round(X[2:, -1], 3)) == {0.25, 1.0}
    # direction=max negates interim metrics too
    assert y[2] == -0.1


def test_validation_errors():
    with pytest.raises(ValueError):
        GP(acq_fun="ucb")
    with pytest.raises(ValueError):
        TPE(gamma=1.5)
    with pytest.raises(ValueError):
        GP(random_fraction=2.0)
    with pytest.raises(ValueError):
        GP(imputation="median")
    with pytest.raises(ValueError):
        GP(multi_fidelity="interp")
