"""Test harness configuration.

Runs everything on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (SURVEY.md §4 "in-process fake cluster"). Must be
set before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The TPU plugin on this image re-asserts its platform over the env var (and
# its backend init can hang on a wedged tunnel even from CPU-pinned
# processes), so pin through jax.config AND drop its backend factory (must
# happen before any backend init).
from maggy_tpu.util import force_cpu

force_cpu()

import pytest


@pytest.fixture()
def tmp_env(tmp_path):
    """Point the ambient Env at a per-test temp dir."""
    from maggy_tpu.core import env as env_mod
    from maggy_tpu.core.env.base import BaseEnv

    old_root = os.environ.get("MAGGY_TPU_LOG_ROOT")
    os.environ["MAGGY_TPU_LOG_ROOT"] = str(tmp_path)
    env_mod.set_instance(BaseEnv(str(tmp_path)))
    yield env_mod.get_instance()
    env_mod.set_instance(None)
    if old_root is None:
        os.environ.pop("MAGGY_TPU_LOG_ROOT", None)
    else:
        os.environ["MAGGY_TPU_LOG_ROOT"] = old_root
