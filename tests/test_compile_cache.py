"""Persistent XLA compilation cache: one compile per geometry across Trainer
instances/trials/processes (each Trainer jits its own step closure, so
without this N same-geometry HPO trials pay N full compiles)."""

import os
import subprocess
import sys
import textwrap

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

SCRIPT = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from maggy_tpu import util

    d = util.enable_compilation_cache()
    if os.environ.get("MAGGY_TPU_COMPILE_CACHE") == "1":
        assert d is not None and os.path.isdir(d), d
        assert jax.config.jax_compilation_cache_dir == d
        # idempotent
        assert util.enable_compilation_cache() == d
    else:
        # CPU backend without the force flag: disabled (XLA:CPU AOT reload
        # can SIGILL across machine-feature drift)
        assert d is None, d
        assert not jax.config.jax_compilation_cache_dir
    print("CACHE-OK", d)
    """
).format(repo=REPO)


def _run(env_overrides, tmp_path):
    script = tmp_path / "cache_probe.py"
    script.write_text(SCRIPT)
    env = dict(os.environ)
    env.pop("MAGGY_TPU_COMPILE_CACHE", None)
    env["MAGGY_TPU_COMPILE_CACHE_DIR"] = str(tmp_path / "xcache")
    env.update(env_overrides)
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_cache_enabled_when_forced(tmp_path):
    out = _run({"MAGGY_TPU_COMPILE_CACHE": "1"}, tmp_path)
    assert "CACHE-OK" in out and "xcache" in out


def test_cache_skipped_on_cpu_by_default(tmp_path):
    out = _run({}, tmp_path)
    assert "CACHE-OK None" in out


def test_cache_disabled_explicitly(tmp_path):
    out = _run({"MAGGY_TPU_COMPILE_CACHE": "0"}, tmp_path)
    assert "CACHE-OK None" in out
