"""Serving engine: slot lifecycle invariants under churn, greedy-decode
equivalence with one-shot ``generate_cached``, compile-once decode step, and
per-request PRNG isolation (ISSUE 2 tentpole + satellites)."""

import dataclasses
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.exceptions import BadArgumentsError
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.serve import Engine, Request, SamplingParams, Scheduler, SlotManager
from maggy_tpu.serve.slots import SlotOccupiedError

# float32 so one-pass prefill and token-by-token cache fill agree bit-for-bit
# on greedy argmax (the bf16 tie-break caveat the packed tests tolerate)
CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def served():
    model = Decoder(CFG)
    params = unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    return model, params


def make_engine(params, num_slots=4):
    return Engine(CFG, params, num_slots=num_slots)


def reference(params, prompt, max_new, temperature=0.0, rng=None):
    """One-shot generate_cached over the same prompt/params."""
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = np.zeros((1, len(prompt) + max_new), np.int32)
    buf[0, : len(prompt)] = prompt
    out = generate_cached(
        decode_model,
        params,
        jnp.asarray(buf),
        jnp.asarray([len(prompt)]),
        temperature=temperature,
        rng=rng,
    )
    return np.asarray(out)[0, len(prompt):]


def run_all(scheduler, requests, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(r.state in ("done", "failed", "cancelled", "expired") for r in requests):
            return
        time.sleep(0.01)
    raise AssertionError(
        f"requests stuck: {[(r.id, r.state) for r in requests]}"
    )


# --------------------------------------------------------------------- slots


def test_slot_manager_invariants():
    sm = SlotManager(2)
    r1, r2, r3 = (Request(prompt=[1, 2]) for _ in range(3))
    s1 = sm.admit(r1, first_token=5)
    sm.check_invariants()
    s2 = sm.admit(r2, first_token=6)
    assert {s1, s2} == {0, 1} and not sm.free_slots()
    with pytest.raises(SlotOccupiedError, match="no free slot"):
        sm.admit(r3, first_token=7)
    # double-admit of the same request is an invariant violation
    sm.evict(s1)
    sm.check_invariants()
    with pytest.raises(SlotOccupiedError, match="already in a slot"):
        sm.admit(r2, first_token=8)
    with pytest.raises(SlotOccupiedError, match="already free"):
        sm.evict(s1)
    # freed slot is reusable
    s3 = sm.admit(r3, first_token=7)
    assert s3 == s1
    st = sm.get(s3)
    assert st.next_pos == 2 and st.generated == 1 and st.last_token == 7
    sm.advance(s3, 9)
    assert st.next_pos == 3 and st.last_token == 9
    sm.check_invariants()


def test_slot_churn_reuses_all_slots(served):
    """Admission under churn lands on freed slots; the host mirror never
    leaks or double-books."""
    _, params = served
    engine = make_engine(params, num_slots=2)
    seen_slots = set()
    for i in range(6):
        req = Request(prompt=[1 + i, 2, 3], params=SamplingParams(max_new=2))
        slot, _ = engine.admit(req)
        seen_slots.add(slot)
        engine.slots.check_invariants()
        if engine.slots.active_count == 2:
            engine.step()
            engine.release(engine.slots.active_slots()[0])
            engine.slots.check_invariants()
    assert seen_slots == {0, 1}


# -------------------------------------------------------------- equivalence


def test_greedy_engine_matches_one_shot(served):
    """Acceptance: every request's greedy output equals one-shot
    generate_cached on the same prompt — requests admitted at different
    times into different slots, decoded in one shared compiled step."""
    _, params = served
    engine = make_engine(params, num_slots=4)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        prompts = [
            [1, 2, 3, 4],
            [5, 6, 7],
            [9, 10, 11, 12, 13],
            [2, 4, 6, 8, 10, 12],
            [7, 3],
            [40, 41, 42],
        ]
        reqs = [
            scheduler.submit(p, SamplingParams(max_new=6)) for p in prompts
        ]
        run_all(scheduler, reqs)
    finally:
        scheduler.stop()
    for req, prompt in zip(reqs, prompts):
        assert req.state == "done", (req.state, req.error)
        np.testing.assert_array_equal(
            np.asarray(req.tokens),
            reference(params, prompt, 6),
            err_msg=f"prompt {prompt}: engine diverges from generate_cached",
        )


def test_eos_stops_early(served):
    _, params = served
    # find the token greedy decode emits second, use it as eos
    ref = reference(params, [1, 2, 3], 6)
    eos = int(ref[1])
    engine = make_engine(params)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        req = scheduler.submit(
            [1, 2, 3], SamplingParams(max_new=6, eos_id=eos)
        )
        run_all(scheduler, [req])
    finally:
        scheduler.stop()
    assert req.state == "done"
    assert req.tokens[-1] == eos and len(req.tokens) == 2


# -------------------------------------------------------------- compile-once


def test_decode_step_compiles_once_under_churn(served):
    """The whole point of slot-based static shapes: request churn (varying
    prompt lengths, admissions interleaved with decode) never retraces the
    decode step. Prefill compiles per power-of-two bucket only."""
    _, params = served
    engine = make_engine(params, num_slots=3)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        reqs = []
        for i in range(9):
            plen = 2 + (i * 3) % 11  # lengths spread over 2..12
            reqs.append(
                scheduler.submit(
                    list(range(1, plen + 1)),
                    SamplingParams(max_new=3 + (i % 4)),
                )
            )
            time.sleep(0.02)  # staggered arrivals -> admissions mid-decode
        run_all(scheduler, reqs)
    finally:
        scheduler.stop()
    assert all(r.state == "done" for r in reqs)
    counts = engine.compile_counts
    assert counts["decode"] == 1, counts
    # the admit body traces at most twice: standalone, and once more inside
    # the admit-from-prefix program (these range-prompts share prefixes, so
    # prefix-KV reuse legitimately fires under churn)
    assert counts["admit"] <= 2, counts
    # prompt lengths 2..12 span buckets 8 and 16 only; prefix admission
    # compiles per suffix bucket on the same ladder
    assert counts["prefill"] <= 2, counts
    assert counts["prefix_admit"] <= 2, counts


# ---------------------------------------------------------------------- RNG


def test_sampling_deterministic_per_seed_and_slot_independent(served):
    """A request's sampled output is a function of (prompt, params, seed) —
    not of which slot it lands in or which other requests share the batch."""
    _, params = served
    engine = make_engine(params, num_slots=3)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        sp = SamplingParams(max_new=8, temperature=1.0, top_k=8, seed=123)
        # run 1: alone
        a = scheduler.submit([9, 9, 9], sp)
        run_all(scheduler, [a])
        # run 2: same request packed next to noise neighbours
        noise = [
            scheduler.submit([3 + i, 5], SamplingParams(max_new=10, temperature=0.7, seed=i))
            for i in range(2)
        ]
        b = scheduler.submit([9, 9, 9], sp)
        c = scheduler.submit([9, 9, 9], dataclasses.replace(sp, seed=124))
        run_all(scheduler, noise + [b, c])
    finally:
        scheduler.stop()
    assert a.tokens == b.tokens, "slot/batch neighbours changed sampled output"
    assert a.tokens != c.tokens, "different seeds produced identical samples"


def test_default_rng_warns_when_sampling(served):
    """Satellite: the silent fixed-key footgun now warns — sampling with the
    default key on any generate path, but never for greedy decode."""
    _, params = served
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = jnp.asarray(np.zeros((1, 21), np.int32))  # unique shape -> fresh trace
    plen = jnp.asarray([2])
    with pytest.warns(UserWarning, match="fixed default PRNG key"):
        generate_cached(decode_model, params, buf, plen, temperature=0.73)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        generate_cached(decode_model, params, buf, plen)  # greedy: silent


# ------------------------------------------------------------- async decode


def test_async_decode_matches_sync_engine(served):
    """ACCEPTANCE (ISSUE 5): the async double-buffered drain (decode i+1
    dispatched before host-reading step i) produces byte-identical token
    streams to the synchronous engine — greedy AND sampled — under
    staggered admissions, and the decode step still compiles once."""
    _, params = served

    def run(async_decode, temp):
        engine = Engine(CFG, params, num_slots=3, async_decode=async_decode)
        scheduler = Scheduler(engine)
        scheduler.start()
        try:
            prompts = [[1, 2, 3, 4], [5, 6, 7], [9, 10, 11, 12, 13], [2, 4, 6], [7, 3]]
            reqs = []
            for i, p in enumerate(prompts):
                reqs.append(
                    scheduler.submit(
                        p,
                        SamplingParams(
                            max_new=4 + (i % 3), temperature=temp, seed=17 + i
                        ),
                    )
                )
                time.sleep(0.01)  # staggered -> admissions interleave decode
            run_all(scheduler, reqs)
        finally:
            scheduler.stop()
        assert all(r.state == "done" for r in reqs), [
            (r.state, r.error) for r in reqs
        ]
        return [list(r.tokens) for r in reqs], engine

    for temp in (0.0, 0.8):
        sync_streams, _ = run(False, temp)
        async_streams, engine = run(True, temp)
        assert sync_streams == async_streams, f"temp={temp}: streams diverge"
        assert engine.compile_counts["decode"] == 1, engine.compile_counts


def test_async_flush_discards_post_finish_garbage(served):
    """Direct engine drive: the one extra step a slot decodes before the
    host learns it finished is discarded at drain — a released/re-admitted
    slot never leaks a stale token, and flush() retires the pending
    dispatch when the active set empties."""
    _, params = served
    tel = __import__("maggy_tpu").telemetry.Telemetry(worker="t")
    engine = Engine(
        CFG, params, num_slots=1, async_decode=True, telemetry_recorder=tel
    )
    slot, first = engine.admit(
        Request(prompt=[1, 2, 3], params=SamplingParams(max_new=4))
    )
    toks = [first]
    # decode: output lags dispatch by one step — first step returns nothing
    out = engine.step()
    assert out.tokens == {}
    while len(toks) < 4:
        out = engine.step()
        toks.extend(out.tokens.values())
    engine.release(slot)
    # the pending dispatch still references the released slot: its token
    # belongs to no one and must vanish
    leftover = engine.flush()
    assert leftover.tokens == {}
    assert engine.flush().tokens == {}  # idempotent
    engine.slots.check_invariants()
    # re-admission into the same slot starts a fresh stream that matches the
    # no-churn reference (stale pending state must not bleed through)
    slot2, first2 = engine.admit(
        Request(prompt=[1, 2, 3], params=SamplingParams(max_new=4))
    )
    toks2 = [first2]
    while len(toks2) < 4:
        toks2.extend(engine.step().tokens.values())
    engine.release(slot2)
    engine.flush()
    assert toks2 == toks == list(reference(params, [1, 2, 3], 4))
    assert "serve.drain_ms" in tel.snapshot()["gauges"]


# ------------------------------------------------------------------- limits


def test_submit_validates_length_and_params(served):
    _, params = served
    engine = make_engine(params)
    scheduler = Scheduler(engine)
    with pytest.raises(BadArgumentsError, match="max_seq_len"):
        scheduler.submit(list(range(60)), SamplingParams(max_new=10))
    with pytest.raises(BadArgumentsError, match="empty prompt"):
        scheduler.submit([], SamplingParams())
    with pytest.raises(ValueError, match="max_new"):
        scheduler.submit([1], SamplingParams(max_new=0))


def test_cancel_storm_releases_all_resources(served):
    """Satellite (ISSUE 10): cancel/deadline-expire release cache resources
    through the ONE shared release path — after a storm of cancellations
    (queued and mid-decode alike) plus deadline expiries, no slot and no
    KV page leaks, and the engine still serves fresh work correctly."""
    _, params = served
    engine = Engine(CFG, params, num_slots=3)  # paged default
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        reqs = []
        for i in range(12):
            reqs.append(
                scheduler.submit(
                    [1 + i, 2, 3], SamplingParams(max_new=30),
                    # a few die by deadline instead of cancel
                    deadline_s=0.2 if i % 4 == 3 else None,
                )
            )
        time.sleep(0.15)  # let some admit and decode
        for i, r in enumerate(reqs):
            if i % 4 != 3:
                scheduler.cancel(r.id)
        deadline = time.time() + 60
        while time.time() < deadline and any(
            r.state not in ("done", "cancelled", "expired", "failed")
            for r in reqs
        ):
            time.sleep(0.01)
        assert not any(r.state == "failed" for r in reqs), [
            (r.state, r.error) for r in reqs
        ]
        assert scheduler.drain(timeout=30)
    finally:
        scheduler.stop()
    # the storm left nothing behind: no occupied slot, no referenced page
    assert engine.slots.active_count == 0
    engine.slots.check_invariants()
    assert engine.paged
    assert engine.allocator.pages_free == engine.allocator.pages_total, (
        engine.allocator.stats()
    )
    engine.allocator.check_invariants()
    engine.page_table.check_invariants(engine.allocator)
    # and the engine still serves fresh requests byte-identically
    slot, first = engine.admit(
        Request(prompt=[1, 2, 3], params=SamplingParams(max_new=4))
    )
    toks = [first]
    while len(toks) < 4:
        toks.extend(engine.step().tokens.values())
    assert toks == list(reference(params, [1, 2, 3], 4))


def test_admit_without_free_slot_raises(served):
    _, params = served
    engine = make_engine(params, num_slots=1)
    engine.admit(Request(prompt=[1, 2], params=SamplingParams(max_new=4)))
    with pytest.raises(SlotOccupiedError):
        engine.admit(Request(prompt=[3, 4], params=SamplingParams(max_new=4)))
