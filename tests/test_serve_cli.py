"""Serving CLI (`python -m maggy_tpu.serve`) and the params-only checkpoint
restore it uses to load trained weights onto the engine."""

import os
import re
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_restore_params_roundtrip(tmp_path):
    """Checkpointer.restore_params pulls just the params subtree out of a
    saved TrainState, unboxed to raw arrays — the exact tree the serve
    engine (and generate_cached) take."""
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.checkpoint import Checkpointer
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create("dp", devices=jax.devices()[:1])
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 4, 16, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    ck = Checkpointer(str(tmp_path), async_save=False)
    ck.save(3, state)
    ck.wait()

    params = ck.restore_params()  # latest step
    expected = unbox(state.params)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        expected,
    )
    # the restored tree drives the decode model directly
    logits = Decoder(cfg).apply(
        {"params": params}, jnp.zeros((1, 4), jnp.int32)
    )
    assert logits.shape == (1, 4, cfg.vocab_size)
    ck.close()

    with pytest.raises(FileNotFoundError):
        Checkpointer(str(tmp_path / "empty"), async_save=False).restore_params()


def test_build_config_presets(tmp_path):
    from maggy_tpu.serve.__main__ import build_config

    cfg = build_config("tiny", max_seq_len=32)
    assert cfg.max_seq_len == 32
    with pytest.raises(SystemExit, match="unknown --config"):
        build_config("nonsense")
    path = tmp_path / "cfg.json"
    path.write_text('{"vocab_size": 128, "d_model": 32, "n_layers": 1, '
                    '"n_heads": 2, "n_kv_heads": 2, "d_ff": 64}')
    cfg = build_config(str(path))
    assert cfg.vocab_size == 128 and cfg.n_layers == 1


@pytest.mark.slow
def test_cli_serves_over_rpc(tmp_path):
    """Subprocess end-to-end: the CLI boots a random-init tiny model, a
    client generates through it, SIGTERM shuts it down cleanly, and the
    telemetry JSONL landed under --exp-dir."""
    from maggy_tpu.serve import ServeClient

    exp_dir = str(tmp_path / "exp")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "maggy_tpu.serve",
            "--config", "tiny", "--max-seq-len", "64", "--slots", "2",
            "--host", "127.0.0.1", "--secret", "cli-test-secret",
            "--exp-dir", exp_dir,
        ],
        stderr=subprocess.PIPE,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    port = None
    try:
        deadline = time.time() + 120
        for line in proc.stderr:
            m = re.search(r"listening on 127\.0\.0\.1:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
            assert time.time() < deadline, "CLI never reported its port"
        assert port is not None
        with ServeClient(("127.0.0.1", port), "cli-test-secret") as client:
            tokens = client.generate([1, 2, 3], max_new=5, timeout=90)
            assert len(tokens) == 5
            stats = client.stats()
            assert stats["compile_counts"]["decode"] == 1
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
        assert os.path.exists(
            os.path.join(exp_dir, "telemetry", "worker_serve.jsonl")
        )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
