"""Pod worker liveness: a registered worker that goes silent past
worker_timeout must abort the experiment loudly instead of hanging the driver
forever (the routine TPU-pod preemption case)."""

import threading
import time

import pytest

from maggy_tpu import experiment
from maggy_tpu.config import DistributedConfig
from maggy_tpu.core import rpc

pytestmark = pytest.mark.slow  # subprocess/multi-process tier


def test_silent_pod_worker_aborts(tmp_env):
    def train(hparams, reporter, ctx):
        reporter.broadcast(1.0, step=0)
        return {"metric": 1.0}

    config = DistributedConfig(
        hparams={},
        num_executors=2,
        sharding="dp",
        data_plane="local",
        driver_addr="127.0.0.1:1",  # pod mode marker (driver never dials it)
        worker_timeout=2.0,
        hb_interval=0.05,
    )
    holder = {}

    def run():
        try:
            experiment.lagom(train, config)
        except BaseException as e:  # noqa: BLE001
            holder["error"] = e

    t = threading.Thread(target=run)
    t.start()
    # wait for the driver, then impersonate remote partition 1: register once,
    # heartbeat briefly, then go silent (preempted host)
    deadline = time.time() + 30
    driver = None
    while time.time() < deadline:
        driver = experiment.CURRENT_DRIVER
        if driver is not None and driver.server is not None and driver.server.port:
            break
        time.sleep(0.02)
    assert driver is not None and driver.pod_mode
    ghost = rpc.Client(
        ("127.0.0.1", driver.server.port), 1, driver.server.secret, hb_interval=0.05
    )
    ghost.register({"host": "preempted-host"})
    ghost.stop()  # silence

    t.join(timeout=60)
    assert not t.is_alive(), "driver hung on the silent worker"
    assert "error" in holder
    assert "silent" in str(holder["error"])
