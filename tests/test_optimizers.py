"""Controller tests with a simulated metric oracle (SURVEY.md §4 implication:
optimizers are deterministic given seeded RNG — no cluster needed)."""

import pytest

from maggy_tpu import Searchspace, Trial
from maggy_tpu.optimizer import (
    IDLE,
    Asha,
    GridSearch,
    RandomSearch,
    SingleRun,
    get_optimizer,
)


def space():
    return Searchspace(
        lr=("DOUBLE", [0.001, 1.0]),
        width=("INTEGER", [8, 64]),
        act=("CATEGORICAL", ["relu", "gelu"]),
    )


def drive(opt, oracle, max_steps=10_000):
    """Minimal driver loop: run trials to completion serially."""
    finished = []
    while True:
        suggestion = opt.get_suggestion()
        if suggestion is None:
            break
        if suggestion == IDLE:
            # serial driver: IDLE with nothing in flight would spin forever
            assert opt.trial_store, "IDLE returned with no busy trials"
            break
        opt.trial_store[suggestion.trial_id] = suggestion
        suggestion.begin()
        suggestion.finalize(oracle(suggestion.params))
        del opt.trial_store[suggestion.trial_id]
        opt.final_store.append(suggestion)
        finished.append(suggestion)
        assert len(finished) < max_steps
    return finished


def wire(opt, num_trials, direction="max"):
    opt.setup(space(), num_trials, {}, [], direction=direction)
    return opt


def test_randomsearch_runs_all_unique_trials():
    opt = wire(RandomSearch(seed=1), 20)
    finished = drive(opt, lambda p: p["lr"])
    assert len(finished) == 20
    assert len({t.trial_id for t in finished}) == 20
    for t in finished:
        assert opt.searchspace.contains({k: v for k, v in t.params.items() if k != "budget"})


def test_randomsearch_seed_determinism():
    a = drive(wire(RandomSearch(seed=7), 10), lambda p: 0.0)
    b = drive(wire(RandomSearch(seed=7), 10), lambda p: 0.0)
    assert [t.trial_id for t in a] == [t.trial_id for t in b]


def test_gridsearch_covers_cartesian_product():
    sp = Searchspace(
        batch=("DISCRETE", [32, 64]),
        act=("CATEGORICAL", ["relu", "gelu"]),
        depth=("INTEGER", [1, 3]),
    )
    n = GridSearch.get_num_trials(sp)
    assert n == 2 * 2 * 3
    opt = GridSearch()
    opt.setup(sp, n, {}, [])
    finished = drive(opt, lambda p: 0.0)
    assert len(finished) == n
    combos = {(t.params["batch"], t.params["act"], t.params["depth"]) for t in finished}
    assert len(combos) == n


def test_gridsearch_grids_continuous_axes():
    sp = Searchspace(lr=("DOUBLE", [0.0, 1.0]))
    assert GridSearch.get_num_trials(sp, grid_points=4) == 4
    opt = GridSearch(grid_points=4)
    opt.setup(sp, 4, {}, [])
    lrs = [t.params["lr"] for t in drive(opt, lambda p: 0.0)]
    assert lrs == [0.0, pytest.approx(1 / 3), pytest.approx(2 / 3), 1.0]


def test_singlerun():
    opt = SingleRun()
    opt.setup(space(), 3, {}, [])
    finished = drive(opt, lambda p: 1.0)
    assert len(finished) == 3


def test_asha_budgets_and_promotion_direction_max():
    opt = Asha(reduction_factor=2, resource_min=1, resource_max=4, seed=3)
    opt.setup(space(), 8, {}, [], direction="max")
    assert opt.budgets == [1, 2, 4]
    # oracle: bigger lr is better — promotions should chase high-lr configs
    finished = drive(opt, lambda p: p["lr"])
    base = [t for t in finished if t.params["budget"] == 1]
    rung1 = [t for t in finished if t.params["budget"] == 2]
    rung2 = [t for t in finished if t.params["budget"] == 4]
    assert len(base) == 8
    assert len(rung1) == len(base) // 2
    assert len(rung2) == len(rung1) // 2
    # the best base config must have been promoted (direction respected)
    best_base = max(base, key=lambda t: t.final_metric)
    assert {k: v for k, v in best_base.params.items() if k != "budget"} in [
        {k: v for k, v in t.params.items() if k != "budget"} for t in rung1
    ]


def test_asha_promotion_direction_min():
    opt = Asha(reduction_factor=2, resource_min=1, resource_max=2, seed=3)
    opt.setup(space(), 4, {}, [], direction="min")
    finished = drive(opt, lambda p: p["lr"])
    base = [t for t in finished if t.params["budget"] == 1]
    promoted = [t for t in finished if t.params["budget"] == 2]
    best_base = min(base, key=lambda t: t.final_metric)
    assert len(promoted) == 2
    promoted_configs = [
        {k: v for k, v in t.params.items() if k != "budget"} for t in promoted
    ]
    assert {k: v for k, v in best_base.params.items() if k != "budget"} in promoted_configs


def test_asha_validation():
    with pytest.raises(ValueError):
        Asha(reduction_factor=1)
    with pytest.raises(ValueError):
        Asha(resource_min=4, resource_max=2)


def test_registry():
    assert isinstance(get_optimizer("randomsearch"), RandomSearch)
    assert isinstance(get_optimizer("asha"), Asha)
    assert isinstance(get_optimizer(None), SingleRun)
    inst = RandomSearch()
    assert get_optimizer(inst) is inst
    with pytest.raises(ValueError):
        get_optimizer("simulated-annealing")


def test_metrics_array_negation():
    opt = wire(RandomSearch(seed=5), 5, direction="max")
    finished = drive(opt, lambda p: p["lr"])
    y = opt.get_metrics_array()
    assert (y <= 0).all()  # negated under max
    assert opt.ybest() == -max(t.final_metric for t in finished)
    opt2 = wire(RandomSearch(seed=5), 5, direction="min")
    drive(opt2, lambda p: p["lr"])
    assert (opt2.get_metrics_array() >= 0).all()


def test_hparams_exist():
    opt = wire(RandomSearch(seed=2), 3)
    t = opt.get_suggestion()
    opt.trial_store[t.trial_id] = t
    assert opt.hparams_exist(t.params)
    assert not opt.hparams_exist({"lr": 0.5, "width": 9, "act": "relu"})
