"""Prefix-KV reuse (maggy_tpu/serve/prefix.py + engine admit-from-prefix).

The ISSUE 6 acceptance criteria: a request sharing a resident prompt prefix
skips prefill for the shared tokens (counter-verified), outputs are
byte-identical to no-reuse — greedy AND sampled — and the decode/admit
programs still compile once across request churn.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.serve import Engine, PrefixIndex, Request, SamplingParams

CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
SYS = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]  # 12-token shared "system prompt"


@pytest.fixture(scope="module")
def params():
    model = Decoder(CFG)
    return unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )


def reference(params, prompt, max_new):
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = np.zeros((1, len(prompt) + max_new), np.int32)
    buf[0, : len(prompt)] = prompt
    out = generate_cached(
        decode_model, params, jnp.asarray(buf), jnp.asarray([len(prompt)])
    )
    return list(np.asarray(out)[0, len(prompt):])


def run_engine(params, requests, max_new, prefix_reuse, telemetry_recorder=None):
    """Admit all requests (slots permitting), decode to completion; returns
    ({request_index: tokens}, engine)."""
    engine = Engine(
        CFG,
        params,
        num_slots=4,
        prefix_reuse=prefix_reuse,
        telemetry_recorder=telemetry_recorder,
    )
    streams = {}
    slot_of = {}
    for i, (prompt, sp) in enumerate(requests):
        slot, first = engine.admit(Request(prompt=prompt, params=sp))
        streams[i] = [first]
        slot_of[slot] = i
    while any(
        len(streams[slot_of[s]]) < max_new for s in engine.slots.active_slots()
    ):
        out = engine.step()
        for s, t in out.tokens.items():
            i = slot_of[s]
            if len(streams[i]) < max_new:
                streams[i].append(t)
    for s in list(engine.slots.active_slots()):
        engine.release(s)
    engine.flush()
    return streams, engine


# ------------------------------------------------------------- index units


def test_prefix_index_match_and_remove():
    idx = PrefixIndex(min_len=8)
    idx.insert(0, SYS + [11, 12, 13])
    # exact-LCP extension past the bucket that found it
    slot, lcp = idx.match(SYS + [11, 12, 99])
    assert slot == 0 and lcp == 14  # 12 shared + [11, 12]
    # shorter than min_len: no match
    assert idx.match(SYS[:6] + [99, 98, 97]) is None
    # unrelated prompt: no match
    assert idx.match(list(range(40, 60))) is None
    # newest insertion wins the shared bucket
    idx.insert(1, SYS + [21])
    slot, lcp = idx.match(SYS + [21, 5])
    assert slot == 1 and lcp == 13
    # removal un-indexes
    idx.remove(1)
    idx.remove(0)
    assert idx.match(SYS + [11]) is None
    assert idx.resident() == {}


def test_prefix_index_prefers_longest_bucket():
    idx = PrefixIndex(min_len=8)
    idx.insert(0, SYS[:8] + [70, 71, 72, 73, 74, 75, 76, 77])
    idx.insert(1, SYS[:8] + [70, 71, 72, 73, 74, 75, 76, 99])
    # a probe sharing 16 tokens with slot 0 must find slot 0 via the
    # 16-bucket even though slot 1 owns the 8-bucket (newest insertion)
    slot, lcp = idx.match(SYS[:8] + [70, 71, 72, 73, 74, 75, 76, 77, 1, 2])
    assert slot == 0 and lcp == 16


# ------------------------------------------------------------- byte parity


def test_prefix_reuse_greedy_parity(params):
    """Greedy outputs identical with reuse on/off; prefill runs only for
    the suffix on the hit."""
    max_new = 6
    requests = [
        (SYS + [11, 12, 13], SamplingParams(max_new=max_new)),
        (SYS + [21, 22], SamplingParams(max_new=max_new)),
        (SYS + [31], SamplingParams(max_new=max_new)),
    ]
    on, eng_on = run_engine(params, requests, max_new, prefix_reuse=True)
    off, eng_off = run_engine(params, requests, max_new, prefix_reuse=False)
    assert on == off, "prefix reuse changed tokens"
    for i, (prompt, _) in enumerate(requests):
        assert on[i] == reference(params, prompt, max_new)
    # counter-verified: request 0 full-prefilled; 1 and 2 reused 12 tokens
    assert eng_on.prefill_calls == 1
    assert eng_on.prefix_hits == 2
    assert eng_on.prefix_tokens_saved == 2 * len(SYS)
    assert eng_off.prefill_calls == 3
    assert eng_off.prefix_hits == 0
    # decode still compiled exactly once on both engines
    assert eng_on.compile_counts["decode"] == 1
    assert eng_off.compile_counts["decode"] == 1


def test_prefix_reuse_sampled_parity(params):
    """Sampled outputs (temperature + top_k, per-request seeds) are also
    byte-identical: the reused rows are exact and the PRNG chain depends
    only on (params, prompt, seed)."""
    max_new = 6
    requests = [
        (SYS + [11, 12], SamplingParams(max_new=max_new, temperature=0.9,
                                        top_k=12, seed=5)),
        (SYS + [41, 42, 43], SamplingParams(max_new=max_new, temperature=0.7,
                                            top_k=8, seed=9)),
    ]
    on, eng_on = run_engine(params, requests, max_new, prefix_reuse=True)
    off, eng_off = run_engine(params, requests, max_new, prefix_reuse=False)
    assert on == off
    assert eng_on.prefix_hits == 1 and eng_off.prefix_hits == 0


def test_identical_prompt_reuses_all_but_last_token(params):
    """A fully identical resident prompt still prefills >= 1 suffix token
    (the logit that samples the first output) and reuses the rest."""
    max_new = 4
    prompt = SYS + [11, 12]
    requests = [
        (prompt, SamplingParams(max_new=max_new)),
        (list(prompt), SamplingParams(max_new=max_new)),
    ]
    on, eng = run_engine(params, requests, max_new, prefix_reuse=True)
    assert on[0] == on[1] == reference(params, prompt, max_new)
    assert eng.prefix_hits == 1
    assert eng.prefix_tokens_saved == len(prompt) - 1


def test_prefix_churn_compile_once(params):
    """Release/re-admit churn against a long-lived resident: every churned
    request admits from the anchor's prefix, one decode compile for the
    whole run, and released slots leave the index (no stale-slot reuse)."""
    max_new = 3
    engine = Engine(CFG, params, num_slots=2, prefix_reuse=True)
    # the anchor stays resident across every wave (system-prompt stand-in)
    anchor_slot, _ = engine.admit(
        Request(prompt=SYS + [99], params=SamplingParams(max_new=50))
    )
    outputs = {}
    for wave in range(3):
        prompt = SYS + [40 + wave]
        slot, first = engine.admit(
            Request(prompt=prompt, params=SamplingParams(max_new=max_new))
        )
        stream = [first]
        while len(stream) < max_new:
            out = engine.step()
            if slot in out.tokens:
                stream.append(out.tokens[slot])
        engine.release(slot)
        outputs[wave] = (prompt, stream)
    engine.release(anchor_slot)
    engine.flush()
    assert engine.compile_counts["decode"] == 1
    # only the anchor full-prefilled; every churned request hit its prefix
    assert engine.prefill_calls == 1
    assert engine.prefix_hits == 3
    assert engine.prefix_tokens_saved == 3 * len(SYS)
    # released slots are gone from the index
    assert engine.prefix_index.resident() == {}
    for wave, (prompt, stream) in outputs.items():
        assert stream == reference(params, prompt, max_new), f"wave {wave}"


def test_prefix_counters_in_stats_and_telemetry(params, tmp_path, tmp_env):
    """prefix_hits / prefix_tokens_saved surface in scheduler stats and the
    exported telemetry JSONL."""
    from maggy_tpu.serve import Scheduler
    from maggy_tpu.telemetry import worker_telemetry

    tel = worker_telemetry("serve", str(tmp_path), role="serve")
    engine = Engine(CFG, params, num_slots=4, prefix_reuse=True,
                    telemetry_recorder=tel)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        reqs = [
            scheduler.submit(SYS + [60 + i], SamplingParams(max_new=3))
            for i in range(3)
        ]
        import time

        deadline = time.time() + 120
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs)
        stats = scheduler.stats()
        assert stats["prefix_hits"] == 2
        assert stats["prefix_tokens_saved"] == 2 * len(SYS)
        assert stats["prefill_calls"] == 1
        assert stats["compile_counts"]["decode"] == 1
    finally:
        scheduler.stop()
    tel.close()
    path = os.path.join(str(tmp_path), "telemetry", "worker_serve.jsonl")
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    counters = {}
    for rec in records:
        if rec.get("kind") == "snapshot":
            counters.update(rec.get("counters") or {})
    assert counters.get("serve.prefix_hits") == 2, counters
    assert counters.get("serve.prefix_tokens_saved") == 2 * len(SYS)
    # the prefix admission leaves its span trail too
    span_names = {r["name"] for r in records if r.get("kind") == "span"}
    assert "serve.prefix_admit" in span_names, sorted(span_names)
