"""Control-plane protocol tests with an in-process server and fake workers
(SURVEY.md §4: "in-process fake cluster ... for driver<->worker protocol tests"
— coverage the reference never had)."""

import threading
import time

import pytest

from maggy_tpu.core import rpc
from maggy_tpu.exceptions import ReservationTimeoutError, RpcError
from maggy_tpu.reporter import Reporter


@pytest.fixture()
def server():
    s = rpc.Server(num_executors=2)

    def reg(m):
        s.reservations.register(m["partition_id"], m.get("meta", {}))
        return {"type": "OK"}

    s.register_callback("QUERY", lambda m: {"type": "QUERY", "ready": s.reservations.done()})
    s.register_callback("REG", reg)
    s.start(host="127.0.0.1")
    yield s
    s.stop()


def client_for(server, pid=0):
    return rpc.Client((server.host, server.port), pid, server.secret, hb_interval=0.05)


def test_register_and_query(server):
    c0 = client_for(server, 0)
    assert c0.register({"host": "h0"})["type"] == "OK"
    assert not server.reservations.done()
    c1 = client_for(server, 1)
    c1.register({"host": "h1"})
    c0.await_reservations(timeout=5)
    assert server.reservations.done()
    spec = server.reservations.cluster_spec()
    assert [e["partition_id"] for e in spec] == [0, 1]
    assert spec[0]["host"] == "h0"
    c0.stop()
    c1.stop()


def test_bad_secret_rejected(server):
    bad = rpc.Client((server.host, server.port), 0, "wrong-secret")
    with pytest.raises(RpcError, match="bad secret"):
        bad.register()
    bad.stop()


def test_unknown_verb_rejected(server):
    c = client_for(server)
    with pytest.raises(RpcError, match="unknown verb"):
        c._request({"type": "BOGUS"})
    c.stop()


def test_handler_exception_becomes_err_reply(server):
    def boom(msg):
        raise ValueError("kaput")

    server.register_callback("BOOM", boom)
    c = client_for(server)
    with pytest.raises(RpcError, match="kaput"):
        c._request({"type": "BOOM"})
    # connection still usable afterwards
    assert c._request({"type": "QUERY"})["type"] == "QUERY"
    c.stop()


def test_heartbeat_metric_and_stop(server):
    """Full monitoring plane: heartbeat drains reporter -> METRIC -> STOP reply
    flips the reporter's early-stop flag (reference §3.5 micro-stack)."""
    metrics = []
    stop_now = threading.Event()

    def metric_cb(msg):
        if msg.get("metric") is not None:
            metrics.append((msg["metric"], msg["step"]))
        return {"type": "STOP"} if stop_now.is_set() else {"type": "OK"}

    server.register_callback("METRIC", metric_cb)
    c = client_for(server, 0)
    rep = Reporter()
    rep.reset("trial-x")
    c.start_heartbeat(rep)
    rep.broadcast(0.7, step=3)
    deadline = time.time() + 5
    while not metrics and time.time() < deadline:
        time.sleep(0.01)
    assert metrics and metrics[-1][0] == 0.7 and metrics[-1][1] == 3

    stop_now.set()
    from maggy_tpu.exceptions import EarlyStopException

    step = 4
    deadline = time.time() + 5
    stopped = False
    while time.time() < deadline:
        try:
            rep.broadcast(0.9, step=step)
        except EarlyStopException:
            stopped = True
            break
        step += 1
        time.sleep(0.05)
    assert stopped, "early stop never propagated through heartbeat"
    c.stop()


def test_heartbeat_final_flush(server):
    """Client.stop() sends one last beat so trailing logs are not lost."""
    got_logs = []
    server.register_callback(
        "METRIC", lambda m: (got_logs.extend(m.get("logs") or []), {"type": "OK"})[1]
    )
    c = client_for(server, 0)
    rep = Reporter()
    c.start_heartbeat(rep)
    rep.log("tail-line", verbose=False)
    c.stop()
    assert "tail-line" in got_logs


def test_reservation_timeout():
    s = rpc.Server(num_executors=3)
    s.start(host="127.0.0.1")
    try:
        with pytest.raises(ReservationTimeoutError):
            s.await_reservations(timeout=0.2)
    finally:
        s.stop()


def test_large_frame_roundtrip(server):
    server.register_callback("ECHO", lambda m: {"type": "ECHO", "blob": m["blob"]})
    c = client_for(server)
    blob = "x" * 1_000_000
    assert c._request({"type": "ECHO", "blob": blob})["blob"] == blob
    c.stop()


# ------------------------------------------------------------ frame robustness
# Hostile/buggy peers must produce clean per-connection errors, never a wedged
# server loop (ISSUE 2 satellite).


import socket as socket_mod  # noqa: E402

from maggy_tpu import constants  # noqa: E402


def _raw_conn(server):
    sock = socket_mod.create_connection((server.host, server.port), timeout=5)
    sock.settimeout(5)
    return sock


def _server_still_serves(server):
    """A fresh well-formed client works — the accept loop survived."""
    c = client_for(server, pid=9)
    try:
        assert c._request({"type": "QUERY"})["type"] == "QUERY"
    finally:
        c.stop()


def test_oversized_frame_gets_err_and_close(server):
    sock = _raw_conn(server)
    try:
        # declared length over the cap; no payload follows
        sock.sendall(rpc._LEN.pack(constants.RPC_MAX_MESSAGE + 1))
        reply = rpc.recv_frame(sock)
        assert reply["type"] == "ERR" and "exceeds cap" in reply["error"]
        # the server closes this connection afterwards
        with pytest.raises(RpcError, match="closed by peer"):
            rpc.recv_frame(sock)
    finally:
        sock.close()
    _server_still_serves(server)


def test_garbage_payload_gets_err_and_connection_survives(server):
    sock = _raw_conn(server)
    try:
        blob = b"\xff\x00\xfenot json at all"
        sock.sendall(rpc._LEN.pack(len(blob)) + blob)
        reply = rpc.recv_frame(sock)
        assert reply["type"] == "ERR" and "malformed" in reply["error"]
        # framing stayed aligned: the same connection still handles real verbs
        rpc.send_frame(
            sock, {"type": "QUERY", "secret": server.secret, "partition_id": 0}
        )
        assert rpc.recv_frame(sock)["type"] == "QUERY"
    finally:
        sock.close()


def test_non_object_payload_gets_err(server):
    sock = _raw_conn(server)
    try:
        blob = b'[1, 2, 3]'
        sock.sendall(rpc._LEN.pack(len(blob)) + blob)
        reply = rpc.recv_frame(sock)
        assert reply["type"] == "ERR" and "JSON object" in reply["error"]
    finally:
        sock.close()


def test_truncated_frame_disconnect_is_clean(server):
    sock = _raw_conn(server)
    # declare 100 bytes, send 10, vanish mid-frame
    sock.sendall(rpc._LEN.pack(100) + b"0123456789")
    sock.close()
    _server_still_serves(server)


def test_send_frame_rejects_oversized_client_side():
    class _NullSock:
        def sendall(self, data):
            raise AssertionError("oversized frame must not reach the wire")

    with pytest.raises(RpcError, match="exceeds frame cap"):
        rpc.send_frame(_NullSock(), {"blob": "x" * (constants.RPC_MAX_MESSAGE + 1)})
