"""Model-family tests: decoder forward correctness, scan/unroll equivalence,
remat equivalence, GQA, MLP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models import MLP, Decoder, DecoderConfig


def tiny(**kw):
    return DecoderConfig.tiny(**kw)


def test_decoder_forward_shapes_and_dtype():
    cfg = tiny()
    model = Decoder(cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32  # logits always fp32 for a stable loss


def test_decoder_causality():
    """Changing a future token must not change past logits."""
    cfg = tiny()
    model = Decoder(cfg)
    t1 = jnp.asarray(np.arange(16)[None, :] % cfg.vocab_size, dtype=jnp.int32)
    t2 = t1.at[0, 10].set((int(t1[0, 10]) + 1) % cfg.vocab_size)
    variables = model.init(jax.random.key(0), t1)
    l1 = model.apply(variables, t1)
    l2 = model.apply(variables, t2)
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
    assert not np.allclose(l1[0, 10:], l2[0, 10:], atol=1e-5)


def test_scan_matches_unrolled():
    cfg_s = tiny(scan_layers=True)
    cfg_u = tiny(scan_layers=False)
    tokens = jnp.asarray(np.arange(12)[None, :], dtype=jnp.int32)
    vs = Decoder(cfg_s).init(jax.random.key(1), tokens)

    # map scanned params [L, ...] onto the unrolled layout layers_{i}
    import flax.linen as nn

    def unstack(tree, i):
        return jax.tree.map(
            lambda x: x[i],
            tree,
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )

    scanned = vs["params"]["layers"]["layer"]
    unrolled_params = {
        k: v for k, v in vs["params"].items() if k != "layers"
    }
    for i in range(cfg_u.n_layers):
        layer_i = jax.tree.map(lambda x: x[i] if hasattr(x, "shape") else x,
                               jax.tree.map(lambda x: x.value if isinstance(x, nn.Partitioned) else x,
                                            scanned,
                                            is_leaf=lambda x: isinstance(x, nn.Partitioned)))
        unrolled_params[f"layers_{i}"] = {"layer": layer_i}

    out_s = Decoder(cfg_s).apply(vs, tokens)
    out_u = Decoder(cfg_u).apply({"params": unrolled_params}, tokens)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_u), atol=2e-2)


def test_remat_matches_plain():
    cfg_a = tiny(remat=False)
    cfg_b = tiny(remat=True)
    tokens = jnp.asarray(np.arange(12)[None, :], dtype=jnp.int32)
    variables = Decoder(cfg_a).init(jax.random.key(2), tokens)
    la = Decoder(cfg_a).apply(variables, tokens)
    lb = Decoder(cfg_b).apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-5)


def test_gqa_heads_validation():
    with pytest.raises(ValueError):
        DecoderConfig(d_model=64, n_heads=4, n_kv_heads=3)
    with pytest.raises(ValueError):
        DecoderConfig(d_model=65, n_heads=4)


def test_llama3_8b_geometry():
    cfg = DecoderConfig.llama3_8b()
    assert cfg.d_model == 4096 and cfg.n_layers == 32 and cfg.n_kv_heads == 8
    assert cfg.head_dim == 128


def test_mlp_forward():
    model = MLP(features=(32, 16), num_classes=10)
    x = jnp.zeros((4, 28, 28))
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x)
    assert out.shape == (4, 10)
