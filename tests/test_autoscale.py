"""Fleet autoscaler (maggy_tpu/serve/fleet/autoscale.py): the capacity loop.

The decision ladder (brownout handoff, hysteresis holds, cooldown,
min/max clamps, headroom gates) is a pure function over frozen
``Observation`` rows, so it is unit-tested without a fleet — including
the satellite-4 properties: sustained brownout level >= 2 scales out,
recovery steps brownout down to 0 *before* any scale-in, and the
cooldown prevents flapping under the seeded diurnal+burst replay. The
drain-safe scale events (byte-identical scale-in, kill-mid-drain chaos
fallback, half-open probation on scale-up) run against real engines on
CPU, mirroring tests/test_serve_fleet.py.
"""

import dataclasses
import threading
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.resilience import chaos
from maggy_tpu.serve import ServeClient
from maggy_tpu.serve.fleet import (
    AutoscaleConfig,
    Autoscaler,
    ReplicaSpec,
    Router,
    RouterConfig,
    launch_fleet,
)
from maggy_tpu.serve.fleet.autoscale import Observation
from maggy_tpu.serve.fleet.replica import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    DEAD,
    UP,
    CircuitBreaker,
)
from maggy_tpu.serve.loadgen import diurnal_burst_spec
from maggy_tpu.serve.loadgen import generate as gen_schedule

CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = Decoder(CFG)
    return unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )


def reference(params, prompt, max_new):
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = np.zeros((1, len(prompt) + max_new), np.int32)
    buf[0, : len(prompt)] = prompt
    out = generate_cached(
        decode_model, params, jnp.asarray(buf), jnp.asarray([len(prompt)])
    )
    return list(np.asarray(out)[0, len(prompt):])


# ------------------------------------------------------------ decision ladder


def bare_autoscaler(**cfg_kwargs):
    """An Autoscaler over a stub router: decide() never touches fleet
    state, so the ladder is testable with no replicas at all."""
    router = types.SimpleNamespace(
        replicas=[],
        telemetry=types.SimpleNamespace(
            event=lambda *a, **k: None,
            count=lambda *a, **k: None,
        ),
    )
    return Autoscaler(router, config=AutoscaleConfig(**cfg_kwargs))


def obs(now, replicas=2, util=0.5, queue=0, level=0, headroom=0.5):
    return Observation(
        now=float(now),
        replicas=replicas,
        util=util,
        queue_depth=queue,
        brownout_level=level,
        headroom_pct=headroom,
    )


def test_sustained_brownout_scales_out():
    a = bare_autoscaler(escalate_hold_s=4.0, high_hold_s=3.0)
    assert a.decide(obs(0.0, level=2)) is None
    assert a.decide(obs(2.0, level=2)) is None
    assert a.decide(obs(4.0, level=2)) == "up"


def test_brownout_blip_resets_the_hold():
    a = bare_autoscaler(escalate_hold_s=4.0)
    assert a.decide(obs(0.0, level=2)) is None
    assert a.decide(obs(3.0, level=0)) is None  # ladder recovered: clock resets
    assert a.decide(obs(5.0, level=3)) is None
    assert a.decide(obs(8.0, level=3)) is None  # only 3s of the new episode
    assert a.decide(obs(9.0, level=3)) == "up"


def test_high_util_scales_out():
    a = bare_autoscaler(high_hold_s=3.0, target_util=0.8)
    assert a.decide(obs(0.0, util=0.95)) is None
    assert a.decide(obs(2.0, util=0.5)) is None  # dipped: clock resets
    assert a.decide(obs(3.0, util=0.95)) is None
    assert a.decide(obs(6.0, util=0.95)) == "up"


def test_recovery_steps_brownout_down_before_scale_in():
    """The ladder unwinds first: the idle clock must not start while the
    fleet is still degrading requests (brownout > 0), however low the
    utilization already is."""
    a = bare_autoscaler(low_hold_s=6.0)
    assert a.decide(obs(0.0, util=0.1, level=2)) is None
    assert a.decide(obs(1.0, util=0.1, level=1)) is None
    assert a.decide(obs(2.0, util=0.1, level=1)) is None
    # ladder reaches 0 at t=3: the low_hold clock starts HERE, not at t=0
    assert a.decide(obs(3.0, util=0.1, level=0)) is None
    assert a.decide(obs(8.5, util=0.1, level=0)) is None
    assert a.decide(obs(9.0, util=0.1, level=0)) == "down"


def test_scale_in_requires_empty_queue():
    a = bare_autoscaler(low_hold_s=2.0)
    assert a.decide(obs(0.0, util=0.1)) is None
    assert a.decide(obs(1.0, util=0.1, queue=3)) is None  # backlog: clock resets
    assert a.decide(obs(2.0, util=0.1)) is None
    assert a.decide(obs(4.0, util=0.1)) == "down"


def test_scale_in_blocked_without_headroom():
    a = bare_autoscaler(low_hold_s=1.0, min_headroom_pct=0.05)
    assert a.decide(obs(0.0, util=0.1, headroom=0.01)) is None
    assert a.decide(obs(1.0, util=0.1, headroom=0.01)) is None  # HBM tight
    assert a.decide(obs(1.2, util=0.1, headroom=0.40)) == "down"


def test_scale_in_blocked_when_survivors_would_run_hot():
    a = bare_autoscaler(low_hold_s=1.0, target_util=0.4, low_util=0.35)
    assert a.decide(obs(0.0, replicas=2, util=0.3)) is None
    # idle held, but 2 -> 1 projects 0.6 utilization > 0.4 target
    assert a.decide(obs(1.5, replicas=2, util=0.3)) is None
    b = bare_autoscaler(low_hold_s=1.0, target_util=0.4, low_util=0.35)
    assert b.decide(obs(0.0, replicas=3, util=0.2)) is None
    # 3 -> 2 projects 0.3 < 0.4: safe
    assert b.decide(obs(1.5, replicas=3, util=0.2)) == "down"


def test_max_clamp_journals_at_capacity_once():
    a = bare_autoscaler(max_replicas=2, escalate_hold_s=1.0)
    assert a.decide(obs(0.0, replicas=2, level=2)) is None
    assert a.decide(obs(1.0, replicas=2, level=2)) is None  # pinned at max
    assert a.at_capacity()
    assert a.decide(obs(2.0, replicas=2, level=2)) is None

    def blocked():
        return [e for e in a.events if e["event"] == "fleet.scale.blocked"]

    assert len(blocked()) == 1  # one journal entry per pressure episode
    # pressure clears: flag drops, a later episode journals again
    assert a.decide(obs(3.0, replicas=2, level=0)) is None
    assert not a.at_capacity()
    assert a.decide(obs(4.0, replicas=2, level=2)) is None
    assert a.decide(obs(5.0, replicas=2, level=2)) is None
    assert len(blocked()) == 2


def test_min_clamp_blocks_scale_in():
    a = bare_autoscaler(min_replicas=1, low_hold_s=1.0)
    assert a.decide(obs(0.0, replicas=1, util=0.0)) is None
    assert a.decide(obs(2.0, replicas=1, util=0.0)) is None


def test_cooldown_prevents_flapping_under_burst_replay():
    """Satellite 4: replay the seeded diurnal+burst schedule through the
    ladder as a synthetic utilization series; every pair of scale events
    must be separated by the cooldown, and the burst must still force at
    least one scale-out."""
    spec = diurnal_burst_spec(
        seed=7, duration_s=60.0, base_rps=3.0, burst_mult=6.0
    )
    per_sec = [0] * 60
    for arrival in gen_schedule(spec):
        per_sec[min(59, int(arrival.at_s))] += 1
    a = bare_autoscaler(
        max_replicas=4,
        scale_cooldown_s=10.0,
        escalate_hold_s=4.0,
        high_hold_s=2.0,
        low_hold_s=5.0,
    )
    replicas, events = 1, []
    for sec, rate in enumerate(per_sec):
        # 3 slots per replica; offered rate saturates them linearly
        util = min(1.0, rate / (3.0 * replicas))
        action = a.decide(obs(float(sec), replicas=replicas, util=util))
        if action == "up":
            replicas += 1
        elif action == "down":
            replicas -= 1
        if action:
            a._last_event_ts = float(sec)  # what actuation would stamp
            events.append((sec, action))
    assert any(kind == "up" for _, kind in events), (
        f"burst never forced a scale-out: {events}"
    )
    gaps = [t2 - t1 for (t1, _), (t2, _) in zip(events, events[1:])]
    assert all(g >= 10.0 for g in gaps), (
        f"scale events inside the cooldown window: {events}"
    )
    assert 1 <= replicas <= 4


# ------------------------------------------------------- chaos kinds (sat 3)


def test_guard_defers_rollback_while_storm_persists(monkeypatch):
    """The post-scale-up guard must not revert capacity while the very
    overload that triggered the scale-out is still blowing attainment
    down (doomed backlog completing late): the window re-arms against
    the degraded baseline instead, and judges again once pressure moves
    — the no-fight rule, applied to the guard itself."""
    a = bare_autoscaler(guard_window_s=1.0, regress_tol=0.1)
    a._guard = {"direction": "up", "since": 0.0, "baseline": 0.9, "replica": 7}
    monkeypatch.setattr(a, "_attainment", lambda now, w: 0.1)
    monkeypatch.setattr(a, "observe", lambda now: obs(now, level=3))
    a._tick_guard(2.0)
    assert a._guard is not None  # deferred, not rolled back
    assert a._guard["since"] == 2.0 and a._guard["baseline"] == 0.1
    assert a.events[-1]["event"] == "fleet.scale.guard_extended"
    assert a.events[-1]["brownout"] == 3
    # pressure moved: attainment holds against the re-armed baseline
    monkeypatch.setattr(a, "observe", lambda now: obs(now, level=0, util=0.2))
    monkeypatch.setattr(a, "_attainment", lambda now, w: 0.6)
    a._tick_guard(4.0)
    assert a._guard is None
    assert a.events[-1]["event"] == "fleet.scale.committed"


def test_new_chaos_kinds_are_declared():
    assert "replica_spawn_slow" in chaos.KINDS
    assert "replica_kill_mid_drain" in chaos.KINDS


def test_replica_spawn_slow_seam():
    ch = chaos.Chaos.parse("replica_spawn_slow:replica=2,secs=0.5")
    assert ch.replica_spawn_slow(1) == 0.0  # wrong replica: no fault
    assert ch.replica_spawn_slow(2) == 0.5
    assert ch.replica_spawn_slow(2) == 0.0  # budget (times=1) consumed


def test_replica_kill_mid_drain_seam():
    ch = chaos.Chaos.parse("replica_kill_mid_drain:replica=1")
    assert ch.replica_kill_mid_drain(0) is False
    assert ch.replica_kill_mid_drain(1) is True
    assert ch.replica_kill_mid_drain(1) is False  # fires exactly once


# ----------------------------------------- breaker probation + reset (sat 1)


def test_breaker_reset_returns_to_pristine_closed():
    br = CircuitBreaker(0, trips=1, cooldown_s=5.0)
    now = time.time()
    assert br.score(1000.0, 10.0, 3.0, 50.0, now) == "opened"
    assert br.state == BREAKER_OPEN
    br.reset()
    assert br.state == BREAKER_CLOSED
    assert br.ok(now)  # dispatchable immediately, no cooldown ghost


def test_breaker_probation_gate():
    br = CircuitBreaker(1, trips=2, cooldown_s=5.0)
    br.begin_probation(close_below_ms=100.0)
    assert br.state == BREAKER_HALF_OPEN
    assert br.take_probe("r1")
    assert not br.take_probe("r2")  # one canary at a time
    br.observe_ttft("r1", 50.0, time.time())  # under the bar: closes
    assert br.state == BREAKER_CLOSED
    # a slow probe re-opens instead
    br2 = CircuitBreaker(2, trips=2, cooldown_s=5.0)
    br2.begin_probation(close_below_ms=100.0)
    assert br2.take_probe("r9")
    br2.observe_ttft("r9", 500.0, time.time())
    assert br2.state == BREAKER_OPEN


def fake_replica(index, state=UP, num_slots=4):
    """A replica-shaped namespace for router unit tests (no engine)."""
    return types.SimpleNamespace(
        index=index,
        state=state,
        spec=types.SimpleNamespace(num_slots=num_slots, role="any"),
        describe=lambda: {"replica": index, "state": state, "addr": None,
                          "restarts": 0, "devices": [], "uptime_s": 0.0},
        client=None,
        stop=lambda drain=True, timeout=30.0: None,
        kill=lambda: None,
        respawn=lambda: ("127.0.0.1", 9999),
    )


def test_respawn_resets_breaker_window_and_metrics():
    """Satellite 1: a respawned replica shares nothing with the dead one.
    Its breaker must come back pristine CLOSED and its pre-death
    SeriesStore must be dropped, or stale latency samples re-open the
    breaker / re-trip alerts on the fresh stack."""
    dead = fake_replica(0, state=DEAD)
    router = Router([dead], config=RouterConfig(max_restarts=1))
    # the pre-death state a naive respawn would leak: an OPEN breaker
    # (probation probe lost when the replica died) + a latency store
    router.breakers[0].begin_probation(100.0)
    router.breakers[0].take_probe("ghost")
    router.replica_metrics[0] = object()
    router._handle_replica_down(dead)
    assert router.counters["respawned"] == 1
    assert router.breakers[0].state == BREAKER_CLOSED
    assert router.breakers[0].ok(time.time())
    assert 0 not in router.replica_metrics
    assert 0 not in router._down_handled


def test_respawn_suppressed_for_draining_replica():
    """A death mid-drain is the kill-mid-drain fallback: requeue happens,
    but the victim being deliberately removed must never respawn."""
    dead = fake_replica(0, state=DEAD)
    router = Router([dead], config=RouterConfig(max_restarts=1))
    router.begin_drain(0)
    router._handle_replica_down(dead)
    assert router.counters["respawned"] == 0
    assert router._restarts_used == 0


# ------------------------------------------------ retire forgets all (sat 2)


def test_retire_forgets_every_per_replica_trace():
    r0, r1 = fake_replica(0), fake_replica(1)
    router = Router([r0, r1], config=RouterConfig())
    router.prefix_map.update(0, ["d0"])
    router.prefix_map.update(1, ["d1", "shared"])
    router.prefix_map.update(0, ["shared"])
    router._stats_cache[1] = {"active_slots": 0}
    router.replica_metrics[1] = object()
    router.begin_drain(1)
    with router._lock:
        assert router._fleet_stats()["replicas"][1]["state"] == "draining"
    router.retire_replica(r1)
    assert [r.index for r in router.replicas] == [0]
    assert 1 not in router.breakers
    assert 1 not in router.retry_budgets
    assert 1 not in router._stats_cache
    assert 1 not in router.replica_metrics
    assert 1 not in router._draining
    # the prefix map forgets the victim but keeps survivors' entries
    assert router.prefix_map.replicas_for("d1") == frozenset()
    assert router.prefix_map.replicas_for("shared") == frozenset({0})
    # and FSTATS carries no ghost row
    with router._lock:
        rows = router._fleet_stats()["replicas"]
    assert [row["replica"] for row in rows] == [0]


def test_admit_replica_builds_fresh_probation_breaker():
    r0 = fake_replica(0)
    router = Router([r0], config=RouterConfig(slo_ttft_ms=800.0))
    fresh = fake_replica(5)
    router.admit_replica(fresh, probation=True)
    assert [r.index for r in router.replicas] == [0, 5]
    assert router.breakers[5].state == BREAKER_HALF_OPEN
    assert 5 in router.retry_budgets
    # index allocator never reuses: next spawn is past the admitted one
    assert router.allocate_index() == 6


def test_rebalance_excess_sheds_pinned_backlog():
    """When capacity comes online, routed-but-unstarted work pinned to
    the overloaded replica is requeued to the shared queue (oldest two
    waves per slot stay put; the shed tail keeps its original order,
    ahead of fresh arrivals)."""
    from maggy_tpu.serve.fleet.router import ROUTED, REQUEUED, RouteEntry

    r0 = fake_replica(0, num_slots=1)
    router = Router([r0], config=RouterConfig())
    for i in range(5):
        e = RouteEntry(
            rid=f"r{i}", payload={"prompt": [1, 2, 3], "qos": "standard"},
            state=ROUTED, replica=0, submitted_ts=100.0 + i,
        )
        router._entries[e.rid] = e
    # one stream already producing tokens and one finished: both stay
    started = RouteEntry(
        rid="started", payload={"prompt": [4]}, state=ROUTED, replica=0,
        snapshot={"n_tokens": 2}, submitted_ts=90.0,
    )
    finished = RouteEntry(
        rid="fin", payload={"prompt": [5]}, state=ROUTED, replica=0,
        final={"done": True, "state": "done"}, submitted_ts=91.0,
    )
    router._entries["started"] = started
    router._entries["fin"] = finished
    moved = router.rebalance_excess()
    assert moved == 3  # keep = 2 slots x 1; r0/r1 stay bound, r2-r4 shed
    assert router.counters["requeued"] == 3
    assert list(router._pending) == ["r2", "r3", "r4"]  # original order
    for rid in ("r2", "r3", "r4"):
        e = router._entries[rid]
        assert e.state == REQUEUED and e.replica is None and e.resubmits == 1
    for rid in ("r0", "r1", "started", "fin"):
        assert router._entries[rid].state == ROUTED
        assert router._entries[rid].replica == 0
    # idempotent: nothing left above the per-slot keep line
    assert router.rebalance_excess() == 0


# --------------------------------------------- scale events on real engines


# holds and cooldown pinned far out so decide() never fires on its own:
# these tests drive scale events directly and assert the drain/warm
# machinery, not the (unit-tested) ladder timing
EVENT_CFG = dict(
    min_replicas=1,
    max_replicas=2,
    scale_cooldown_s=600.0,
    escalate_hold_s=600.0,
    high_hold_s=600.0,
    low_hold_s=600.0,
    guard_window_s=0.5,
    drain_grace_s=0.4,
    drain_timeout_s=30.0,
    warm_timeout_s=240.0,
)


def _drive(host, port, secret, prompts, max_new, results, errors, stagger=0.03):
    threads = []

    def one(i, prompt, delay):
        try:
            time.sleep(delay)
            with ServeClient((host, port), secret) as client:
                results[i] = client.generate(prompt, max_new=max_new, timeout=240)
        except Exception as e:  # noqa: BLE001 - surfaced via the errors list
            errors.append((i, repr(e)))

    for i, p in enumerate(prompts):
        t = threading.Thread(target=one, args=(i, p, stagger * i))
        t.start()
        threads.append(t)
    return threads


def _wait_retired(router, index, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if router._replica(index) is None:
            return True
        time.sleep(0.05)
    return False


def test_scale_in_drain_is_byte_identical(params):
    """Drain-based scale-in mid-traffic: every request completes with
    tokens byte-identical to single-engine decode — finished on the
    victim inside the grace, or spilled + requeued to the survivor."""
    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=2),
        replicas=2,
        autoscale=AutoscaleConfig(**EVENT_CFG),
    )
    host, port = router.start(host="127.0.0.1")
    prompts = [
        [1, 2, 3], [4, 5, 6, 7], [8, 9], [10, 11, 12],
        [13, 14, 15, 16], [3, 1, 4],
    ]
    max_new = 8
    results, errors = {}, []
    try:
        threads = _drive(host, port, router.secret, prompts, max_new,
                         results, errors)
        time.sleep(0.5)  # let dispatch spread waves over both replicas
        victim = router._replica(1)
        assert victim is not None
        router.autoscaler._begin_scale_down(
            time.time(), reason="test", victim=victim
        )
        for t in threads:
            t.join(timeout=240)
        assert not errors, errors
        assert len(results) == len(prompts)
        for i, prompt in enumerate(prompts):
            assert results[i] == reference(params, prompt, max_new), (
                f"request {i} diverges across the scale-in drain"
            )
        assert _wait_retired(router, 1), "victim never retired"
        events = [e["event"] for e in router.autoscaler.snapshot()["events"]]
        assert "fleet.scale.down" in events
        assert "fleet.scale.retired" in events
        assert router.counters["failed"] == 0
    finally:
        router.stop()


def test_kill_mid_drain_falls_back_to_requeue(params):
    """Chaos kills the victim while its drain is in progress: the down
    path requeues its streams (no respawn — it was being removed), the
    autoscaler finishes the retire, and completions stay byte-identical."""
    chaos.install(chaos.Chaos.parse("replica_kill_mid_drain:replica=1"))
    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=2),
        replicas=2,
        autoscale=AutoscaleConfig(**EVENT_CFG),
    )
    host, port = router.start(host="127.0.0.1")
    prompts = [[2, 3, 4], [5, 6, 7, 8], [9, 10], [11, 12, 13], [1, 2]]
    max_new = 8
    results, errors = {}, []
    try:
        threads = _drive(host, port, router.secret, prompts, max_new,
                         results, errors)
        time.sleep(0.5)
        victim = router._replica(1)
        assert victim is not None
        router.autoscaler._begin_scale_down(
            time.time(), reason="test", victim=victim
        )
        for t in threads:
            t.join(timeout=240)
        assert not errors, errors
        for i, prompt in enumerate(prompts):
            assert results[i] == reference(params, prompt, max_new), (
                f"request {i} diverges across the kill-mid-drain fallback"
            )
        assert _wait_retired(router, 1), "victim never retired"
        retired = [
            e for e in router.autoscaler.snapshot()["events"]
            if e["event"] == "fleet.scale.retired"
        ]
        assert retired and retired[0]["mode"] == "kill_fallback"
        assert router.counters["respawned"] == 0
    finally:
        chaos.reset()
        router.stop()


def test_scale_up_admits_behind_probation_gate(params):
    """Scale-up warms off-pump (compile + probe) and admits HALF_OPEN:
    the first real request is the canary that closes the breaker, and its
    tokens match single-engine decode."""
    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=2),
        replicas=1,
        config=RouterConfig(slo_ttft_ms=5000.0),
        autoscale=AutoscaleConfig(**EVENT_CFG),
    )
    host, port = router.start(host="127.0.0.1")
    try:
        with ServeClient((host, port), router.secret) as client:
            client.generate([1, 2, 3], max_new=2, timeout=240)  # warm r0
            router.autoscaler._begin_scale_up(time.time(), reason="test")
            deadline = time.time() + 240
            while time.time() < deadline and len(router.replicas) < 2:
                time.sleep(0.05)
            assert len(router.replicas) == 2, "warmed replica never admitted"
            breaker = router.breakers[1]
            # admitted in probation: no traffic has closed it yet
            assert breaker.state == BREAKER_HALF_OPEN
            prompts = [[5, 6, 7], [8, 9], [2, 4, 6], [1, 3, 5, 7]]
            outs = [
                client.generate(p, max_new=4, timeout=240) for p in prompts
            ]
            deadline = time.time() + 30
            while time.time() < deadline and breaker.state != BREAKER_CLOSED:
                time.sleep(0.05)
            assert breaker.state == BREAKER_CLOSED, (
                "probation canary never closed the breaker"
            )
            for p, out in zip(prompts, outs):
                assert out == reference(params, p, 4)
        events = [e["event"] for e in router.autoscaler.snapshot()["events"]]
        assert "fleet.scale.up" in events
        assert "fleet.scale.admitted" in events
    finally:
        router.stop()


def test_monitor_renders_autoscale_line_and_draining_tag():
    from maggy_tpu.monitor import render_status

    out = render_status(
        {
            "kind": "ServeFleet",
            "name": "fleet",
            "state": "RUNNING",
            "app_id": "a",
            "run_id": 1,
            "elapsed_s": 4.0,
            "fleet": {
                "routing": {"routed": 9, "requeued": 1, "shed": 0,
                            "respawned": 0},
                "replicas": [
                    {"replica": 0, "state": "up", "active_slots": 1,
                     "num_slots": 2, "queue_depth": 0, "requests_done": 5,
                     "prefix_hits": 0},
                    {"replica": 1, "state": "draining", "active_slots": 1,
                     "num_slots": 2, "queue_depth": 0, "requests_done": 4,
                     "prefix_hits": 0},
                ],
            },
            "serve": {
                "queue_depth": 0,
                "requests_done": 9,
                "autoscale": {
                    "phase": "draining",
                    "min_replicas": 1,
                    "max_replicas": 4,
                    "at_capacity": False,
                    "last_event": {"event": "fleet.scale.down",
                                   "reason": "idle"},
                },
            },
        }
    )
    assert "autoscale: 2 replicas [1..4]" in out
    assert "phase=draining" in out
    assert "last=fleet.scale.down(idle)" in out
    assert "DRAI" in out


@pytest.mark.slow
def test_burst_drives_scale_out_end_to_end(params):
    """The full loop under the canned diurnal+burst replay: sustained
    pressure walks the brownout ladder, the autoscaler scales out, and
    no request fails across the scale event."""
    from maggy_tpu.serve import TrafficReplay
    from maggy_tpu.serve.qos import STANDARD

    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=2, paged=True, num_pages=8),
        replicas=1,
        config=RouterConfig(
            slo_ttft_ms=400.0,
            admission="queue",
            brownout_escalate_s=0.3,
            brownout_recover_s=1.0,
        ),
        autoscale=AutoscaleConfig(
            min_replicas=1,
            max_replicas=2,
            scale_cooldown_s=3.0,
            escalate_hold_s=0.5,
            high_hold_s=0.5,
            low_hold_s=2.0,
            guard_window_s=1.0,
            drain_grace_s=0.5,
            warm_timeout_s=240.0,
        ),
    )
    host, port = router.start(host="127.0.0.1")
    try:
        with ServeClient((host, port), router.secret) as client:
            # warm with standard class: best-effort warmups would be held
            # by the SLO queue-gate once the first compile inflates the
            # TTFT projection
            for i in range(4):
                client.generate(list(range(1 + i, 13 + i)), max_new=2,
                                qos=STANDARD, timeout=240)
            spec = diurnal_burst_spec(
                seed=7, duration_s=10.0, base_rps=6.0, burst_mult=6.0
            )
            outcomes = TrafficReplay(
                client, gen_schedule(spec), result_timeout_s=60.0
            ).run(timeout=240.0)
        events = [e["event"] for e in router.autoscaler.snapshot()["events"]]
        assert "fleet.scale.up" in events, (
            f"burst never drove a scale-out: {events}"
        )
        failed = [
            o for o in outcomes
            if o["status"] in ("failed", "submit_error")
        ]
        assert not failed, failed
    finally:
        router.stop()
