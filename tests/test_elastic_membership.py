"""Elastic membership acceptance (ISSUE 9, docs/resilience.md "Elastic
membership"): checkpoint-consistent mesh reshape when slices leave and
rejoin, driven end-to-end on the CPU mesh by the deterministic chaos
harness — drop slice 1 of 2 mid-run and the run completes at reduced width
with the membership epoch bumped and the final loss matching an
uninterrupted run; a rejoin restores full width; a min_slices violation
fails clean; plus the slice-topology mesh units, the double-fault restart
serialization regression, the Checkpointer warn-and-reshard satellite, and
the chaos-kind registry lint."""

import glob
import json
import os

import numpy as np
import pytest

from maggy_tpu import experiment, telemetry
from maggy_tpu.config import DistributedConfig
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.resilience.membership import (
    MembershipMonitor,
    MembershipView,
    MembershipViolation,
)

VOCAB_SEED = 5
NUM_STEPS = 8


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos_mod.reset()
    yield
    chaos_mod.reset()


# ----------------------------------------------------------------- units


def test_membership_view_transitions():
    view = MembershipView.full(3, min_slices=1, mode="sim")
    assert view.epoch == 0 and view.active == (0, 1, 2) and view.inactive == ()

    v1 = view.drop(1)
    assert v1.epoch == 1 and v1.active == (0, 2) and v1.inactive == (1,)
    # duplicate fault report: idempotent, no epoch burn
    assert v1.drop(1) is v1

    v2 = v1.rejoin(1)
    assert v2.epoch == 2 and v2.active == (0, 1, 2)
    assert v2.rejoin(1) is v2
    with pytest.raises(ValueError):
        v2.rejoin(7)  # outside the launch topology

    # wire round-trip
    assert MembershipView.from_dict(v2.as_dict()) == v2

    # min_slices floor: a clean deterministic violation, never a hang
    floor = MembershipView.full(2, min_slices=2)
    with pytest.raises(MembershipViolation):
        floor.drop(0)


def test_membership_monitor_signal_and_adopt():
    mon = MembershipMonitor(MembershipView.full(2))
    assert mon.pending_epoch() is None
    mon.signal(0)  # not newer: ignored
    assert mon.pending_epoch() is None
    mon.signal(2)
    assert mon.pending_epoch() == 2
    mon.adopt(MembershipView(epoch=2, total_slices=2, active=(0,)))
    assert mon.pending_epoch() is None and mon.active == (0,)


def test_slice_topology_mesh_and_rules():
    import jax

    from maggy_tpu.parallel import sharding as shd
    from maggy_tpu.parallel.mesh import make_slice_mesh, slice_device_groups
    from maggy_tpu.parallel.spec import AXIS_SLICE, ShardingSpec, SliceTopology

    groups = slice_device_groups(2)
    assert len(groups) == 2 and len(groups[0]) == 4
    # slices are contiguous partitions, slice-major (the dryrun generalization)
    assert groups[0] + groups[1] == list(jax.devices())
    with pytest.raises(ValueError):
        slice_device_groups(3)  # 8 devices don't split into 3

    topo = SliceTopology(n_slices=2, slice_spec=ShardingSpec(fsdp=4))
    assert topo.num_devices == 8 and topo.devices_per_slice == 4
    mesh = make_slice_mesh(topo)
    assert dict(mesh.shape)[AXIS_SLICE] == 2
    assert dict(mesh.shape)["fsdp"] == 4
    # reshape transition preserves the per-slice layout
    assert topo.with_slices(1).slice_spec == topo.slice_spec

    # n=8 geometry on the CPU mesh: one device per simulated slice
    wide = SliceTopology(n_slices=8, slice_spec=ShardingSpec())
    assert dict(make_slice_mesh(wide).shape)[AXIS_SLICE] == 8

    # batch spans (slice, data, fsdp) under slice rules; params never
    # shard over slice (the reshape is a pure re-placement)
    rules = dict(shd.slice_rules())
    assert rules["batch"] == (AXIS_SLICE, "data", "fsdp")
    assert rules["embed"] == "fsdp"


def test_chaos_slice_kinds():
    ch = chaos_mod.Chaos.parse("slice_drop:slice=1,step=4;slice_rejoin:slice=1,step=6")
    assert ch.slice_drop((0, 1), step=3) is None  # step mismatch
    assert ch.slice_drop((0, 1), step=4) == 1
    assert ch.slice_drop((0, 1), step=4) is None  # budget consumed
    assert ch.slice_rejoin((1,), step=6) == 1
    with pytest.raises(ValueError, match="unknown kind"):
        # built dynamically so the kind-registry lint (which checks literal
        # specs) doesn't flag this deliberate typo
        chaos_mod.Chaos.parse("slice_" + "dorp:slice=1")


# --------------------------------------------------------------- harness


def _exported_counters(exp_dir):
    merged = {}
    for path in glob.glob(os.path.join(exp_dir, "telemetry", "*.jsonl")):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "snapshot":
                    for k, v in (rec.get("counters") or {}).items():
                        merged[k] = merged.get(k, 0) + v
    return merged


def _exported_gauges(exp_dir):
    merged = {}
    for path in glob.glob(os.path.join(exp_dir, "telemetry", "*.jsonl")):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "snapshot":
                    merged.update(rec.get("gauges") or {})
    return merged


class RecordingBatches:
    """Data-parity harness (the PR 5 ``skip()`` discipline): a fresh
    synthetic stream per train_fn invocation that logs the global batch
    index of every batch SERVED to fit and where each resume skipped to,
    so the test can prove every global batch index lands in the committed
    trajectory exactly once across reshapes."""

    def __init__(self, vocab_size, log):
        from maggy_tpu.train.data import synthetic_lm_batches

        self._it = synthetic_lm_batches(vocab_size, 8, 16, seed=VOCAB_SEED)
        self._pos = 0
        self._segment = {"resume_from": 0, "served": []}
        log.append(self._segment)

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self._it)
        self._segment["served"].append(self._pos)
        self._pos += 1
        return batch

    def skip(self, n):
        for _ in range(n):
            next(self._it)
        self._pos += n
        self._segment["resume_from"] = self._pos
        return n


def _assert_exactly_once(segments, total):
    """Committed trajectory check: each segment serves a contiguous run
    from its resume point; truncating each segment at its successor's
    resume point must tile 0..total-1 with no gap and no overlap."""
    committed = []
    for i, seg in enumerate(segments):
        start = seg["resume_from"]
        assert seg["served"] == list(range(start, start + len(seg["served"])))
        end = segments[i + 1]["resume_from"] if i + 1 < len(segments) else total
        committed.extend(range(start, end))
    assert committed == list(range(total))


def _train_fn_factory(cfg, data_log=None, num_steps=NUM_STEPS):
    import jax
    import optax

    from maggy_tpu.train.checkpoint import Checkpointer
    from maggy_tpu.train.data import synthetic_lm_batches

    def train(model, hparams, reporter, ctx, trial_dir):
        trainer = ctx.trainer(model, optax.adamw(3e-3))
        if data_log is not None:
            data = RecordingBatches(cfg.vocab_size, data_log)
        else:
            data = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=VOCAB_SEED)
        state = trainer.make_state(
            jax.random.key(0),
            next(synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=VOCAB_SEED)),
        )
        ckpt = Checkpointer(os.path.join(trial_dir, "ckpt"), async_save=False)
        try:
            # prefetch=0: chaos fires at exact step boundaries and the
            # parity harness equates served batches with executed steps
            state, metrics = trainer.fit(
                state, data, num_steps=num_steps, checkpointer=ckpt,
                checkpoint_every=2, resume="auto", prefetch=0,
            )
        finally:
            ckpt.close()
        return {"metric": -metrics["loss"], "loss": metrics["loss"]}

    return train


def _elastic_conf(cfg, **kw):
    defaults = dict(
        module=None, hparams={}, sharding="fsdp", data_plane="local",
        hb_interval=0.05, elastic=True, num_slices=2, min_slices=1,
    )
    defaults.update(kw)
    from maggy_tpu.models import Decoder

    defaults["module"] = Decoder(cfg)
    return DistributedConfig(**defaults)


# ------------------------------------------------------------ acceptance

# the uninterrupted reference run is identical for the drop and rejoin
# acceptance tests (same seed, same config, loss independent of env root) —
# computed once per session so tier-1 pays its compile cost once
_REF = {}


def _ref_loss(cfg):
    if "loss" not in _REF:
        _REF["loss"] = experiment.lagom(
            _train_fn_factory(cfg), _elastic_conf(cfg)
        )["loss"]
    return _REF["loss"]


def test_slice_drop_reshapes_and_matches_uninterrupted(tmp_env):
    """ACCEPTANCE: drop slice 1 of 2 at step 5 → the run completes at
    reduced width with the membership epoch bumped, the reshape metrics in
    the exported telemetry, the final loss within tolerance of an
    uninterrupted run, and every global batch index consumed exactly once
    across the reshape (data-parity harness)."""
    from maggy_tpu.models import DecoderConfig

    cfg = DecoderConfig.tiny()
    ref_loss = _ref_loss(cfg)

    chaos_mod.install(chaos_mod.Chaos.parse("slice_drop:slice=1,step=5"))
    log = []
    result = experiment.lagom(_train_fn_factory(cfg, data_log=log), _elastic_conf(cfg))
    assert result["num_workers"] == 1
    np.testing.assert_allclose(result["loss"], ref_loss, rtol=1e-3)

    # two fit segments: full width to the drop, reduced width from the
    # last complete checkpoint (step 4); indices tile 0..7 exactly once
    assert len(log) == 2
    assert log[1]["resume_from"] == 4  # checkpoint_every=2, drop at step 5
    _assert_exactly_once(log, NUM_STEPS)

    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    counters = _exported_counters(exp_dir)
    assert counters.get("resilience.slice_drops", 0) == 1
    gauges = _exported_gauges(exp_dir)
    assert gauges.get("resilience.membership_epoch") == 1
    assert gauges.get("resilience.active_slices") == 1
    assert gauges.get("resilience.reshape_ms", 0) > 0


def test_slice_rejoin_restores_width(tmp_env):
    """ACCEPTANCE: drop slice 1 at step 3, rejoin at step 6 → full width is
    restored (epoch 2), both transitions counted, loss still matches the
    uninterrupted run, and the committed trajectory stays exactly-once
    across BOTH reshapes (the rejoin one is graceful: fit checkpoints
    first, so nothing re-runs)."""
    from maggy_tpu.models import DecoderConfig

    cfg = DecoderConfig.tiny()
    ref_loss = _ref_loss(cfg)

    chaos_mod.install(
        chaos_mod.Chaos.parse("slice_drop:slice=1,step=3;slice_rejoin:slice=1,step=6")
    )
    log = []
    result = experiment.lagom(_train_fn_factory(cfg, data_log=log), _elastic_conf(cfg))
    np.testing.assert_allclose(result["loss"], ref_loss, rtol=1e-3)

    assert len(log) == 3
    assert log[1]["resume_from"] == 2  # abrupt drop: back to the last retained ckpt
    assert log[2]["resume_from"] == 6  # graceful rejoin: no step re-runs
    _assert_exactly_once(log, NUM_STEPS)

    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    counters = _exported_counters(exp_dir)
    assert counters.get("resilience.slice_drops", 0) == 1
    assert counters.get("resilience.slice_rejoins", 0) == 1
    assert counters.get("resilience.reshape_checkpoints", 0) >= 1
    gauges = _exported_gauges(exp_dir)
    assert gauges.get("resilience.membership_epoch") == 2
    assert gauges.get("resilience.active_slices") == 2


def test_min_slices_violation_fails_clean(tmp_env):
    """Shrinking below min_slices aborts deterministically with the
    violation as the experiment error — not a hang, not a restart loop."""
    from maggy_tpu.models import DecoderConfig

    cfg = DecoderConfig.tiny()
    chaos_mod.install(chaos_mod.Chaos.parse("slice_drop:slice=1,step=3"))
    with pytest.raises(MembershipViolation, match="min_slices=2"):
        experiment.lagom(
            _train_fn_factory(cfg), _elastic_conf(cfg, min_slices=2)
        )


@pytest.mark.slow
def test_worker_mode_shrink_completes(tmp_env):
    """Worker-per-slice mode: killing worker 1 of 2 under elastic=True is a
    membership drop, not a restart — the survivor reshapes (its own
    EXEC_CONFIG re-run) and the run completes with one worker's result and
    zero restart slots burned."""
    from maggy_tpu.models import DecoderConfig

    cfg = DecoderConfig.tiny()
    chaos_mod.install(chaos_mod.Chaos.parse("kill:worker=1,step=4"))
    result = experiment.lagom(
        _train_fn_factory(cfg),
        _elastic_conf(cfg, sharding="dp", num_executors=2, num_slices=None),
    )
    assert result["num_workers"] == 1
    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    counters = _exported_counters(exp_dir)
    assert counters.get("resilience.slice_drops", 0) == 1
    assert counters.get("resilience.dist_restarts", 0) == 0


def test_double_fault_restarts_serialized(tmp_env):
    """REGRESSION (double-fault window): worker A dies at step 4 and worker
    B at step 5 while A's relaunch is still in flight — both restarts are
    serialized behind the restart epoch, both partitions relaunch exactly
    once, and the run completes with both finals."""
    from maggy_tpu.models import DecoderConfig

    cfg = DecoderConfig.tiny()
    chaos_mod.install(
        chaos_mod.Chaos.parse("kill:worker=0,step=4;kill:worker=1,step=5")
    )
    result = experiment.lagom(
        _train_fn_factory(cfg),
        DistributedConfig(
            module=__import__("maggy_tpu.models", fromlist=["Decoder"]).Decoder(cfg),
            hparams={}, sharding="dp", data_plane="local", hb_interval=0.05,
            num_executors=2, max_restarts=2,
        ),
    )
    assert result["num_workers"] == 2
    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    counters = _exported_counters(exp_dir)
    assert counters.get("resilience.dist_restarts", 0) == 2


def test_duplicate_death_report_refunds_restart_slot(tmp_env):
    """Unit for the serialization itself: two _RESTART messages for ONE
    death (thread-death + liveness sweep racing) must yield one relaunch
    and one charged slot — the duplicate is detected by its stale epoch
    and refunded."""
    from maggy_tpu.core.driver.distributed import DistributedTrainingDriver

    cfg = DistributedConfig(hparams={}, sharding="dp", data_plane="local",
                            max_restarts=2)
    driver = DistributedTrainingDriver(cfg, "app", 0)
    respawned = []
    driver._respawn_executor = lambda pid: respawned.append(pid)
    driver._restarts = 2  # both deaths already charged on the dying threads

    msg = {"type": "_RESTART", "partition_id": 0, "error": "x", "restart": 1,
           "epoch": 0}
    driver._digest_restart(dict(msg))
    driver._digest_restart(dict(msg))  # duplicate report, same observed epoch
    assert respawned == [0]
    assert driver._restarts == 1  # the duplicate's slot was refunded

    # a genuinely later death of the SAME partition (observed after the
    # first restart landed) is a fresh restart, not a duplicate
    driver._digest_restart({**msg, "epoch": driver._restart_epoch})
    assert respawned == [0, 0]


# ------------------------------------------------------------- satellites


def test_checkpointer_warns_and_reshards_across_meshes(tmp_path):
    """Satellite: restore onto a mesh that differs from the one recorded in
    the sidecar meta warns loudly ("resharding"), counts
    resilience.ckpt_reshards, and still lands the exact values on the new
    layout — the world-size-independent restore the reshape path rides."""
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train.checkpoint import Checkpointer
    from maggy_tpu.train.data import synthetic_lm_batches
    from maggy_tpu.train.trainer import TrainContext

    cfg = DecoderConfig.tiny()
    batch = next(synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=0))

    ctx8 = TrainContext.create("fsdp")
    trainer8 = ctx8.trainer(Decoder(cfg), optax.adamw(1e-3))
    state8 = trainer8.make_state(jax.random.key(0), batch)
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(0, state8, meta=trainer8.checkpoint_meta())
    meta = ck.saved_meta(0)
    assert meta["n_processes"] == 1  # world-size provenance in the sidecar
    assert meta["num_devices"] == 8

    # live mesh = 4 devices: warn-and-reshard instead of silent mis-sharding
    ctx4 = TrainContext.create("fsdp", devices=jax.devices()[:4])
    trainer4 = ctx4.trainer(Decoder(cfg), optax.adamw(1e-3))
    template = trainer4.make_state(jax.random.key(1), batch)
    tel = telemetry.Telemetry(worker="t", role="test")
    with telemetry.current(tel):
        with pytest.warns(UserWarning, match="resharding every leaf"):
            restored = ck.restore(template)
    ck.close()
    assert tel.snapshot()["counters"]["resilience.ckpt_reshards"] == 1

    import flax.linen as nn

    def unwrap(leaf):
        return leaf.value if isinstance(leaf, nn.Partitioned) else leaf

    np.testing.assert_allclose(
        np.asarray(unwrap(restored.params["embedding"])),
        np.asarray(unwrap(state8.params["embedding"])),
    )
    assert len(unwrap(restored.params["embedding"]).sharding.device_set) == 4


def test_monitor_renders_membership_line():
    from maggy_tpu.monitor import render_status

    panel = render_status(
        {
            "name": "dist", "kind": "DistributedTrainingDriver",
            "state": "RUNNING", "app_id": "a", "run_id": 0,
            "num_executors": 1, "workers_done": 0, "restarts": 0,
            "membership_epoch": 1, "active_slices": [0], "num_slices": 2,
            "min_slices": 1, "membership_mode": "sim",
        }
    )
    assert "membership: epoch=1" in panel
    assert "slices 1/2" in panel


def test_exec_config_carries_membership_view():
    """The EXEC_CONFIG exchange is how a reshape reaches workers: the
    payload must carry the current epoch's view (and, in worker mode,
    size the training group to the active set)."""
    cfg = DistributedConfig(hparams={}, sharding="dp", data_plane="local",
                            elastic=True, num_slices=2, min_slices=1)
    from maggy_tpu.core.driver.distributed import DistributedTrainingDriver

    driver = DistributedTrainingDriver(cfg, "app", 0)
    driver.server = driver._make_server()  # not started; cluster_spec is empty
    out = driver._exec_config_callback({})
    assert out["membership"]["epoch"] == 0
    assert out["membership"]["active"] == [0, 1]
    assert out["membership"]["mode"] == "sim"

    driver.membership = driver.membership.drop(1)
    out = driver._exec_config_callback({})
    assert out["membership"]["epoch"] == 1
    assert out["membership"]["active"] == [0]


# ------------------------------------------------------------------ lint


def test_chaos_kind_lint_repo_clean_and_detects():
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_chaos_kinds", os.path.join(repo, "tools", "check_chaos_kinds.py")
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    # tier-1 wiring: the whole repo must be clean
    assert lint.main([]) == 0

    kinds = lint.load_kinds(repo)
    assert "slice_drop" in kinds and "slice_rejoin" in kinds

    bad = (
        'chaos.fire("slice_dorp", slice=1)\n'
        'import os\nos.environ["MAGGY_TPU_CHAOS"] = "kil:worker=1"\n'
        'monkeypatch.setenv("MAGGY_TPU_CHAOS", "hb_dropp:worker=0")\n'
        'env = {"MAGGY_TPU_CHAOS": "replica_kil:replica=1"}\n'
        'Chaos.parse("rpc_stal:verb=GET")\n'
        '"abc".count("a")\n'  # never flagged: not a chaos receiver
    )
    hits = lint.check_source(bad, "x.py", kinds)
    assert len(hits) == 5
    # declared kinds pass wherever they appear
    ok = 'chaos.fire("slice_drop", slice=1)\nChaos.parse("kill:worker=0")\n'
    assert lint.check_source(ok, "x.py", kinds) == []
