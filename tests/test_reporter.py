"""Reporter tests: broadcast validation, monotonic steps, early-stop exception,
log draining (reference reporter.py:77-142 semantics)."""

import numpy as np
import pytest

from maggy_tpu import Reporter, exceptions


def test_broadcast_and_drain():
    r = Reporter()
    r.broadcast(0.5)
    r.broadcast(0.6, step=5)
    r.log("hello")
    trial_id, metric, step, logs = r.get_data()
    assert metric == 0.6 and step == 5
    assert logs == ["hello"]
    # logs drained
    assert r.get_data()[3] == []


def test_broadcast_type_validation():
    r = Reporter()
    with pytest.raises(exceptions.BroadcastMetricTypeError):
        r.broadcast("not-a-number")
    with pytest.raises(exceptions.BroadcastMetricTypeError):
        r.broadcast(True)
    with pytest.raises(exceptions.BroadcastStepTypeError):
        r.broadcast(0.5, step=1.5)
    r.broadcast(np.float32(0.5))  # numpy scalars are fine
    r.broadcast(0.7, step=np.int64(10))


def test_monotonic_steps():
    r = Reporter()
    r.broadcast(0.5, step=3)
    with pytest.raises(exceptions.BroadcastStepValueError):
        r.broadcast(0.6, step=3)
    with pytest.raises(exceptions.BroadcastStepValueError):
        r.broadcast(0.6, step=1)
    r.broadcast(0.6, step=4)


def test_early_stop_raises_on_next_broadcast():
    r = Reporter()
    r.broadcast(0.1, step=0)
    r.early_stop()
    with pytest.raises(exceptions.EarlyStopException) as ei:
        r.broadcast(0.2, step=1)
    # the metric is preserved on the exception (reference trial_executor.py:194-196)
    assert ei.value.metric == 0.2


def test_reset_clears_state():
    r = Reporter()
    r.broadcast(0.5, step=9)
    r.early_stop()
    r.reset(trial_id="abc")
    assert r.trial_id == "abc"
    assert r.get_metric() is None
    r.broadcast(0.1, step=0)  # no EarlyStopException, steps restart


def test_log_file(tmp_path):
    p = tmp_path / "exec.log"
    r = Reporter(log_file=str(p))
    r.log("line1", verbose=False)
    r.log("line2", verbose=False)
    r.close()
    assert p.read_text().splitlines() == ["line1", "line2"]


def test_capture_prints_restores_builtin_print():
    """ADVICE r4: builtins.print must be restored once the LAST capture
    exits (no permanent process-wide swap), and the tee must wrap whatever
    print was installed when the first capture entered."""
    import builtins

    from maggy_tpu.reporter import Reporter, capture_prints

    before = builtins.print
    r1, r2 = Reporter(), Reporter()
    with capture_prints(r1):
        assert builtins.print is not before  # tee installed
        with capture_prints(r2):
            print("inner")
        assert builtins.print is not before  # r1 still active
        print("outer")
    assert builtins.print is before  # fully restored
    _, _, _, logs1 = r1.get_data()
    assert "outer" in logs1
    _, _, _, logs2 = r2.get_data()
    assert "inner" in logs2


def test_capture_prints_leaves_foreign_wrapper():
    """A hook installed ON TOP of the tee mid-capture is not clobbered at
    uninstall; the refcount drops our state without touching their chain."""
    import builtins

    from maggy_tpu.reporter import Reporter, capture_prints

    before = builtins.print
    r = Reporter()
    with capture_prints(r):
        inner = builtins.print

        def foreign(*a, **k):
            inner(*a, **k)

        builtins.print = foreign
    assert builtins.print is foreign  # their wrapper survives
    builtins.print = before  # cleanup


def test_remote_log_periodic_flush_and_truncation(monkeypatch):
    """ADVICE r4: a remote (object-store) log root publishes periodically —
    a crash loses at most one window — and the in-memory buffer is capped
    with an explicit truncation notice."""
    import uuid

    from maggy_tpu.core import env as env_mod
    from maggy_tpu.core.env.gcs import GcsEnv
    from maggy_tpu.reporter import Reporter

    env = GcsEnv(f"memory://rep-{uuid.uuid4().hex[:8]}")
    env_mod.set_instance(env)
    try:
        monkeypatch.setattr(Reporter, "_REMOTE_FLUSH_EVERY", 4)
        monkeypatch.setattr(Reporter, "_REMOTE_MAX_LINES", 10)
        path = env.root + "/executor_0.log"
        rep = Reporter(log_file=path, partition_id=0)
        for i in range(4):
            rep.log(f"line {i}")
        # periodic flush happened BEFORE close
        with env.open_file(path) as f:
            assert "line 3" in f.read()
        for i in range(4, 20):
            rep.log(f"line {i}")
        rep.close()
        with env.open_file(path) as f:
            final = f.read()
        assert "truncated" in final        # cap enforced, loudly
        assert "line 19" in final          # newest lines kept
        assert "line 0" not in final       # oldest dropped
    finally:
        env_mod.set_instance(None)


def test_remote_log_flush_continues_past_cap(monkeypatch):
    """Regression: the periodic flush must keep firing after the buffer cap
    pins len(history) — a monotonic counter, not the buffer length, drives
    the cadence."""
    import uuid

    from maggy_tpu.core import env as env_mod
    from maggy_tpu.core.env.gcs import GcsEnv
    from maggy_tpu.reporter import Reporter

    env = GcsEnv(f"memory://rep-{uuid.uuid4().hex[:8]}")
    env_mod.set_instance(env)
    try:
        monkeypatch.setattr(Reporter, "_REMOTE_FLUSH_EVERY", 4)
        monkeypatch.setattr(Reporter, "_REMOTE_MAX_LINES", 10)
        path = env.root + "/executor_0.log"
        rep = Reporter(log_file=path, partition_id=0)
        for i in range(28):  # far past the cap; NO close()
            rep.log(f"line {i}")
        with env.open_file(path) as f:
            content = f.read()
        assert "line 27" in content  # flushed after the cap, without close
        assert "truncated" in content
    finally:
        env_mod.set_instance(None)


def test_capture_prints_survives_stale_tee_wrapper():
    """Regression: a foreign wrapper that captured a stale tee reference
    must not cause infinite recursion when a NEW capture saves that wrapper
    as the 'original' print."""
    import builtins

    from maggy_tpu.reporter import Reporter, capture_prints

    before = builtins.print
    r1 = Reporter()
    with capture_prints(r1):
        stale_tee = builtins.print

        def foreign(*a, **k):
            stale_tee(*a, **k)  # closes over the tee

        builtins.print = foreign
    assert builtins.print is foreign
    r2 = Reporter()
    with capture_prints(r2):  # saves `foreign` (whose chain hits the tee)
        print("no recursion")  # would RecursionError without the guard
    _, _, _, logs = r2.get_data()
    assert "no recursion" in logs
    builtins.print = before  # cleanup
