"""Reporter tests: broadcast validation, monotonic steps, early-stop exception,
log draining (reference reporter.py:77-142 semantics)."""

import numpy as np
import pytest

from maggy_tpu import Reporter, exceptions


def test_broadcast_and_drain():
    r = Reporter()
    r.broadcast(0.5)
    r.broadcast(0.6, step=5)
    r.log("hello")
    trial_id, metric, step, logs = r.get_data()
    assert metric == 0.6 and step == 5
    assert logs == ["hello"]
    # logs drained
    assert r.get_data()[3] == []


def test_broadcast_type_validation():
    r = Reporter()
    with pytest.raises(exceptions.BroadcastMetricTypeError):
        r.broadcast("not-a-number")
    with pytest.raises(exceptions.BroadcastMetricTypeError):
        r.broadcast(True)
    with pytest.raises(exceptions.BroadcastStepTypeError):
        r.broadcast(0.5, step=1.5)
    r.broadcast(np.float32(0.5))  # numpy scalars are fine
    r.broadcast(0.7, step=np.int64(10))


def test_monotonic_steps():
    r = Reporter()
    r.broadcast(0.5, step=3)
    with pytest.raises(exceptions.BroadcastStepValueError):
        r.broadcast(0.6, step=3)
    with pytest.raises(exceptions.BroadcastStepValueError):
        r.broadcast(0.6, step=1)
    r.broadcast(0.6, step=4)


def test_early_stop_raises_on_next_broadcast():
    r = Reporter()
    r.broadcast(0.1, step=0)
    r.early_stop()
    with pytest.raises(exceptions.EarlyStopException) as ei:
        r.broadcast(0.2, step=1)
    # the metric is preserved on the exception (reference trial_executor.py:194-196)
    assert ei.value.metric == 0.2


def test_reset_clears_state():
    r = Reporter()
    r.broadcast(0.5, step=9)
    r.early_stop()
    r.reset(trial_id="abc")
    assert r.trial_id == "abc"
    assert r.get_metric() is None
    r.broadcast(0.1, step=0)  # no EarlyStopException, steps restart


def test_log_file(tmp_path):
    p = tmp_path / "exec.log"
    r = Reporter(log_file=str(p))
    r.log("line1", verbose=False)
    r.log("line2", verbose=False)
    r.close()
    assert p.read_text().splitlines() == ["line1", "line2"]
