"""tensorboard registry + callbacks: logdir inside trials, scalar logging,
ReporterCallback semantics."""

import json
import os

import pytest

from maggy_tpu import Reporter, Searchspace, experiment, exceptions
from maggy_tpu.callbacks import ReporterCallback
from maggy_tpu.config import HyperparameterOptConfig


def test_logdir_outside_trial_raises():
    from maggy_tpu import tensorboard as tb

    with pytest.raises(RuntimeError, match="inside a running trial"):
        tb.logdir()


def test_logdir_and_scalars_inside_lagom(tmp_env):
    from maggy_tpu import tensorboard as tb

    seen_dirs = []

    def train(hparams, reporter):
        d = tb.logdir()
        seen_dirs.append(d)
        tb.scalar("acc", hparams["x"], step=0)
        tb.scalar("acc", hparams["x"] + 0.1, step=1)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=3, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        num_executors=2, es_policy="none", hb_interval=0.05, seed=0,
    )
    experiment.lagom(train, cfg)
    assert len(set(seen_dirs)) == 3  # one registry entry per trial
    for d in seen_dirs:
        assert os.path.exists(os.path.join(d, ".hparams.json"))
        lines = open(os.path.join(d, "events.jsonl")).read().splitlines()
        assert len(lines) == 2
        assert json.loads(lines[1])["step"] == 1
    # unregistered after the experiment
    with pytest.raises(RuntimeError):
        tb.logdir()


def test_hparams_plugin_config_readable(tmp_path):
    """write_hparams_config emits an event the TB HParams plugin itself can
    parse — typed columns for every searchspace dimension (the reference's
    hp.hparams_config parity, tensorboard.py:47-102)."""
    tb_mod = pytest.importorskip("tensorboard")
    import glob

    from maggy_tpu import tensorboard as tb

    sp = Searchspace(
        x=("DOUBLE", [0.0, 1.0]),
        n=("INTEGER", [2, 8]),
        act=("CATEGORICAL", ["relu", "gelu"]),
    )
    assert tb.write_hparams_config(str(tmp_path), sp)

    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )
    from tensorboard.plugins.hparams import metadata, plugin_data_pb2

    exp = None
    for f in glob.glob(str(tmp_path / "events*")):
        for ev in EventFileLoader(f).Load():
            for v in ev.summary.value:
                if v.tag == metadata.EXPERIMENT_TAG:
                    pd = plugin_data_pb2.HParamsPluginData.FromString(
                        v.metadata.plugin_data.content
                    )
                    exp = pd.experiment
    assert exp is not None
    assert sorted(h.name for h in exp.hparam_infos) == ["act", "n", "x"]
    assert [m.name.tag for m in exp.metric_infos] == ["metric"]


def test_hparams_session_start_written(tmp_path):
    pytest.importorskip("tensorboard")
    import glob

    from maggy_tpu import tensorboard as tb
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )
    from tensorboard.plugins.hparams import metadata, plugin_data_pb2

    tb.write_hparams({"x": 0.25, "act": "gelu"}, logdir=str(tmp_path))
    got = None
    for f in glob.glob(str(tmp_path / "events*")):
        for ev in EventFileLoader(f).Load():
            for v in ev.summary.value:
                if v.tag == metadata.SESSION_START_INFO_TAG:
                    pd = plugin_data_pb2.HParamsPluginData.FromString(
                        v.metadata.plugin_data.content
                    )
                    got = pd.session_start_info.hparams
    assert got is not None
    assert got["x"].number_value == 0.25
    assert got["act"].string_value == "gelu"


def test_reporter_callback():
    r = Reporter()
    cb = ReporterCallback(r, metric="loss", negate=True, every=2)
    cb({"loss": 0.5}, step=0)
    cb({"loss": 0.4}, step=1)  # skipped (every=2)
    cb({"loss": 0.3}, step=2)
    _, metric, step, _ = r.get_data()
    assert metric == -0.3 and step == 2
    r.early_stop()
    with pytest.raises(exceptions.EarlyStopException):
        cb({"loss": 0.2}, step=4)
