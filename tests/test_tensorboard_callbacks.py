"""tensorboard registry + callbacks: logdir inside trials, scalar logging,
ReporterCallback semantics."""

import json
import os

import pytest

from maggy_tpu import Reporter, Searchspace, experiment, exceptions
from maggy_tpu.callbacks import ReporterCallback
from maggy_tpu.config import HyperparameterOptConfig


def test_logdir_outside_trial_raises():
    from maggy_tpu import tensorboard as tb

    with pytest.raises(RuntimeError, match="inside a running trial"):
        tb.logdir()


def test_logdir_and_scalars_inside_lagom(tmp_env):
    from maggy_tpu import tensorboard as tb

    seen_dirs = []

    def train(hparams, reporter):
        d = tb.logdir()
        seen_dirs.append(d)
        tb.scalar("acc", hparams["x"], step=0)
        tb.scalar("acc", hparams["x"] + 0.1, step=1)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=3, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        num_executors=2, es_policy="none", hb_interval=0.05, seed=0,
    )
    experiment.lagom(train, cfg)
    assert len(set(seen_dirs)) == 3  # one registry entry per trial
    for d in seen_dirs:
        assert os.path.exists(os.path.join(d, ".hparams.json"))
        lines = open(os.path.join(d, "events.jsonl")).read().splitlines()
        assert len(lines) == 2
        assert json.loads(lines[1])["step"] == 1
    # unregistered after the experiment
    with pytest.raises(RuntimeError):
        tb.logdir()


def test_reporter_callback():
    r = Reporter()
    cb = ReporterCallback(r, metric="loss", negate=True, every=2)
    cb({"loss": 0.5}, step=0)
    cb({"loss": 0.4}, step=1)  # skipped (every=2)
    cb({"loss": 0.3}, step=2)
    _, metric, step, _ = r.get_data()
    assert metric == -0.3 and step == 2
    r.early_stop()
    with pytest.raises(exceptions.EarlyStopException):
        cb({"loss": 0.2}, step=4)
