"""Native C++ batch loader: compilation, correctness vs numpy, epoch
semantics, shutdown."""

import numpy as np
import pytest

from maggy_tpu.train.native_loader import NativeBatchLoader, _native_lib


def data(n=100):
    rng = np.random.default_rng(0)
    return {
        "x": rng.normal(size=(n, 8)).astype(np.float32),
        "y": rng.integers(0, 10, size=(n,)).astype(np.int32),
    }


def test_native_lib_compiles():
    assert _native_lib() is not None, "g++ toolchain expected in this image"


def test_batches_are_correct_rows():
    d = data()
    loader = NativeBatchLoader(d, batch_size=16, seed=1)
    assert loader.using_native
    seen = []
    for _ in range(6):  # one epoch = 6 full batches of 16 (drop remainder)
        b = next(loader)
        assert b["x"].shape == (16, 8) and b["y"].shape == (16,)
        # every batch row must be an actual dataset row with matching label
        for i in range(16):
            matches = np.where((d["x"] == b["x"][i]).all(axis=1))[0]
            assert len(matches) == 1
            assert d["y"][matches[0]] == b["y"][i]
            seen.append(matches[0])
    # a full epoch covers 96 distinct rows (no duplicates within the epoch)
    assert len(set(seen)) == 96
    loader.close()


def test_seed_determinism():
    d = data()
    a = NativeBatchLoader(d, batch_size=10, seed=7)
    b = NativeBatchLoader(d, batch_size=10, seed=7)
    for _ in range(5):
        np.testing.assert_array_equal(next(a)["x"], next(b)["x"])
    a.close()
    b.close()
    c = NativeBatchLoader(d, batch_size=10, seed=8)
    assert not np.array_equal(next(c)["x"], next(NativeBatchLoader(d, batch_size=10, seed=7))["x"])
    c.close()


def test_no_shuffle_preserves_order():
    d = data(20)
    loader = NativeBatchLoader(d, batch_size=5, shuffle=False)
    b = next(loader)
    np.testing.assert_array_equal(b["x"], d["x"][:5])
    loader.close()


def test_single_epoch_stops():
    d = data(20)
    loader = NativeBatchLoader(d, batch_size=5, loop=False)
    batches = list(loader)
    assert len(batches) == 4
    loader.close()


def test_validation():
    with pytest.raises(ValueError):
        NativeBatchLoader({}, batch_size=4)
    with pytest.raises(ValueError):
        NativeBatchLoader({"x": np.zeros((4, 2)), "y": np.zeros(5)}, batch_size=2)
    with pytest.raises(ValueError):
        NativeBatchLoader({"x": np.zeros((4, 2))}, batch_size=8)


def test_unclosed_loader_is_collectable():
    """The producer thread must not pin an un-closed loader (and its dataset)."""
    import gc
    import threading
    import time
    import weakref

    loader = NativeBatchLoader(data(50), batch_size=10, seed=0)
    next(loader)
    ref = weakref.ref(loader)
    thread = loader._thread
    del loader
    gc.collect()
    assert ref() is None, "producer thread pinned the loader alive"
    deadline = time.time() + 5
    while thread.is_alive() and time.time() < deadline:
        time.sleep(0.05)
    assert not thread.is_alive(), "producer thread did not exit after collection"


@pytest.mark.slow
def test_feeds_trainer():
    """Loader output flows straight into the sharded trainer."""
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext

    cfg = DecoderConfig.tiny()
    rng = np.random.default_rng(0)
    start = rng.integers(0, cfg.vocab_size, (64, 1))
    toks = ((start + np.arange(32)[None, :] * 3) % cfg.vocab_size).astype(np.int32)
    loader = NativeBatchLoader({"tokens": toks}, batch_size=8, seed=0)
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    state = trainer.make_state(jax.random.key(0), next(loader))
    state, metrics = trainer.fit(state, loader, num_steps=10)
    assert np.isfinite(metrics["loss"])
    loader.close()
