"""Generation: a decoder trained on deterministic sequences must continue
them; greedy/temperature/eos semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate
from maggy_tpu.train import TrainContext
from maggy_tpu.train.data import synthetic_lm_batches

pytestmark = pytest.mark.slow  # module fixture trains a model (~17s setup)


@pytest.fixture(scope="module")
def trained():
    import jax as _jax

    cfg = DecoderConfig.tiny()
    # single-device mesh: this host has 1 physical core, and a 150-step loop
    # with per-step 8-device all-reduces can trip XLA's 40s collective
    # rendezvous timeout under load
    ctx = TrainContext.create("dp", devices=_jax.devices()[:1])
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(5e-3))
    # arithmetic sequences with step 1..6 mod 256 (synthetic_lm_batches)
    data = synthetic_lm_batches(cfg.vocab_size, 16, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    for _ in range(150):
        state, m = trainer.step(state, trainer.shard_batch(next(data)))
    assert float(m["loss"]) < 1.0
    return Decoder(cfg), {"params": state.params}


def test_greedy_continues_learned_pattern(trained):
    model, variables = trained
    # prompt: 0,3,6,...,21 (step 3); model should continue 24,27,...
    max_len = 16
    prompt = np.zeros((1, max_len), dtype=np.int32)
    prompt[0, :8] = np.arange(8) * 3
    out = generate(model, variables, jnp.asarray(prompt), jnp.asarray([8]))
    out = np.asarray(out[0])
    expected = (np.arange(max_len) * 3) % 256
    matches = (out[8:] == expected[8:]).mean()
    assert matches > 0.6, (out, expected)
    # prompt untouched
    np.testing.assert_array_equal(out[:8], prompt[0, :8])


def test_temperature_sampling_differs_by_rng(trained):
    model, variables = trained
    prompt = np.zeros((1, 12), dtype=np.int32)
    prompt[0, :4] = [0, 5, 10, 15]
    a = generate(model, variables, jnp.asarray(prompt), jnp.asarray([4]),
                 rng=jax.random.key(1), temperature=2.0)
    b = generate(model, variables, jnp.asarray(prompt), jnp.asarray([4]),
                 rng=jax.random.key(2), temperature=2.0)
    assert not np.array_equal(np.asarray(a), np.asarray(b))
    # greedy is deterministic
    g1 = generate(model, variables, jnp.asarray(prompt), jnp.asarray([4]))
    g2 = generate(model, variables, jnp.asarray(prompt), jnp.asarray([4]))
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_eos_propagates(trained):
    model, variables = trained
    prompt = np.zeros((2, 10), dtype=np.int32)
    prompt[:, :3] = [[0, 2, 4], [1, 3, 5]]
    plen = jnp.asarray([3, 3])
    # choose the eos id the model actually generates first, so EOS must fire
    free_run = np.asarray(generate(model, variables, jnp.asarray(prompt), plen))
    eos = int(free_run[0, 3])
    out = np.asarray(
        generate(model, variables, jnp.asarray(prompt), plen, eos_id=eos)
    )
    hits = np.where(out[0] == eos)[0]
    assert hits.size, (out, eos)
    assert (out[0, hits[0]:] == eos).all()  # everything after EOS stays EOS


def test_variable_prompt_lengths(trained):
    model, variables = trained
    prompt = np.zeros((2, 12), dtype=np.int32)
    prompt[0, :4] = np.arange(4) * 2
    prompt[1, :6] = np.arange(6) * 4
    out = generate(
        model, variables, jnp.asarray(prompt), jnp.asarray([4, 6])
    )
    out = np.asarray(out)
    np.testing.assert_array_equal(out[0, :4], prompt[0, :4])
    np.testing.assert_array_equal(out[1, :6], prompt[1, :6])
    assert (out[1, 6:] != 0).any()  # generation actually happened
