"""Streaming sharded dataset: disjoint per-process coverage (petastorm
RANK/WORLD_SIZE semantics), mmap-backed shard IO, batching across shard
boundaries, Parquet row-group ingestion, and end-to-end training from
on-disk shards."""

import numpy as np
import pytest

from maggy_tpu.train.sharded_dataset import (
    ParquetShardedDataset,
    ShardedDataset,
    ShardedStreamLoader,
    write_parquet,
    write_sharded,
)


def make_dataset(tmp_path, n=256, num_shards=8, seq=8):
    data = {
        "tokens": np.arange(n * seq, dtype=np.int32).reshape(n, seq),
        "sample_id": np.arange(n, dtype=np.int64),
    }
    write_sharded(str(tmp_path / "ds"), data, num_shards=num_shards)
    return ShardedDataset(str(tmp_path / "ds")), data


def drain_ids(loader, limit=10_000):
    ids = []
    for batch in loader:
        ids.extend(batch["sample_id"].tolist())
        if len(ids) > limit:
            raise AssertionError("loader did not stop")
    return ids


def test_layout_and_mmap(tmp_path):
    ds, data = make_dataset(tmp_path)
    assert ds.fields == ["sample_id", "tokens"]
    assert ds.num_shards == 8
    shard = ds.open_shard("tokens", 0)
    assert isinstance(shard, np.memmap)  # local shards never fully load


def test_disjoint_process_coverage(tmp_path):
    ds, data = make_dataset(tmp_path)
    seen = {}
    for pid in range(3):
        loader = ds.loader(
            batch_size=16, loop=False, process_index=pid, num_processes=3
        )
        seen[pid] = set(drain_ids(loader))
    # disjoint...
    assert not (seen[0] & seen[1]) and not (seen[0] & seen[2]) and not (seen[1] & seen[2])
    # ...and the union covers everything except at most the per-process batch tails
    union = seen[0] | seen[1] | seen[2]
    assert len(union) > 256 - 3 * 16
    # shard assignment is round-robin and balanced
    assert ds.my_shards(0, 3) == [0, 3, 6]
    assert ds.my_shards(2, 3) == [2, 5]


def test_batches_cross_shard_boundaries(tmp_path):
    # shard size 8 rows, batch 12: every batch spans shards; all full-sized
    ds, data = make_dataset(tmp_path, n=64, num_shards=8)
    loader = ds.loader(batch_size=12, loop=False, shuffle=True, seed=3)
    batches = list(loader)
    assert all(b["tokens"].shape == (12, 8) for b in batches)
    assert len(batches) == 64 // 12
    ids = [i for b in batches for i in b["sample_id"].tolist()]
    assert len(ids) == len(set(ids))  # no duplicates within the epoch


def make_parquet(tmp_path, n=128, seq=8, rows_per_group=16, num_files=2):
    pytest.importorskip("pyarrow")
    data = {
        "tokens": np.arange(n * seq, dtype=np.int32).reshape(n, seq),
        "sample_id": np.arange(n, dtype=np.int64),
    }
    write_parquet(
        str(tmp_path / "pq"), data,
        rows_per_group=rows_per_group, num_files=num_files,
    )
    return ParquetShardedDataset(str(tmp_path / "pq")), data


def test_parquet_row_group_units_and_columns(tmp_path):
    """Row groups are the shard unit (reference dataloader.py:100-144);
    fixed-size-list columns come back as 2-D rows, scalars as 1-D."""
    ds, data = make_parquet(tmp_path)  # 128 rows, 16/group, 2 files
    assert ds.num_shards == 8
    assert sorted(ds.fields) == ["sample_id", "tokens"]
    g0 = ds.open_shard("tokens", 0)
    assert g0.shape == (16, 8) and g0.dtype == np.int32
    np.testing.assert_array_equal(g0, data["tokens"][:16])
    sid = ds.open_shard("sample_id", 3)
    assert sid.shape == (16,)
    np.testing.assert_array_equal(sid, data["sample_id"][48:64])


def test_parquet_disjoint_process_coverage(tmp_path):
    """Shuffled, two processes, one epoch: disjoint ids whose union is the
    exact full dataset (rows_per_group and batch chosen to leave no tail)."""
    ds, data = make_parquet(tmp_path)  # 8 groups x 16 rows
    seen = {}
    for pid in range(2):
        loader = ds.loader(
            batch_size=16, loop=False, shuffle=True, seed=7,
            process_index=pid, num_processes=2,
        )
        seen[pid] = set(drain_ids(loader))
    assert not (seen[0] & seen[1])
    assert seen[0] | seen[1] == set(range(128))
    assert ds.my_shards(0, 2) == [0, 2, 4, 6]


def test_parquet_train_end_to_end(tmp_path):
    """A Decoder trains straight off a Parquet dir through the C++ gather."""
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext

    pytest.importorskip("pyarrow")
    cfg = DecoderConfig.tiny()
    rng = np.random.default_rng(0)
    base = rng.integers(0, cfg.vocab_size, (64, 1), dtype=np.int32)
    tokens = np.tile(base, (1, 16))
    write_parquet(str(tmp_path / "lm"), {"tokens": tokens}, rows_per_group=8)

    ds = ParquetShardedDataset(str(tmp_path / "lm"))
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-2))
    loader = ds.loader(batch_size=8, ctx=ctx)
    state = trainer.make_state(jax.random.key(0), next(loader))
    losses = []
    for _ in range(6):
        state, m = trainer.step(state, trainer.shard_batch(next(loader), local=True))
        losses.append(float(m["loss"]))
    loader.close()
    assert losses[-1] < losses[0]


def test_parquet_validation(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    with pytest.raises(ValueError, match="No .parquet files"):
        ParquetShardedDataset(str(tmp_path))
    ds, _ = make_parquet(tmp_path)
    with pytest.raises(ValueError, match="not in parquet schema"):
        ParquetShardedDataset(str(tmp_path / "pq"), columns=["nope"])
    # ragged list columns are rejected with guidance
    ragged = pa.table({"x": pa.array([[1, 2], [3]])})
    pq.write_table(ragged, str(tmp_path / "ragged.parquet"))
    ds2 = ParquetShardedDataset(str(tmp_path / "ragged.parquet"))
    with pytest.raises(ValueError, match="Ragged"):
        ds2.open_shard("x", 0)
    # more files than rows would write empty part files -> spinning shards
    with pytest.raises(ValueError, match="chunk count"):
        write_parquet(
            str(tmp_path / "tiny"),
            {"x": np.zeros(3, np.int32)},
            rows_per_group=1,
            num_files=10,
        )
    # cross-file schema drift must fail at construction, not mid-training
    drift = tmp_path / "drift"
    drift.mkdir()
    pq.write_table(
        pa.table({"tokens": pa.FixedSizeListArray.from_arrays(
            pa.array(np.zeros(16, np.int32)), 8)}),
        str(drift / "part-00000.parquet"),
    )
    pq.write_table(
        pa.table({"tokens": pa.FixedSizeListArray.from_arrays(
            pa.array(np.zeros(8, np.int32)), 4)}),
        str(drift / "part-00001.parquet"),
    )
    with pytest.raises(ValueError, match="type mismatch"):
        ParquetShardedDataset(str(drift))


def test_shuffle_determinism_and_loop(tmp_path):
    ds, _ = make_dataset(tmp_path, n=64, num_shards=4)
    a = drain_ids(ds.loader(batch_size=16, loop=False, seed=5))
    b = drain_ids(ds.loader(batch_size=16, loop=False, seed=5))
    c = drain_ids(ds.loader(batch_size=16, loop=False, seed=6))
    assert a == b
    assert a != c
    looping = ds.loader(batch_size=16, loop=True, seed=5)
    got = [next(looping) for _ in range(64 // 16 + 2)]  # runs past one epoch
    looping.close()
    assert len(got) == 6


def test_producer_error_propagates(tmp_path):
    """A shard that vanishes mid-run surfaces as RuntimeError at next(), not
    a silent hang on the queue."""
    import os

    ds, _ = make_dataset(tmp_path, n=64, num_shards=4)
    for f in ("tokens", "sample_id"):
        os.remove(tmp_path / "ds" / f / "shard-00002.npy")
    loader = ds.loader(batch_size=8, loop=False, shuffle=False)
    with pytest.raises(RuntimeError, match="producer failed"):
        drain_ids(loader)


def test_mismatched_shard_names_rejected(tmp_path):
    import os

    ds_dir = tmp_path / "ds"
    make_dataset(tmp_path, n=64, num_shards=4)
    os.rename(
        ds_dir / "tokens" / "shard-00003.npy", ds_dir / "tokens" / "shard-00009.npy"
    )
    with pytest.raises(ValueError, match="Inconsistent shard files"):
        ShardedDataset(str(ds_dir))


def test_validation_errors(tmp_path):
    ds, _ = make_dataset(tmp_path)
    with pytest.raises(ValueError, match="processes but only"):
        ds.my_shards(0, 100)
    with pytest.raises(ValueError, match="process_index"):
        ds.my_shards(5, 3)
    with pytest.raises(ValueError, match="equal leading dims"):
        write_sharded(str(tmp_path / "bad"), {"a": np.zeros(4), "b": np.zeros(5)}, 2)


@pytest.mark.slow
def test_train_from_disk_shards(tmp_path):
    """End-to-end: a decoder trains from on-disk shards it never fully loads."""
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext

    cfg = DecoderConfig.tiny()
    # learnable stream: each row repeats one token, so loss drops fast
    tokens = np.tile(
        (np.arange(512, dtype=np.int32) % cfg.vocab_size)[:, None], (1, 32)
    )
    write_sharded(str(tmp_path / "lm"), {"tokens": tokens}, num_shards=16)
    ds = ShardedDataset(str(tmp_path / "lm"))

    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-2))
    loader = ds.loader(batch_size=8, ctx=ctx)
    state = trainer.make_state(jax.random.key(0), next(loader))
    first = last = None
    for _ in range(40):
        # loader batches are process-local: local=True skips global slicing
        state, m = trainer.step(state, trainer.shard_batch(next(loader), local=True))
        first = first if first is not None else float(m["loss"])
        last = float(m["loss"])
    loader.close()
    assert np.isfinite(last) and last < first
