"""Host-DRAM KV page tier + prefix-affinity routing (ISSUE 18): pool
LRU/capacity semantics, fleet prefix-map bounds, spill -> swap-in byte
parity through the engine, alias-aware allocator spill ranking, the
``host_pool_slow`` chaos seam, and the router's affinity pick."""

import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.serve import Engine, Request, SamplingParams
from maggy_tpu.serve.paging import BlockAllocator
from maggy_tpu.serve.tier import FleetPrefixMap, HostPagePool, TieringPolicy

CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = Decoder(CFG)
    return unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )


def _blocks(n, fill, leaves=("k", "v")):
    return {ks: np.full((n, 2, 3), fill, np.float32) for ks in leaves}


# ----------------------------------------------------------------- host pool


def test_host_pool_roundtrip_and_lru_eviction():
    pool = HostPagePool(capacity_pages=4)
    assert pool.put("a", _blocks(2, 1.0), {"kind": "resume"})
    assert pool.put("b", _blocks(2, 2.0), {"kind": "prefix"})
    # roundtrip is byte-exact and returns copies
    blocks, meta = pool.get("a")
    np.testing.assert_array_equal(blocks["k"], np.full((2, 2, 3), 1.0))
    assert meta == {"kind": "resume"}
    blocks["k"][:] = 99.0  # a caller scribbling on its copy
    np.testing.assert_array_equal(pool.get("a")[0]["k"], np.full((2, 2, 3), 1.0))
    # the get refreshed "a": a put that needs room evicts "b" (LRU), not "a"
    assert pool.put("c", _blocks(2, 3.0), {})
    assert pool.has("a") and pool.has("c") and not pool.has("b")
    st = pool.stats()
    assert st["resident_packs"] == 2 and st["host_evictions"] == 1
    assert st["host_pages_used"] == 4 and st["host_pages_free"] == 0
    assert pool.get("b") is None and pool.stats()["misses"] == 1
    assert sorted(pool.keys()) == ["a", "c"]


def test_host_pool_refuses_oversized_and_shrinks():
    pool = HostPagePool(capacity_pages=3)
    assert not pool.put("big", _blocks(4, 1.0), {})  # > whole budget
    assert pool.put("a", _blocks(2, 1.0), {})
    assert pool.put("b", _blocks(1, 2.0), {})
    # same-key put replaces (old pages recycled, no eviction needed)
    assert pool.put("a", _blocks(2, 5.0), {})
    assert pool.has("b")
    np.testing.assert_array_equal(pool.get("a")[0]["v"], np.full((2, 2, 3), 5.0))
    # autopilot shrink evicts immediately, LRU first ("b" is older now)
    pool.set_capacity(2)
    assert pool.has("a") and not pool.has("b")
    pool.set_capacity(0)
    assert pool.stats()["resident_packs"] == 0
    pool.drop("a")  # drop on a missing key is a no-op


# --------------------------------------------------------------- prefix map


def test_prefix_map_update_replaces_and_forgets():
    m = FleetPrefixMap()
    m.update(0, ["d1", "d2"])
    m.update(1, ["d2"])
    assert m.replicas_for("d1") == frozenset({0})
    assert m.replicas_for("d2") == frozenset({0, 1})
    # a fresh snapshot REPLACES the replica's contribution
    m.update(0, ["d3"])
    assert m.replicas_for("d1") == frozenset()
    assert m.replicas_for("d2") == frozenset({1})
    m.forget_replica(1)
    assert m.replicas_for("d2") == frozenset()
    snap = m.snapshot()
    assert snap["entries"] == 1 and snap["replicas"] == {"0": 1}


def test_prefix_map_bounded_lru():
    m = FleetPrefixMap(max_entries=2)
    m.update(0, ["a"])
    m.update(1, ["b"])
    m.update(2, ["c"])  # trims "a", the least recently reported
    assert m.replicas_for("a") == frozenset()
    assert m.replicas_for("b") == frozenset({1})
    assert m.replicas_for("c") == frozenset({2})
    assert m.snapshot()["entries"] == 2


def test_tiering_policy_verdict_and_ledger():
    pol = TieringPolicy(low_water_pct=0.1)
    assert not pol.should_spill(None)  # no ledger yet -> never spill
    assert not pol.should_spill(0.1)  # at the mark is still fine
    assert pol.should_spill(0.09)
    pol.note_spill(3, pressure=True)
    pol.note_spill(2, prefix=True)
    pol.note_fill(2, prefix=True)
    st = pol.stats()
    assert st["spills"] == 2 and st["spilled_pages"] == 5
    assert st["prefix_spills"] == 1 and st["pressure_spills"] == 1
    assert st["fills"] == 1 and st["prefix_fills"] == 1


# ------------------------------------------------- alias-aware spill ranking


def test_allocator_coldest_and_fragmentation_exclude_shared():
    """Satellite regression: a prefix-aliased page (refcount >= 2) must
    never rank spill-eligible, and the pinned/reclaimable split tiles the
    referenced set — under churned share/release, not just fresh allocs."""
    alloc = BlockAllocator(num_pages=8, page_size=16)
    mine = alloc.alloc(3)
    theirs = alloc.alloc(2)
    alloc.share(theirs)  # aliased by a second request now
    alloc.touch(mine, gen=5)
    cold = alloc.coldest()
    assert set(cold) == set(mine), "shared pages leaked into spill ranking"
    assert set(alloc.coldest(include_shared=True)) == set(mine) | set(theirs)
    frag = alloc.fragmentation()
    assert frag["pages_pinned_shared"] == 2
    assert frag["pages_reclaimable"] == 3
    alloc.check_invariants()
    # one sharer lets go: the pages become reclaimable and spill-eligible
    alloc.release(theirs)
    frag = alloc.fragmentation()
    assert frag["pages_pinned_shared"] == 0
    assert frag["pages_reclaimable"] == 5
    assert set(alloc.coldest()) == set(mine) | set(theirs)
    alloc.check_invariants()


# -------------------------------------------------------------- chaos seam


def test_chaos_host_pool_slow_delays_fill():
    pool = HostPagePool(capacity_pages=2)
    pool.put("a", _blocks(1, 1.0), {})
    chaos_mod.install(chaos_mod.Chaos.parse("host_pool_slow:ms=80,times=1"))
    try:
        t0 = time.perf_counter()
        assert pool.get("a") is not None
        slow = time.perf_counter() - t0
        assert slow >= 0.08, f"chaos delay not injected ({slow * 1e3:.1f}ms)"
        t0 = time.perf_counter()
        assert pool.get("a") is not None  # budget spent: back to fast
        assert time.perf_counter() - t0 < 0.08
    finally:
        chaos_mod.reset()


# --------------------------------------------------------- router affinity


def _router_with_two_replicas(affinity_ms):
    from maggy_tpu.serve.fleet import Replica, RouterConfig
    from maggy_tpu.serve.fleet.router import Router

    replicas = [
        Replica(i, types.SimpleNamespace(role="any"), secret="s")
        for i in range(2)
    ]
    return Router(
        replicas,
        config=RouterConfig(affinity_weight_ms=affinity_ms),
    ), replicas


def test_pick_replica_prefers_prefix_holder():
    router, replicas = _router_with_two_replicas(affinity_ms=50.0)
    # identical load on both replicas: without affinity the round-robin
    # cursor alternates; with a digest the holder wins every time
    router._stats_cache = {0: {}, 1: {}}
    router.prefix_map.update(1, ["deadbeef"])
    for _ in range(4):
        best, proj = router._pick_replica(
            replicas, digest="deadbeef", affinity_ms=50.0
        )
        assert best.index == 1
    assert router.counters["affinity_hits"] == 4
    # a genuinely overloaded holder still loses: the bonus is bounded
    router._stats_cache = {
        0: {},
        1: {"queue_depth": 50, "num_slots": 1, "active_slots": 1},
    }
    best, _ = router._pick_replica(
        replicas, digest="deadbeef", affinity_ms=50.0
    )
    assert best.index == 0
    assert router.counters["affinity_misses"] == 1


def test_pick_replica_affinity_blind_without_weight():
    router, replicas = _router_with_two_replicas(affinity_ms=0.0)
    router._stats_cache = {0: {}, 1: {}}
    router.prefix_map.update(1, ["deadbeef"])
    picks = {
        router._pick_replica(replicas, digest="deadbeef", affinity_ms=0.0)[0].index
        for _ in range(4)
    }
    assert picks == {0, 1}, "zero weight must leave round-robin untouched"
    assert router.counters["affinity_hits"] == 0
    assert router.counters["affinity_misses"] == 0


# ------------------------------------------------- engine spill / swap-in


def _drive(eng, slot, toks, n):
    while len(toks) < n:
        out = eng.step()
        if slot in out.tokens:
            toks.append(out.tokens[slot])
        if not eng.slots.active_slots():
            break
    return toks


def test_spill_swap_in_byte_parity(params):
    """Acceptance: a stream preempted through spill_stream and re-admitted
    from its host pack continues byte-identically with an uninterrupted
    run — sampled (seeded), not just greedy — and a later same-prefix
    admission fills from the released prefix pack at suffix-only cost."""
    prompt = list(range(3, 40))  # 37 tokens, spans >2 pages
    sp = SamplingParams(max_new=10, temperature=0.7, seed=5)

    free_eng = Engine(CFG, params, num_slots=2, num_pages=24, tier=False)
    assert free_eng.tier is None
    r = Request(id="a", prompt=list(prompt), params=sp)
    slot, first = free_eng.admit(r)
    free = _drive(free_eng, slot, [first], sp.max_new)[: sp.max_new]

    eng = Engine(CFG, params, num_slots=2, num_pages=24, tier=True)
    r = Request(id="a", prompt=list(prompt), params=sp)
    slot, first = eng.admit(r)
    # the scheduler owns the drained-token history: each drained token is
    # appended to the live request, which is what spill_stream captures
    r.tokens.append(first)
    for _ in range(4):
        out = eng.step()
        if slot in out.tokens:
            r.tokens.append(out.tokens[slot])
    out = eng.flush()
    if slot in out.tokens:
        r.tokens.append(out.tokens[slot])
    toks = list(r.tokens)
    # preempt with spill: the scheduler's order — capture, then release
    assert eng.spill_stream(slot)
    eng.release(slot)
    resumed = Request(id="a", prompt=list(prompt), params=sp, tokens=list(toks))
    slot2, first2 = eng.admit(resumed)
    toks2 = _drive(eng, slot2, list(toks) + [first2], sp.max_new)
    assert toks2[: sp.max_new] == free, "swap-in diverged from the free run"
    ts = eng.tier_stats
    assert ts["fills"] == 1 and ts["prefix_fills"] == 0, ts
    # swap-in cost: only the undrained suffix was recomputed
    assert eng.prefill_tokens < 2 * len(prompt)

    # release leaves a prefix pack; a same-prefix admission fills from it
    eng.release(slot2)
    assert eng.tier_stats["prefix_spills"] >= 1
    pt = eng.prefill_tokens
    probe = Request(
        id="c",
        prompt=list(prompt) + [41, 42],
        params=SamplingParams(max_new=3, temperature=0.0, seed=9),
    )
    slot3, f3 = eng.admit(probe)
    assert eng.tier_stats["prefix_fills"] == 1
    suffix_cost = eng.prefill_tokens - pt
    assert suffix_cost < len(prompt), suffix_cost
    # and the fill is correct: a tier-less engine agrees on the token
    r4 = Request(
        id="c",
        prompt=list(prompt) + [41, 42],
        params=SamplingParams(max_new=3, temperature=0.0, seed=9),
    )
    _, f4 = free_eng.admit(r4)
    assert int(f3) == int(f4), (int(f3), int(f4))


def test_stale_resume_pack_dropped(params):
    """A resume pack whose drained-token history no longer matches the
    re-admitted request must be dropped, not served: the admit falls back
    (here to the prefix path or plain prefill) and stays correct."""
    prompt = list(range(3, 30))
    sp = SamplingParams(max_new=6, temperature=0.0, seed=1)
    eng = Engine(CFG, params, num_slots=2, num_pages=24, tier=True)
    r = Request(id="a", prompt=list(prompt), params=sp)
    slot, first = eng.admit(r)
    r.tokens.append(first)
    for _ in range(2):
        out = eng.step()
        if slot in out.tokens:
            r.tokens.append(out.tokens[slot])
    eng.flush()
    assert eng.spill_stream(slot)
    eng.release(slot)
    # re-admit with a DIFFERENT drained history than the pack captured
    resumed = Request(
        id="a", prompt=list(prompt), params=sp, tokens=[999, 998]
    )
    eng.admit(resumed)
    assert not eng.tier.has(f"rid:{resumed.id}"), "stale pack must be dropped"
    ts = eng.tier_stats
    # any fill here came from the prefix fallback, never the stale pack
    assert ts["fills"] == ts["prefix_fills"], ts


def test_tier_env_gate_and_knob_seams(params, monkeypatch):
    monkeypatch.setenv("MAGGY_TPU_SERVE_TIER", "0")
    eng = Engine(CFG, params, num_slots=2, num_pages=24)
    assert eng.tier is None and eng.tier_stats == {"enabled": False}
    monkeypatch.delenv("MAGGY_TPU_SERVE_TIER")
    eng = Engine(CFG, params, num_slots=2, num_pages=24)
    assert eng.tier is not None
    assert eng.tier_stats["host_pages_total"] == 2 * eng.num_pages
    eng.set_tier_host_pages(7)
    assert eng.tier_stats["host_pages_total"] == 7
    eng.set_tier_low_water(0.2)
    assert eng.tier_policy.low_water_pct == 0.2
    # dense engines never attach a tier
    dense = Engine(CFG, params, num_slots=2, paged=False)
    assert dense.tier is None
