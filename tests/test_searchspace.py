"""Searchspace tests — mirrors the reference suite's construction/validation
coverage (maggy/tests/test_searchspace.py:24-77) and adds round-trip property
tests for the unit-cube transform."""

import pytest

from maggy_tpu import Searchspace


def make_space():
    return Searchspace(
        lr=("DOUBLE", [1e-4, 1e-1]),
        layers=("INTEGER", [1, 8]),
        batch=("DISCRETE", [32, 64, 128]),
        act=("CATEGORICAL", ["relu", "gelu", "silu"]),
    )


def test_construction_and_accessors():
    sp = make_space()
    assert len(sp) == 4
    assert sp.names() == {
        "lr": "DOUBLE",
        "layers": "INTEGER",
        "batch": "DISCRETE",
        "act": "CATEGORICAL",
    }
    assert sp.lr == [1e-4, 1e-1]
    assert sp.get("layers") == [1, 8]
    assert "batch" in sp
    # lower-case type strings are accepted
    sp2 = Searchspace(x=("double", [0.0, 1.0]))
    assert sp2.get_type("x") == Searchspace.DOUBLE


def test_to_dict_roundtrip():
    sp = make_space()
    sp2 = Searchspace(**sp.to_dict())
    assert sp2.to_dict() == sp.to_dict()
    sp3 = Searchspace.from_json(sp.json())
    assert sp3.to_dict() == sp.to_dict()


@pytest.mark.parametrize(
    "value",
    [
        ("DOUBLE", [1.0]),  # wrong arity
        ("DOUBLE", [1.0, 1.0]),  # lo == hi
        ("DOUBLE", [2.0, 1.0]),  # lo > hi
        ("DOUBLE", ["a", 1.0]),  # non-numeric
        ("INTEGER", [1.5, 2]),  # non-int bounds
        ("DISCRETE", []),  # empty
        ("DISCRETE", [1, 1]),  # duplicates
        ("CATEGORICAL", ["a", "a"]),  # duplicates
        ("WRONG", [1, 2]),  # bad type
        ("DOUBLE",),  # bad shape
    ],
)
def test_add_validation_errors(value):
    sp = Searchspace()
    with pytest.raises(ValueError):
        sp.add("x", value)


def test_reserved_and_duplicate_names():
    sp = Searchspace(x=("DOUBLE", [0.0, 1.0]))
    with pytest.raises(ValueError):
        sp.add("x", ("DOUBLE", [0.0, 1.0]))
    with pytest.raises(ValueError):
        sp.add("add", ("DOUBLE", [0.0, 1.0]))
    with pytest.raises(ValueError):
        sp.add("_private", ("DOUBLE", [0.0, 1.0]))


def test_sampling_in_bounds():
    sp = make_space()
    for params in sp.get_random_parameter_values(100, seed=7):
        assert sp.contains(params)
    # determinism with a seed
    a = sp.get_random_parameter_values(10, seed=3)
    b = sp.get_random_parameter_values(10, seed=3)
    assert a == b


def test_transform_roundtrip_exact():
    sp = make_space()
    for params in sp.get_random_parameter_values(200, seed=11):
        vec = sp.transform(params)
        assert vec.shape == (4,)
        assert (vec >= 0).all() and (vec <= 1).all()
        back = sp.inverse_transform(vec)
        assert back["layers"] == params["layers"]
        assert back["batch"] == params["batch"]
        assert back["act"] == params["act"]
        assert abs(back["lr"] - params["lr"]) < 1e-12


def test_inverse_transform_any_point_valid():
    import numpy as np

    sp = make_space()
    rng = np.random.default_rng(0)
    for _ in range(100):
        params = sp.inverse_transform(rng.random(4))
        assert sp.contains(params)
    # boundary values decode to valid configs too
    assert sp.contains(sp.inverse_transform(np.zeros(4)))
    assert sp.contains(sp.inverse_transform(np.ones(4)))


def test_dict_list_converters():
    sp = make_space()
    params = sp.sample()
    values = sp.dict_to_list(params)
    assert sp.list_to_dict(values) == params
