"""Attention op correctness: blockwise == reference, ring == reference on a
seq-sharded mesh, Ulysses == reference, flash kernel (interpret mode) ==
reference, and gradients flow through blockwise/ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models.transformer import default_attention
from maggy_tpu.ops.attention import blockwise_attention
from maggy_tpu.ops.flash import flash_attention
from maggy_tpu.parallel.mesh import make_mesh
from maggy_tpu.parallel.ringattention import ring_attention
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.parallel.ulysses import ulysses_attention


def qkv(b=2, s=64, h=4, kh=None, d=16, seed=0, dtype=jnp.float32):
    kh = kh or h
    rng = jax.random.key(seed)
    k1, k2, k3 = jax.random.split(rng, 3)
    return (
        jax.random.normal(k1, (b, s, h, d), dtype),
        jax.random.normal(k2, (b, s, kh, d), dtype),
        jax.random.normal(k3, (b, s, kh, d), dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_k", [16, 64, 50])
def test_blockwise_matches_reference(causal, block_k):
    q, k, v = qkv()
    ref = default_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_blockwise_gqa():
    q, k, v = qkv(h=8, kh=2)
    ref = default_attention(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_blockwise_grads_match():
    q, k, v = qkv(s=32)

    def loss_ref(q, k, v):
        return default_attention(q, k, v, causal=True).sum()

    def loss_blk(q, k, v):
        return blockwise_attention(q, k, v, causal=True, block_k=8).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(causal):
    mesh = make_mesh(ShardingSpec(sp=4, dp=2))
    q, k, v = qkv(b=2, s=64, h=4, d=16)
    ref = default_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=causal)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.slow
def test_ring_gqa_and_grads():
    mesh = make_mesh(ShardingSpec(sp=4, dp=2))
    q, k, v = qkv(b=2, s=32, h=8, kh=4, d=8)
    ref = default_attention(q, k, v, causal=True)
    with mesh:
        out = ring_attention(q, k, v, mesh=mesh, causal=True)
        g = jax.grad(
            lambda q: ring_attention(q, k, v, mesh=mesh, causal=True).sum()
        )(q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g_ref = jax.grad(lambda q: default_attention(q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(causal):
    mesh = make_mesh(ShardingSpec(sp=4, dp=2))
    q, k, v = qkv(b=2, s=64, h=8, d=16)
    ref = default_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh, causal=causal)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_head_divisibility():
    mesh = make_mesh(ShardingSpec(sp=8))
    q, k, v = qkv(h=4)  # 4 heads, 8 shards
    with pytest.raises(ValueError, match="divide the head count"):
        with mesh:
            ulysses_attention(q, k, v, mesh=mesh)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    # d must be a multiple of 128 lanes for the kernel path
    q, k, v = qkv(b=1, s=256, h=2, d=128)
    ref = default_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_flash_auto_blocks():
    """Default tiles: measured-fastest MXU sizes that divide the sequence."""
    from maggy_tpu.ops.flash import _auto_blocks

    assert _auto_blocks(1024, 1024) == (512, 512)
    assert _auto_blocks(8192, 8192) == (512, 1024)  # wide k tiles at long S
    assert _auto_blocks(1280, 1280) == (256, 256)  # halved until they divide
    assert _auto_blocks(128, 128) == (128, 128)


def test_flash_default_blocks_match_reference():
    """The auto-tuned default tiling (block_q/k=None) stays correct, fwd+bwd."""
    q, k, v = qkv(b=1, s=256, h=2, d=128)
    ref = default_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)

    g_ref = jax.grad(lambda q: (default_attention(q, k, v, causal=True) ** 2).sum())(q)
    g_fl = jax.grad(lambda q: (flash_attention(q, k, v, causal=True) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g_fl), np.asarray(g_ref), atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_backward_matches_reference(causal):
    """The Pallas backward kernels (dQ + dK/dV split) against jax.grad through
    the XLA dense path — the round-1 gap (forward-only kernel)."""
    q, k, v = qkv(b=1, s=256, h=2, d=128)

    def loss_ref(q, k, v):
        return (default_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=causal, block_q=128, block_k=128) ** 2
        ).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-2, rtol=2e-2)


def test_flash_independent_bwd_tiles():
    """bwd_block_q/bwd_block_k different from the forward's tiles: the LSE
    residual re-chunks and gradients stay exact (the silicon tuning knob,
    tools/tune_flash.py)."""
    q, k, v = qkv(b=1, s=256, h=2, d=128)

    def loss(bq, bk, bbq, bbk):
        def f(q, k, v):
            o = flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk,
                bwd_block_q=bbq, bwd_block_k=bbk,
            )
            return (o ** 2).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    base = loss(128, 128, 128, 128)
    mixed = loss(128, 128, 64, 32)   # smaller bwd tiles
    wider = loss(64, 64, 128, 256)   # larger bwd tiles than fwd
    for a, b in zip(base, mixed):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
    for a, b in zip(base, wider):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_backward_gqa_bf16():
    """GQA grads sum back over the head group; bf16 within bf16 tolerance."""
    q, k, v = qkv(b=2, s=128, h=4, kh=2, d=128, dtype=jnp.bfloat16)

    def loss_ref(q, k, v):
        return default_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
            .astype(jnp.float32)
            .sum()
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-1, rtol=1e-1
        )


def test_flash_under_remat():
    """flash_attention composes with jax.checkpoint (the training config)."""
    q, k, v = qkv(b=1, s=128, h=2, d=128)

    def loss(q, k, v):
        f = jax.checkpoint(
            lambda q, k, v: flash_attention(q, k, v, causal=True),
            policy=jax.checkpoint_policies.nothing_saveable,
        )
        return (f(q, k, v) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (default_attention(q, k, v, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-2, rtol=2e-2)


def test_sharded_flash_matches_reference():
    """The shard_map wrap that auto_attention uses on multi-device meshes —
    a pallas_call has no GSPMD partitioning rule, so this is the only legal
    multi-chip route; exercised here on the CPU mesh in interpret mode."""
    from maggy_tpu.ops.flash import sharded_flash_attention

    mesh = make_mesh(ShardingSpec(dp=2, fsdp=2, tp=2))
    q, k, v = qkv(b=4, s=128, h=2, d=128)
    ref = default_attention(q, k, v, causal=True)
    with mesh:
        out = jax.jit(
            lambda q, k, v: sharded_flash_attention(
                q, k, v, mesh=mesh, causal=True, interpret=True
            )
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)
    # gradients flow through the shard_map'd custom VJP
    with mesh:
        g = jax.jit(
            jax.grad(
                lambda q: sharded_flash_attention(
                    q, k, v, mesh=mesh, causal=True, interpret=True
                ).sum()
            )
        )(q)
    g_ref = jax.grad(lambda q: default_attention(q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=2e-2, rtol=2e-2)


def test_sharded_flash_refuses_incompatible_mesh():
    from maggy_tpu.ops.flash import sharded_flash_attention

    q, k, v = qkv(b=2, s=128, h=4, d=128)
    sp_mesh = make_mesh(ShardingSpec(sp=4, dp=2))
    assert sharded_flash_attention(q, k, v, mesh=sp_mesh) is None  # sp in use
    dp_mesh = make_mesh(ShardingSpec(dp=8))
    q3, k3, v3 = qkv(b=3, s=128, h=4, d=128)
    assert sharded_flash_attention(q3, k3, v3, mesh=dp_mesh) is None  # 3 % 8


def test_flash_fallback_on_odd_shapes():
    q, k, v = qkv(b=1, s=60, h=2, d=16)  # not tileable -> blockwise fallback
    ref = default_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.slow
def test_decoder_with_ring_attention_e2e():
    """Decoder runs unchanged with ring attention as its attention_fn on an
    sp mesh — the long-context config."""
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.ringattention import make_ring_attention
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    ctx = TrainContext.create(ShardingSpec(sp=4, dp=2))
    cfg = DecoderConfig.tiny(attention_fn=make_ring_attention(ctx.mesh))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 4, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    first = last = None
    for _ in range(15):
        state, m = trainer.step(state, trainer.shard_batch(next(data)))
        if first is None:
            first = float(m["loss"])
        last = float(m["loss"])
    assert np.isfinite(last) and last < first
