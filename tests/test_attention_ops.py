"""Attention op correctness: blockwise == reference, ring == reference on a
seq-sharded mesh, Ulysses == reference, flash kernel (interpret mode) ==
reference, and gradients flow through blockwise/ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models.transformer import default_attention
from maggy_tpu.ops.attention import blockwise_attention
from maggy_tpu.ops.flash import flash_attention
from maggy_tpu.parallel.mesh import make_mesh
from maggy_tpu.parallel.ringattention import ring_attention
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.parallel.ulysses import ulysses_attention


def qkv(b=2, s=64, h=4, kh=None, d=16, seed=0, dtype=jnp.float32):
    kh = kh or h
    rng = jax.random.key(seed)
    k1, k2, k3 = jax.random.split(rng, 3)
    return (
        jax.random.normal(k1, (b, s, h, d), dtype),
        jax.random.normal(k2, (b, s, kh, d), dtype),
        jax.random.normal(k3, (b, s, kh, d), dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_k", [16, 64, 50])
def test_blockwise_matches_reference(causal, block_k):
    q, k, v = qkv()
    ref = default_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_blockwise_gqa():
    q, k, v = qkv(h=8, kh=2)
    ref = default_attention(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_blockwise_grads_match():
    q, k, v = qkv(s=32)

    def loss_ref(q, k, v):
        return default_attention(q, k, v, causal=True).sum()

    def loss_blk(q, k, v):
        return blockwise_attention(q, k, v, causal=True, block_k=8).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(causal):
    mesh = make_mesh(ShardingSpec(sp=4, dp=2))
    q, k, v = qkv(b=2, s=64, h=4, d=16)
    ref = default_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=causal)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gqa_and_grads():
    mesh = make_mesh(ShardingSpec(sp=4, dp=2))
    q, k, v = qkv(b=2, s=32, h=8, kh=4, d=8)
    ref = default_attention(q, k, v, causal=True)
    with mesh:
        out = ring_attention(q, k, v, mesh=mesh, causal=True)
        g = jax.grad(
            lambda q: ring_attention(q, k, v, mesh=mesh, causal=True).sum()
        )(q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g_ref = jax.grad(lambda q: default_attention(q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(causal):
    mesh = make_mesh(ShardingSpec(sp=4, dp=2))
    q, k, v = qkv(b=2, s=64, h=8, d=16)
    ref = default_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh, causal=causal)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_head_divisibility():
    mesh = make_mesh(ShardingSpec(sp=8))
    q, k, v = qkv(h=4)  # 4 heads, 8 shards
    with pytest.raises(ValueError, match="divide the head count"):
        with mesh:
            ulysses_attention(q, k, v, mesh=mesh)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    # d must be a multiple of 128 lanes for the kernel path
    q, k, v = qkv(b=1, s=256, h=2, d=128)
    ref = default_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_flash_fallback_on_odd_shapes():
    q, k, v = qkv(b=1, s=60, h=2, d=16)  # not tileable -> blockwise fallback
    ref = default_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_decoder_with_ring_attention_e2e():
    """Decoder runs unchanged with ring attention as its attention_fn on an
    sp mesh — the long-context config."""
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.ringattention import make_ring_attention
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    ctx = TrainContext.create(ShardingSpec(sp=4, dp=2))
    cfg = DecoderConfig.tiny(attention_fn=make_ring_attention(ctx.mesh))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 4, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    first = last = None
    for _ in range(15):
        state, m = trainer.step(state, trainer.shard_batch(next(data)))
        if first is None:
            first = float(m["loss"])
        last = float(m["loss"])
    assert np.isfinite(last) and last < first
