"""Request-scoped tracing, latency histograms, stall flight recorder
(ISSUE 7): histogram/tracing/watchdog units, RPC trace propagation, JSONL
rotation, scheduler lifecycle events, the 2-replica fleet acceptance run
(one correlated Chrome-trace lane per request + merged histograms + SLO
attainment + analyze_trace attribution), trace continuity across a chaos
replica kill, the flight recorder firing on an injected rpc_stall, and the
telemetry-name lint."""

import importlib.util
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from maggy_tpu.resilience import chaos
from maggy_tpu.telemetry import flightrec, tracing
from maggy_tpu.telemetry import recorder as rec_mod
from maggy_tpu.telemetry.histogram import LatencyHistogram, merge_dicts
from maggy_tpu.telemetry.recorder import Telemetry
from maggy_tpu.telemetry.sink import JsonlSink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -------------------------------------------------------------- histograms


def test_histogram_observe_percentiles_merge():
    h = LatencyHistogram()
    for v in (1.0, 2.0, 4.0, 8.0, 100.0, 100.0, 100.0, 100.0):
        h.observe(v)
    assert h.n == 8
    assert h.mean_ms == pytest.approx(sum((1, 2, 4, 8, 100, 100, 100, 100)) / 8)
    # bucket-resolution approximations: within the ~7% bucket width
    assert h.percentile(0.5) == pytest.approx(8.0, rel=0.20)
    assert h.percentile(0.99) == pytest.approx(100.0, rel=0.10)
    # negative / NaN dropped, never recorded
    h.observe(-5.0)
    h.observe(float("nan"))
    assert h.n == 8

    a, b = LatencyHistogram(), LatencyHistogram()
    for v in (10.0,) * 50:
        a.observe(v)
    for v in (1000.0,) * 50:
        b.observe(v)
    merged = merge_dicts([a.to_dict(), b.to_dict(), None, {"junk": 1}])
    assert merged.n == 100
    # true merged percentiles: median straddles the two populations,
    # p99 comes from the slow replica — what max-of-p50s could never say
    assert merged.percentile(0.25) == pytest.approx(10.0, rel=0.10)
    assert merged.percentile(0.99) == pytest.approx(1000.0, rel=0.10)
    with pytest.raises(ValueError, match="geometry"):
        LatencyHistogram(growth=2.0).merge(a)


def test_histogram_attainment_and_serialization():
    h = LatencyHistogram()
    for _ in range(90):
        h.observe(10.0)
    for _ in range(10):
        h.observe(500.0)
    assert h.attainment(100.0) == pytest.approx(0.9, abs=0.02)
    assert h.attainment(1e9) == pytest.approx(1.0)
    assert LatencyHistogram().attainment(10.0) is None
    rt = LatencyHistogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert rt.n == h.n
    assert rt.percentile(0.5) == h.percentile(0.5)
    assert rt.total_ms == pytest.approx(h.total_ms)


# ----------------------------------------------------------------- tracing


def test_tracing_scope_ensure_and_isolation():
    assert tracing.current() is None
    with tracing.scope("t-outer"):
        assert tracing.current() == "t-outer"
        assert tracing.ensure() == "t-outer"
        with tracing.scope(None):  # handlers mask the outer trace
            assert tracing.current() is None
        with tracing.scope("t-inner"):
            assert tracing.current() == "t-inner"
        assert tracing.current() == "t-outer"
    assert tracing.current() is None
    minted = tracing.ensure()
    assert minted and tracing.current() is None  # ensure() does not install
    seen = {}
    t = threading.Thread(target=lambda: seen.update(t=tracing.current()))
    with tracing.scope("t-main"):
        t.start()
        t.join()
    assert seen["t"] is None  # thread-local: other threads see nothing


def test_recorder_trace_tag_event_histogram_flight():
    tel = Telemetry(worker=3)
    with tracing.scope("tr1"):
        with tel.span("work", step=1):
            pass
        tel.gauge("step_time_ms", 5.0)
        tel.event("req.queued", rid="r1")
    tel.event("req.finished", trace="tr1", rid="r1", state="done")
    tel.histogram("serve.ttft_ms", 25.0)
    tel.histogram("serve.ttft_ms", 30.0)

    events = tel.drain_events()
    assert [e["kind"] for e in events] == ["span", "gauge", "event", "event"]
    assert all(e["trace"] == "tr1" for e in events)
    ev = events[2]
    assert ev["name"] == "req.queued" and ev["attrs"] == {"rid": "r1"}
    # flight ring keeps its own copy after the drain
    assert len(tel.flight) == 4
    snap = tel.snapshot()
    assert snap["hist"]["serve.ttft_ms"]["n"] == 2
    # the registry includes this recorder's ring for watchdog dumps
    rings = {r["worker"]: r for r in rec_mod.flight_snapshots()}
    assert len(rings["3"]["events"]) == 4


# ----------------------------------------------------- rpc trace propagation


def test_rpc_propagates_trace_to_handler_scope():
    from maggy_tpu.core import rpc

    server = rpc.Server(num_executors=0)
    seen = []
    server.register_callback(
        "PING", lambda msg: seen.append((msg.get("trace"), tracing.current()))
        or {"type": "PING"}
    )
    host, port = server.start(host="127.0.0.1")
    try:
        client = rpc.Client((host, port), partition_id=-1, secret=server.secret)
        try:
            with tracing.scope("wire-1"):
                client.request({"type": "PING"})  # ambient id rides the frame
            client.request({"type": "PING", "trace": "wire-2"})  # explicit wins
            client.request({"type": "PING"})  # no scope: no trace field
        finally:
            client.stop()
    finally:
        server.stop()
    assert seen[0] == ("wire-1", "wire-1")
    assert seen[1] == ("wire-2", "wire-2")
    assert seen[2] == (None, None)


# ------------------------------------------------------------ sink rotation


def test_jsonl_sink_rotation_and_rotated_read(tmp_env, tmp_path):
    from maggy_tpu.telemetry.export import load_records

    tdir = os.path.join(str(tmp_path), "exp", "telemetry")
    os.makedirs(tdir)
    path = os.path.join(tdir, "worker_9.jsonl")
    sink = JsonlSink(path, env=tmp_env, max_bytes=400, max_segments=2)
    for i in range(30):
        sink.write(
            [{"kind": "gauge", "name": "g", "ts": float(i), "value": float(i),
              "worker": "9"}]
        )
    sink.close()
    names = sorted(os.listdir(tdir))
    # live file + bounded rotated segments, never more
    assert names[0] == "worker_9.jsonl"
    assert set(names[1:]) <= {"worker_9.jsonl.1", "worker_9.jsonl.2"}
    assert len(names) == 3
    recs = load_records(tmp_env, os.path.join(str(tmp_path), "exp"))
    vals = [r["value"] for r in recs["worker_9"]]
    # rotation dropped the oldest, kept order, and the reader folds the
    # surviving segments oldest-first under ONE stem
    assert vals == sorted(vals)
    assert vals[-1] == 29.0
    assert len(vals) < 30


# ------------------------------------------------- watchdog / flight recorder


def test_watchdog_fires_on_stall_not_on_beats(tmp_path):
    wd = flightrec.Watchdog(stall_s=0.15, interval_s=0.03, dump_dir=str(tmp_path))
    try:
        wd.begin("loop.a")
        deadline = time.time() + 0.6
        while time.time() < deadline and not wd.dumps:
            wd.beat("loop.b")  # beating a DIFFERENT mark must not help a
            time.sleep(0.02)
        assert wd.dumps, "armed mark with no beats never dumped"
        dump = json.load(open(wd.dumps[0]))
        assert dump["reason"].startswith("stall")
        assert "loop.a" in dump["marks"]
        assert dump["threads"]  # every thread's stack is in the payload
        assert any("MainThread" in k for k in dump["threads"])
        # one dump per stall episode: no second dump while still stalled
        n = len(wd.dumps)
        time.sleep(0.3)
        assert len(wd.dumps) == n
        # a beat re-arms; a healthy beating mark never dumps again
        wd.beat("loop.a")
        t0 = time.time()
        while time.time() - t0 < 0.3:
            wd.beat("loop.a")
            time.sleep(0.02)
        assert len(wd.dumps) == n
        wd.end("loop.a")
    finally:
        wd.stop()


def test_flight_recorder_fires_on_rpc_stall(tmp_path):
    """Acceptance seam: an injected rpc_stall wedges the server event loop;
    the watchdog dumps the event ring + thread stacks mid-stall."""
    from maggy_tpu.core import rpc

    wd = flightrec.Watchdog(stall_s=0.2, interval_s=0.05, dump_dir=str(tmp_path))
    flightrec.install(wd)
    chaos.install(chaos.Chaos.parse("rpc_stall:verb=PING,secs=1.0"))
    tel = Telemetry(worker="stalled")
    tel.event("req.queued", trace="stall-trace", rid="r-stall")
    server = rpc.Server(num_executors=0)
    server.register_callback("PING", lambda msg: {"type": "PING"})
    host, port = server.start(host="127.0.0.1")
    try:
        client = rpc.Client((host, port), partition_id=-1, secret=server.secret)
        try:
            client.request({"type": "PING"})  # blocks ~1s in the chaos stall
        finally:
            client.stop()
    finally:
        server.stop()
        chaos.reset()
        flightrec.reset()
    assert wd.dumps, "watchdog never fired during the stall"
    dump = json.load(open(wd.dumps[0]))
    assert "rpc.PING" in dump["reason"] or "rpc.PING" in dump["marks"]
    # the stalled thread's stack shows where it was wedged
    stacks = "".join("".join(frames) for frames in dump["threads"].values())
    assert "sleep" in stacks
    # the flight ring carried the recent lifecycle events into the dump
    rings = {r["worker"]: r["events"] for r in dump["events"]}
    assert any(
        e.get("name") == "req.queued" and e.get("trace") == "stall-trace"
        for e in rings.get("stalled", [])
    )


def test_watchdog_disabled_env(monkeypatch):
    monkeypatch.setenv("MAGGY_TPU_FLIGHTREC", "0")
    flightrec.reset()
    wd = flightrec.get()
    assert isinstance(wd, flightrec.NullWatchdog)
    wd.begin("x")
    wd.beat("x")
    wd.end("x")
    assert wd.dump("r") is None
    monkeypatch.delenv("MAGGY_TPU_FLIGHTREC")
    flightrec.reset()


# --------------------------------------------------------------- CI lint


def test_check_telemetry_names_lint():
    """tools/check_telemetry_names.py runs clean over maggy_tpu/ (wired
    into tier-1 here) and its detector catches typos without flagging
    non-telemetry .count() calls."""
    mod = load_tool("check_telemetry_names")
    assert mod.main([]) == 0

    registry = mod.load_registry(REPO)
    flag = lambda src: mod.check_source(src, "<s>", registry)  # noqa: E731
    # a typo'd gauge is flagged; the registered name is not
    assert flag("tel.gauge('serve.ttft_m', 1)") != []
    assert flag("tel.gauge('serve.ttft_ms', 1)") == []
    # kind mix-up: histogram-only name used as a counter
    assert flag("self.telemetry.count('serve.tpot_ms')") != []
    # dynamic prefixes: registered head passes, unknown head fails
    assert flag("tel.count(f'serve.requests_{k}')") == []
    assert flag("tel.count(f'serve.requestz_{k}')") != []
    # non-telemetry receivers are out of scope (str/list .count)
    assert flag("'abc'.count('serve.nope')") == []
    assert flag("mylist.count(x)") == []
    # variables cannot be checked statically: skipped, not flagged
    assert flag("tel.gauge(name, 1)") == []


def test_trace_overhead_recorder_hot_path():
    """The full per-record observability cost — span + gauge + event +
    histogram, trace-tagged, flight-teed — stays far under any realistic
    step budget (bench.py extra.trace_overhead tracks the engine-level A/B;
    2% of even a 5 ms step is 100 us, asserted loosely here)."""
    tel = Telemetry(worker="bench")
    n = 2000
    with tracing.scope("hot"):
        t0 = time.perf_counter()
        for i in range(n):
            with tel.span("serve.decode_step", active=4):
                pass
            tel.gauge("serve.drain_ms", 0.1)
            tel.histogram("serve.drain_ms", 0.1)
            tel.event("req.first_token", rid="r", ttft_ms=1.0)
        per_iter_us = (time.perf_counter() - t0) / n * 1e6
    assert per_iter_us < 100.0, per_iter_us


# ------------------------------------------------------- analyze_trace units


def test_analyze_trace_attribution_synthetic(tmp_path):
    analyze = load_tool("analyze_trace")
    tdir = os.path.join(str(tmp_path), "telemetry")
    os.makedirs(tdir)
    base = 100.0
    router = [
        ("req.accepted", 0.000, {"rid": "r1"}),
        ("req.dispatched", 0.004, {"replica": 0}),
        ("req.requeued", 0.060, {"replica": 0, "resubmits": 1}),
        ("req.dispatched", 0.062, {"replica": 1}),
        ("req.completed", 0.200, {"state": "done"}),
    ]
    replica = [
        ("req.queued", 0.005, {}),
        ("req.admitted", 0.006, {}),
        ("req.first_token", 0.030, {"ttft_ms": 30.0}),
        ("req.queued", 0.063, {}),
        ("req.admitted", 0.064, {}),
        ("req.first_token", 0.090, {"ttft_ms": 90.0}),
        ("req.finished", 0.190, {"state": "done", "n_tokens": 8}),
    ]
    for stem, events in (("router", router), ("worker_1", replica)):
        with open(os.path.join(tdir, f"{stem}.jsonl"), "w") as f:
            for name, dt, attrs in events:
                f.write(json.dumps({
                    "kind": "event", "name": name, "ts": base + dt,
                    "worker": stem, "trace": "tr-99", "attrs": attrs,
                }) + "\n")
    # per-step gauges ride in the same dir
    with open(os.path.join(tdir, "worker_0.jsonl"), "w") as f:
        for v in (10.0, 12.0):
            f.write(json.dumps({"kind": "gauge", "name": "step_time_ms",
                                "ts": base, "value": v, "worker": "0"}) + "\n")
        f.write(json.dumps({"kind": "gauge", "name": "input_wait_ms",
                            "ts": base, "value": 2.0, "worker": "0"}) + "\n")

    result = analyze.analyze(str(tmp_path))
    rows = result["requests"]
    assert len(rows) == 1
    row = rows[0]
    assert row["trace"] == "tr-99" and row["rid"] == "r1" and row["hops"] == 1
    comp = row["components"]
    # attribution covers the whole span: components sum to measured e2e
    assert sum(comp.values()) == pytest.approx(row["e2e_ms"], rel=0.05)
    assert row["e2e_ms"] == pytest.approx(200.0, rel=0.01)
    assert comp["prefill"] == pytest.approx(24.0 + 26.0, rel=0.05)
    assert comp["decode"] == pytest.approx(100.0, rel=0.05)
    assert comp["lost"] == pytest.approx(30.0, rel=0.05)  # first_token→requeued
    assert comp["route"] > 0 and comp["queue"] > 0
    steps = result["step_summary"]
    assert steps["steps"] == 2
    assert steps["step_ms_mean"] == pytest.approx(11.0)
    assert steps["compute_ms_est"] == pytest.approx(9.0)
    report = analyze.render_report(rows, result["request_summary"], steps)
    assert "per-request attribution" in report
    assert "per-step attribution" in report


# --------------------------------------------- engine-backed lifecycle tests

CFG = None  # built lazily so collection stays fast


def _cfg():
    global CFG
    if CFG is None:
        from maggy_tpu.models import DecoderConfig

        CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    return CFG


@pytest.fixture(scope="module")
def params():
    from maggy_tpu.models import Decoder
    from maggy_tpu.parallel.sharding import unbox

    return unbox(
        Decoder(_cfg()).init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )


def test_scheduler_lifecycle_events_histograms_slo(params):
    """One engine, three requests: the full queued→admitted→first_token→
    finished event chain per trace, scheduler histograms feeding SSTATS
    percentiles, and exact SLO counters."""
    from maggy_tpu.serve import Engine, SamplingParams, Scheduler

    tel = Telemetry(worker="sched")
    engine = Engine(_cfg(), params, num_slots=2, telemetry_recorder=tel)
    scheduler = Scheduler(engine, slo_ttft_ms=60_000.0)
    scheduler.start()
    try:
        reqs = [
            scheduler.submit(
                [1 + i, 2, 3], SamplingParams(max_new=4), trace=f"life-{i}"
            )
            for i in range(3)
        ]
        deadline = time.time() + 120
        while time.time() < deadline and any(r.state != "done" for r in reqs):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs)
    finally:
        scheduler.stop()

    by_trace = {}
    for e in tel.drain_events():
        if e["kind"] == "event":
            by_trace.setdefault(e.get("trace"), []).append(e["name"])
    for i, req in enumerate(reqs):
        assert req.trace == f"life-{i}"
        names = by_trace[f"life-{i}"]
        admitted = (
            "req.admitted" if "req.admitted" in names else "req.prefix_admitted"
        )
        order = [
            names.index("req.queued"), names.index(admitted),
            names.index("req.first_token"), names.index("req.finished"),
        ]
        assert order == sorted(order), names

    stats = scheduler.stats()
    for key in ("ttft_ms_p50", "ttft_ms_p90", "ttft_ms_p95", "ttft_ms_p99",
                "tpot_ms_p50", "queue_wait_ms_p50", "e2e_ms_p50"):
        assert stats[key] is not None, key
    assert stats["latency"]["ttft_ms"]["n"] == 3
    assert stats["latency"]["e2e_ms"]["n"] == 3
    # tiny decoder on CPU: everything lands inside a 60s TTFT budget
    assert stats["slo_ok"] == 3 and stats["slo_miss"] == 0
    assert stats["slo_attainment"] == 1.0
    # recorder-side mirrors for JSONL/monitor snapshots
    snap = tel.snapshot()
    assert snap["hist"]["serve.ttft_ms"]["n"] == 3
    # POLL wire carries the trace id
    assert scheduler.poll(reqs[0].id)["trace"] == "life-0"


def test_fit_emits_run_trace_events():
    """Trainer.fit mints one trace per run: start/end events share it and
    every train_step span inside carries it."""
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    tel = Telemetry(worker=0)
    with rec_mod.current(tel):
        trainer.fit(state, data, num_steps=3)
    events = tel.drain_events()
    lifecycle = [e for e in events if e["kind"] == "event"]
    assert [e["name"] for e in lifecycle] == ["train.run_start", "train.run_end"]
    run_trace = lifecycle[0]["trace"]
    assert run_trace and lifecycle[1]["trace"] == run_trace
    assert lifecycle[0]["attrs"]["num_steps"] == 3
    steps = [e for e in events if e["kind"] == "span" and e["name"] == "train_step"]
    assert len(steps) == 3
    assert all(s.get("trace") == run_trace for s in steps)
    # the ambient trace did not leak out of fit
    assert tracing.current() is None


# ------------------------------------------------------- fleet acceptance


def test_fleet_tracing_acceptance(params, tmp_env):
    """ISSUE 7 acceptance: a staggered 2-replica fleet run yields (a) a
    merged Chrome trace where each request is ONE lane correlated across
    router + replica workers, (b) SSTATS with merged-histogram TTFT
    p50/p95/p99 and SLO attainment, and (c) analyze_trace attribution whose
    components sum to within 5% of the measured e2e."""
    from maggy_tpu.serve import ServeClient
    from maggy_tpu.serve.fleet import ReplicaSpec, RouterConfig, launch_fleet
    from maggy_tpu.telemetry import worker_telemetry
    from maggy_tpu.telemetry.export import REQUESTS_PID, export_chrome_trace

    exp_dir = tmp_env.experiment_dir("app_trace", 1)
    recorders = {}

    def factory(i):
        recorders[i] = worker_telemetry(f"replica{i}", exp_dir, role="serve",
                                        env=tmp_env)
        return recorders[i]

    router_tel = worker_telemetry("router", exp_dir, role="router", env=tmp_env)
    router = launch_fleet(
        ReplicaSpec(_cfg(), params, num_slots=2, telemetry_factory=factory),
        replicas=2,
        config=RouterConfig(slo_ttft_ms=120_000.0, admission="queue"),
        telemetry_recorder=router_tel,
    )
    host, port = router.start(host="127.0.0.1")
    prompts = [[1, 2, 3, 4], [5, 6, 7], [9, 10, 11], [2, 4, 6], [7, 3],
               [20, 21, 22]]
    traces = [f"accept-{i:02d}" for i in range(len(prompts))]
    max_new = 5
    results, errors = {}, []

    def drive(i, prompt, delay):
        try:
            time.sleep(delay)
            with ServeClient((host, port), router.secret) as client:
                rid = client.submit(prompt, max_new=max_new, trace=traces[i])
                snap = client.result(rid, timeout=120)
                assert snap["trace"] == traces[i]  # POLL echoes the trace
                results[i] = snap["tokens"]
        except Exception as e:  # noqa: BLE001 - surfaced via the errors list
            errors.append((i, repr(e)))

    try:
        threads = [
            threading.Thread(target=drive, args=(i, p, 0.04 * i))
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        assert not errors, errors
        assert len(results) == len(prompts)

        with ServeClient((host, port), router.secret) as client:
            deadline = time.time() + 30
            while time.time() < deadline:
                stats = client.stats()
                if stats["routing"]["completed"] == len(prompts):
                    break
                time.sleep(0.05)
        # (b) merged-histogram percentiles + SLO attainment over the fleet
        assert stats["routing"]["completed"] == len(prompts)
        assert stats["latency"]["ttft_ms"]["n"] == len(prompts)
        for key in ("ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99"):
            assert stats[key] is not None and stats[key] > 0
        assert stats["ttft_ms_p50"] <= stats["ttft_ms_p99"]
        assert stats["slo_ttft_ms"] == 120_000.0
        assert stats["slo_ok"] == len(prompts) and stats["slo_miss"] == 0
        assert stats["slo_attainment"] == 1.0
        # monitor renders the latency/SLO line from the same stats
        from maggy_tpu.monitor import render_status

        status = None
        with ServeClient((host, port), router.secret) as client:
            status = client._client.request({"type": "STATUS"})
        panel = render_status(status)
        assert "p99" in panel and "slo 100.0%" in panel
    finally:
        router.stop()
        router_tel.close()
        for tel in recorders.values():
            tel.close()

    # (a) one correlated lane per request in the merged Chrome trace
    out = export_chrome_trace(tmp_env, exp_dir)
    trace_json = json.load(open(out))
    lanes = [e for e in trace_json["traceEvents"] if e.get("pid") == REQUESTS_PID]
    lane_traces = {
        e["args"]["trace"] for e in lanes if e.get("ph") in ("i", "X")
    }
    assert lane_traces == set(traces)
    # every lane shows the full journey: route span + prefill + decode
    for tr in traces:
        phases = {e["name"] for e in lanes
                  if e.get("ph") == "X" and e["args"]["trace"] == tr}
        assert {"route", "queue", "prefill", "decode"} <= phases, (tr, phases)

    # cross-worker correlation: each trace's raw events span the router
    # JSONL AND a replica JSONL
    from maggy_tpu.telemetry.export import load_records

    by_stem = load_records(tmp_env, exp_dir)
    for tr in traces:
        stems = {
            stem
            for stem, records in by_stem.items()
            for r in records
            if r.get("kind") == "event" and r.get("trace") == tr
        }
        assert "worker_router" in stems
        assert any(s.startswith("worker_replica") for s in stems), (tr, stems)

    # (c) analyze_trace attribution sums to the measured e2e within 5%
    analyze = load_tool("analyze_trace")
    result = analyze.analyze(exp_dir)
    rows = {row["trace"]: row for row in result["requests"]}
    assert set(rows) == set(traces)
    for tr, row in rows.items():
        total = sum(row["components"].values())
        assert total == pytest.approx(row["e2e_ms"], rel=0.05), (tr, row)
        assert row["components"].get("decode", 0) > 0
        assert row["components"].get("prefill", 0) > 0
    summary = result["request_summary"]
    assert summary["requests"] == len(prompts)


def test_trace_continuity_across_replica_kill(params):
    """Satellite: a replica_kill mid-stream keeps ONE trace id across the
    requeue — an explicit req.requeued hop on the router, then a second
    queued→admitted→…→finished cycle on the survivor under the same id."""
    from maggy_tpu.serve import ServeClient
    from maggy_tpu.serve.fleet import ReplicaSpec, RouterConfig, launch_fleet

    chaos.install(chaos.Chaos.parse("replica_kill:replica=1"))
    recorders = {}

    def factory(i):
        # respawns reuse the index: keep ONE recorder per replica index
        if i not in recorders:
            recorders[i] = Telemetry(worker=f"replica{i}")
        return recorders[i]

    router_tel = Telemetry(worker="router")
    router = launch_fleet(
        ReplicaSpec(_cfg(), params, num_slots=2, telemetry_factory=factory),
        replicas=2,
        config=RouterConfig(max_restarts=0, quarantine_threshold=2),
        telemetry_recorder=router_tel,
    )
    host, port = router.start(host="127.0.0.1")
    prompts = [[1, 2, 3, 4], [5, 6, 7], [9, 10, 11, 12], [2, 4, 6, 8]]
    traces = [f"chaos-{i:02d}" for i in range(len(prompts))]
    results, errors = {}, []

    def drive(i, prompt, delay):
        try:
            time.sleep(delay)
            with ServeClient((host, port), router.secret) as client:
                rid = client.submit(prompt, max_new=30, trace=traces[i])
                results[i] = client.result(rid, timeout=240)
        except Exception as e:  # noqa: BLE001 - surfaced via the errors list
            errors.append((i, repr(e)))

    try:
        threads = [
            threading.Thread(target=drive, args=(i, p, 0.04 * i))
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        assert chaos.get().fired, "chaos rule never fired"
        assert all(s["state"] == "done" for s in results.values())
    finally:
        router.stop()
        chaos.reset()

    router_events = [
        e for e in router_tel.drain_events() if e["kind"] == "event"
    ]
    requeued = [e for e in router_events if e["name"] == "req.requeued"]
    assert requeued, "no requeue hop event despite the chaos kill"
    # every hop kept a submitted trace id — the binding is durable
    assert {e["trace"] for e in requeued} <= set(traces)

    replica_events = [
        e
        for tel in recorders.values()
        for e in tel.drain_events()
        if e["kind"] == "event"
    ]
    for hop in requeued:
        tr = hop["trace"]
        names = [e["name"] for e in replica_events if e.get("trace") == tr]
        # the SAME trace ran (at least) two admission cycles: one on the
        # killed replica, one on the survivor
        assert names.count("req.queued") >= 2, (tr, names)
        assert names.count("req.finished") >= 1, (tr, names)
        # and the router saw it through to completion under that id
        assert any(
            e["name"] == "req.completed" and e["trace"] == tr
            for e in router_events
        ), tr
