"""Distributed-training path tests on the 8-device CPU mesh: mesh construction,
sharded state placement, training convergence under every preset, the lagom
DistributedConfig e2e path, and the graft dryrun."""

import jax
import numpy as np
import optax
import pytest

from maggy_tpu import experiment
from maggy_tpu.config import DistributedConfig
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.mesh import make_mesh
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext, Trainer
from maggy_tpu.train.data import synthetic_lm_batches


def test_make_mesh_axes():
    spec = ShardingSpec(dp=2, fsdp=2, tp=2)
    mesh = make_mesh(spec)
    assert mesh.shape == {
        "stage": 1, "data": 2, "fsdp": 2, "expert": 1, "seq": 1, "tensor": 2,
    }
    with pytest.raises(ValueError):
        make_mesh(ShardingSpec(dp=3))


def test_sharded_state_placement():
    ctx = TrainContext.create(ShardingSpec(dp=2, fsdp=2, tp=2))
    cfg = DecoderConfig.tiny()
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    batch = next(synthetic_lm_batches(cfg.vocab_size, 8, 32))
    state = trainer.make_state(jax.random.key(0), batch)

    import flax.linen as nn

    def unwrap(leaf):
        return leaf.value if isinstance(leaf, nn.Partitioned) else leaf

    # embedding [L?, vocab, embed] must shard over tensor x fsdp
    emb = unwrap(state.params["embedding"])
    assert "tensor" in str(emb.sharding.spec) and "fsdp" in str(emb.sharding.spec)
    # optimizer state mirrors param shardings (ZeRO-for-free)
    mu_emb = unwrap(state.opt_state[0].mu["embedding"])
    assert mu_emb.sharding == emb.sharding
    # mlp kernel shards over tensor
    wg = unwrap(state.params["layers"]["layer"]["mlp"]["w_gate"]["kernel"])
    assert "tensor" in str(wg.sharding.spec)


@pytest.mark.parametrize("preset", ["dp", "fsdp", "2d"])
@pytest.mark.slow
def test_training_learns_under_preset(preset):
    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create(preset)
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=1)
    state = trainer.make_state(jax.random.key(0), next(data))
    first = last = None
    for i in range(40):
        state, m = trainer.step(state, trainer.shard_batch(next(data)))
        if first is None:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first * 0.85, (preset, first, last)


@pytest.mark.slow
def test_dp_and_fsdp_agree():
    """Same seed, same data: the sharding layout must not change the math."""
    cfg = DecoderConfig.tiny()
    losses = {}
    for preset in ("dp", "fsdp"):
        ctx = TrainContext.create(preset)
        trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-2))
        data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=3)
        state = trainer.make_state(jax.random.key(7), next(data))
        out = []
        for _ in range(5):
            state, m = trainer.step(state, trainer.shard_batch(next(data)))
            out.append(float(m["loss"]))
        losses[preset] = out
    np.testing.assert_allclose(losses["dp"], losses["fsdp"], rtol=2e-4)


@pytest.mark.slow
def test_lagom_distributed_e2e(tmp_env):
    """Oblivious distributed train_fn through the lagom front door."""
    cfg = DecoderConfig.tiny()

    def train(model, dataset, hparams, reporter, ctx):
        trainer = ctx.trainer(model, optax.adamw(hparams["lr"]))
        state = trainer.make_state(jax.random.key(0), next(dataset))
        state, metrics = trainer.fit(
            state, dataset, num_steps=20, reporter=reporter, metric_sign=-1.0
        )
        return {"metric": -metrics["loss"], "loss": metrics["loss"]}

    dconf = DistributedConfig(
        module=Decoder(cfg),
        dataset=synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=5),
        hparams={"lr": 3e-3},
        sharding="2d",
        hb_interval=0.05,
    )
    result = experiment.lagom(train, dconf)
    assert result["num_workers"] == 1
    assert result["loss"] < 5.5


@pytest.mark.slow
def test_graft_entry_and_dryrun():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] == 2048

    mod.dryrun_multichip(8)
