"""Resilience subsystem: retry policy, quarantine, chaos injector,
stranded-completion regression, RPC backoff jitter, monitor panel, and the
exception-hygiene lint (docs/resilience.md)."""

import os
import signal
import threading
import time

import pytest

from maggy_tpu import Searchspace
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.core import rpc
from maggy_tpu.core.driver.hpo import HyperparameterOptDriver
from maggy_tpu.exceptions import RpcError, WorkerLost
from maggy_tpu.resilience import (
    DETERMINISTIC,
    TRANSIENT,
    QuarantineTracker,
    RetryPolicy,
    classify_failure,
)
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.resilience import preemption
from maggy_tpu.trial import Trial


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos_mod.reset()
    yield
    chaos_mod.reset()


# ------------------------------------------------------------------- policy


def test_classify_failure():
    assert classify_failure(WorkerLost("preempted")) == TRANSIENT
    assert classify_failure(chaos_mod.WorkerKilled("chaos")) == TRANSIENT
    assert classify_failure(RpcError("conn reset")) == TRANSIENT
    assert classify_failure(ConnectionResetError()) == TRANSIENT
    assert classify_failure(TimeoutError()) == TRANSIENT
    assert classify_failure(ValueError("bad hparam")) == DETERMINISTIC
    assert classify_failure(RuntimeError("train_fn bug")) == DETERMINISTIC


def test_retry_policy_backoff():
    p = RetryPolicy(max_retries=3, backoff_base=0.5, backoff_factor=2.0,
                    backoff_cap=4.0, jitter=0.25, seed=7)
    delays = [p.delay(a) for a in range(6)]
    # deterministic: same policy, same attempt -> same delay
    assert delays == [p.delay(a) for a in range(6)]
    # exponential growth within jitter bounds, capped
    for a, d in enumerate(delays):
        base = min(0.5 * 2.0**a, 4.0)
        assert base * 0.75 <= d <= base
    assert delays[5] <= 4.0
    # different seeds de-synchronize
    assert RetryPolicy(seed=1).delay(0) != RetryPolicy(seed=2).delay(0)


def test_retry_policy_env_override(monkeypatch):
    cfg = HyperparameterOptConfig(
        num_trials=1, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        trial_retries=5, retry_backoff=2.0,
    )
    assert RetryPolicy.from_config(cfg).max_retries == 5
    assert RetryPolicy.from_config(cfg).backoff_base == 2.0
    monkeypatch.setenv("MAGGY_TPU_TRIAL_RETRIES", "1")
    monkeypatch.setenv("MAGGY_TPU_RETRY_BACKOFF", "0.1")
    assert RetryPolicy.from_config(cfg).max_retries == 1
    assert RetryPolicy.from_config(cfg).backoff_base == 0.1


def test_quarantine_tracker():
    q = QuarantineTracker(threshold=3, cooldown=100.0)
    t0 = 1000.0
    assert not q.record_failure(1, now=t0)
    assert not q.record_failure(1, now=t0)
    # a success resets the streak
    q.record_success(1)
    assert not q.record_failure(1, now=t0)
    assert not q.record_failure(1, now=t0)
    assert q.record_failure(1, now=t0)  # third consecutive -> quarantined
    assert q.is_quarantined(1, now=t0 + 50)
    assert q.quarantined(now=t0 + 50) == [1]
    # other workers unaffected
    assert not q.is_quarantined(2, now=t0 + 50)
    # cooldown elapses -> released on probation...
    assert not q.is_quarantined(1, now=t0 + 101)
    # ...where a single further death re-quarantines immediately
    assert q.record_failure(1, now=t0 + 102)
    assert q.is_quarantined(1, now=t0 + 103)


# -------------------------------------------------------------------- chaos


def test_chaos_parse_and_fire_deterministic():
    ch = chaos_mod.Chaos.parse(
        "kill:worker=1,step=3;hb_drop:worker=0,times=2;rpc_stall:verb=GET,secs=0.25"
    )
    # no match: wrong worker / wrong step
    ch.kill(worker=0, step=3)
    ch.kill(worker=1, step=2)
    with pytest.raises(chaos_mod.WorkerKilled):
        ch.kill(worker=1, step=3)
    # times=1 consumed: the same point never fires twice (resume safety)
    ch.kill(worker=1, step=3)

    assert ch.drop_heartbeat(0)
    assert ch.drop_heartbeat(0)
    assert not ch.drop_heartbeat(0)  # budget of 2 spent
    assert not ch.drop_heartbeat(1)  # other workers unaffected

    assert ch.rpc_stall("GET") == 0.25
    assert ch.rpc_stall("GET") == 0.0
    assert ch.rpc_stall("FINAL") == 0.0
    assert ("kill", {"worker": 1, "step": 3}) in ch.fired


def test_chaos_parse_rejects_garbage():
    with pytest.raises(ValueError):
        chaos_mod.Chaos.parse("kill:worker")


def test_chaos_env_seam(monkeypatch):
    chaos_mod.reset()
    monkeypatch.setenv(chaos_mod.ENV_VAR, "kill:worker=9")
    ch = chaos_mod.get()
    assert ch is not None
    with pytest.raises(chaos_mod.WorkerKilled):
        ch.kill(worker=9)
    # explicit install wins over env; reset re-arms the env seam
    chaos_mod.install(None)
    assert chaos_mod.get() is None
    chaos_mod.reset()
    monkeypatch.delenv(chaos_mod.ENV_VAR)
    assert chaos_mod.get() is None


def test_chaos_rpc_stall_through_server():
    """The server-side stall seam delays the matching verb's reply."""
    chaos_mod.install(chaos_mod.Chaos.parse("rpc_stall:verb=QUERY,secs=0.3"))
    server = rpc.Server(1)
    server.register_callback("QUERY", lambda m: {"type": "QUERY", "ready": True})
    server.start()
    try:
        client = rpc.Client((server.host, server.port), 0, server.secret)
        t0 = time.perf_counter()
        assert client._request({"type": "QUERY"})["ready"]
        stalled = time.perf_counter() - t0
        t0 = time.perf_counter()
        assert client._request({"type": "QUERY"})["ready"]
        clean = time.perf_counter() - t0
        client.stop()
        assert stalled >= 0.3
        assert clean < 0.3
    finally:
        server.stop()


def test_chaos_drops_heartbeats():
    """A matching hb_drop rule swallows beats client-side: the driver sees
    silence, exactly like a preempted worker."""
    from maggy_tpu.reporter import Reporter

    chaos_mod.install(chaos_mod.Chaos.parse("hb_drop:worker=3,times=100"))
    beats = []
    server = rpc.Server(1)
    server.register_callback(
        "METRIC", lambda m: beats.append(m["partition_id"]) or {"type": "OK"}
    )
    server.start()
    try:
        reporter = Reporter(log_file=os.devnull, partition_id=3)
        client = rpc.Client((server.host, server.port), 3, server.secret,
                            hb_interval=0.02)
        client.start_heartbeat(reporter)
        time.sleep(0.2)
        client.stop()
        reporter.close()
        assert beats == []  # every beat swallowed
    finally:
        server.stop()


# ------------------------------------------------- driver-level scheduling


def make_driver(tmp_env, num_trials=4, **kwargs):
    cfg = HyperparameterOptConfig(
        num_trials=num_trials,
        optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
        num_executors=2,
        es_policy="none",
        hb_interval=0.05,
        seed=0,
        **kwargs,
    )
    return HyperparameterOptDriver(cfg, "app_resil", 1)


def _register_and_assign(driver, pid, attempt="a1"):
    driver.server.reservations.register(pid, {"attempt": attempt})
    driver._digest_reg({"type": "REG", "partition_id": pid, "reregistered": False})
    return driver.server.reservations.get_assignment(pid)


def test_requeued_trial_outranks_fresh_suggestions(tmp_env):
    """With zero backoff the lost trial goes straight back to the next free
    worker — retried, not re-suggested."""
    driver = make_driver(tmp_env, retry_backoff=0.0)
    driver.server = driver._make_server()
    driver._register_msg_callbacks()

    first = _register_and_assign(driver, 0)
    assert first is not None
    # restart: the re-REG frees the trial, and the immediate _try_assign
    # hands the SAME trial back (backoff 0, retries remain)
    driver.server.reservations.register(0, {"attempt": "a2"})
    driver._digest_reg({"type": "REG", "partition_id": 0, "reregistered": True})
    assert driver.server.reservations.get_assignment(0) == first
    assert driver.trial_store[first].info_dict["retries"] == 1


def test_worker_quarantined_after_consecutive_losses(tmp_env):
    """Three consecutive lost trials quarantine the worker out of
    _try_assign; a healthy worker keeps serving."""
    driver = make_driver(
        tmp_env, num_trials=8, trial_retries=8, retry_backoff=0.0,
        quarantine_after=3, quarantine_cooldown=60.0,
    )
    driver.server = driver._make_server()
    driver._register_msg_callbacks()

    assert _register_and_assign(driver, 0) is not None
    for n in range(2, 5):  # three worker restarts with in-flight trials
        driver.server.reservations.register(0, {"attempt": f"a{n}"})
        driver._digest_reg({"type": "REG", "partition_id": 0, "reregistered": True})
    assert driver.quarantine.is_quarantined(0)
    # the quarantined worker gets nothing
    assert driver.server.reservations.get_assignment(0) is None
    driver._try_assign(0)
    assert driver.server.reservations.get_assignment(0) is None
    # a different worker still serves (and picks up the requeued trial)
    assert _register_and_assign(driver, 1, attempt="b1") is not None
    assert driver.telemetry.snapshot()["counters"]["resilience.workers_quarantined"] == 1


def test_stranded_completion_regression(tmp_env):
    """ISSUE 4 satellite: the final worker dying *before* budget exhaustion
    with an empty queue used to hang _await_completion (the old sweep only
    finished when _optimizer_exhausted). _maybe_finish now probes the
    controller directly and completes the experiment."""
    driver = make_driver(tmp_env, num_trials=2, trial_retries=0)
    driver.server = driver._make_server()
    driver._register_msg_callbacks()

    first = _register_and_assign(driver, 0)
    # trial 1 finishes cleanly; _digest_final assigns trial 2
    driver.server.reservations.assign_trial(0, None)
    driver._digest_final(
        {"type": "FINAL", "partition_id": 0, "trial_id": first, "metric": 1.0,
         "outputs": {}}
    )
    second = driver.server.reservations.get_assignment(0)
    assert second is not None and second != first
    assert not driver._optimizer_exhausted  # budget not yet exhausted

    # the ONLY worker dies with trial 2 in flight (retry budget 0): nobody is
    # left to poll the controller — the driver must still complete
    driver._digest_worker_lost(
        {"type": "_WORKER_LOST", "partition_id": 0, "error": "RpcError: gone"}
    )
    assert driver.experiment_done.is_set()
    assert len(driver.final_store) == 2
    statuses = sorted(t.status for t in driver.final_store)
    assert statuses == [Trial.ERROR, Trial.FINALIZED]


def test_retry_waits_out_backoff(tmp_env):
    """A requeued trial is not schedulable before its backoff elapses."""
    driver = make_driver(tmp_env, retry_backoff=30.0)
    driver.server = driver._make_server()
    driver._register_msg_callbacks()

    first = _register_and_assign(driver, 0)
    driver.server.reservations.register(0, {"attempt": "a2"})
    driver._digest_reg({"type": "REG", "partition_id": 0, "reregistered": True})
    # the retry sits in the queue (backoff ~30s); the worker got a FRESH trial
    assert [t.trial_id for _r, t in driver._retry_queue] == [first]
    assert driver.server.reservations.get_assignment(0) != first


# ------------------------------------------------------------ rpc satellites


def test_rpc_retry_delay_jitter():
    delays = [rpc._retry_delay(0) for _ in range(50)]
    from maggy_tpu import constants

    base = constants.RPC_RETRY_BASE
    assert all(base * 0.5 <= d <= base * 1.5 for d in delays)
    assert len(set(delays)) > 1  # actually jittered
    # linear growth of the base
    assert min(rpc._retry_delay(4) for _ in range(50)) > max(delays) / 3


def test_rpc_constants_env_overrides(monkeypatch):
    import importlib

    from maggy_tpu import constants

    monkeypatch.setenv("MAGGY_TPU_RPC_MAX_RETRIES", "7")
    monkeypatch.setenv("MAGGY_TPU_RPC_RETRY_BASE", "0.05")
    importlib.reload(constants)
    try:
        assert constants.RPC_MAX_RETRIES == 7
        assert constants.RPC_RETRY_BASE == 0.05
        monkeypatch.setenv("MAGGY_TPU_RPC_MAX_RETRIES", "garbage")
        importlib.reload(constants)
        assert constants.RPC_MAX_RETRIES == 3  # bad value -> default
    finally:
        monkeypatch.delenv("MAGGY_TPU_RPC_MAX_RETRIES")
        monkeypatch.delenv("MAGGY_TPU_RPC_RETRY_BASE")
        importlib.reload(constants)


# ------------------------------------------------------------------ preempt


def test_preemption_hook_sigterm():
    hook = preemption.install()  # pytest runs tests on the main thread
    try:
        assert signal.getsignal(signal.SIGTERM) == hook._handler
        assert not hook.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert hook.wait(timeout=5)
        assert hook.requested()
    finally:
        hook.clear()


def test_preemption_request_from_any_thread():
    preemption.clear()
    t = threading.Thread(target=preemption.request)
    t.start()
    t.join()
    assert preemption.requested()
    preemption.clear()


# ------------------------------------------------------------------ monitor


def test_monitor_renders_resilience_panel():
    from maggy_tpu.monitor import render_status

    status = {
        "name": "exp", "kind": "HyperparameterOptDriver", "state": "RUNNING",
        "app_id": "a", "run_id": 1, "num_executors": 2, "elapsed_s": 5.0,
        "trials_total": 8, "trials_done": 3, "trials_running": 1,
        "trials_requeued": 2, "quarantined": {"1": 42.0},
        "direction": "max", "controller": "RandomSearch",
        "telemetry": {
            "driver": {
                "counters": {
                    "resilience.trials_requeued": 3,
                    "resilience.workers_quarantined": 1,
                    "checkpoint_fallback": 1,
                }
            }
        },
    }
    panel = render_status(status)
    assert "requeued=2" in panel
    assert "quarantined w1:42.0s" in panel
    assert "trials_requeued=3" in panel
    assert "workers_quarantined=1" in panel
    assert "ckpt-fallback 1" in panel
    assert "driver:" in panel


# ----------------------------------------------------------------- CI lint


def test_exception_hygiene_lint():
    """tools/check_exception_hygiene.py runs clean over maggy_tpu/ (wired
    into tier-1 here, beside the bare-print and docs-nav lints)."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_exception_hygiene",
        os.path.join(repo, "tools", "check_exception_hygiene.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0

    # the detector itself
    bare = "try:\n    x()\nexcept:\n    pass\n"
    assert mod.find_violations(bare, "<s>")
    swallow = "try:\n    x()\nexcept Exception:\n    pass\n"
    assert mod.find_violations(swallow, "<s>")
    justified = "try:\n    x()\nexcept Exception:  # best-effort cleanup\n    pass\n"
    assert mod.find_violations(justified, "<s>") == []
    body_comment = (
        "try:\n    x()\nexcept Exception:\n    # optional backend missing\n    pass\n"
    )
    assert mod.find_violations(body_comment, "<s>") == []
    handled = "try:\n    x()\nexcept Exception as e:\n    log(e)\n"
    assert mod.find_violations(handled, "<s>") == []
    narrow = "try:\n    x()\nexcept OSError:\n    pass\n"
    assert mod.find_violations(narrow, "<s>") == []
    broad_tuple = "try:\n    x()\nexcept (ValueError, Exception):\n    pass\n"
    assert mod.find_violations(broad_tuple, "<s>")
