"""Checkpoint/resume: sharded TrainState round-trip via orbax, and HPO
experiment resume skipping finalized trials."""

import jax
import numpy as np
import optax
import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext
from maggy_tpu.train.checkpoint import Checkpointer, load_finalized_trials
from maggy_tpu.train.data import synthetic_lm_batches


@pytest.mark.slow
def test_sharded_state_roundtrip(tmp_path):
    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create(ShardingSpec(dp=2, fsdp=2, tp=2))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    for _ in range(3):
        state, _ = trainer.step(state, trainer.shard_batch(next(data)))

    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    ckpt.save(int(state.step), state)
    ckpt.wait()
    assert ckpt.latest_step() == 3

    # fresh template (different rng -> different values), restore over it
    template = trainer.make_state(jax.random.key(9), next(data))
    restored = ckpt.restore(template)
    ckpt.close()

    import flax.linen as nn

    def unwrap(x):
        return x.value if isinstance(x, nn.Partitioned) else x

    a = unwrap(state.params["embedding"])
    b = unwrap(restored.params["embedding"])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert b.sharding == a.sharding  # restored onto the same mesh layout
    assert int(restored.step) == 3
    # training continues from the restored state
    restored, m = trainer.step(restored, trainer.shard_batch(next(data)))
    assert int(restored.step) == 4


@pytest.mark.slow
def test_cross_mesh_restore(tmp_path):
    """A checkpoint saved under one ShardingSpec restores onto a different
    mesh layout (orbax reshards to the template's NamedShardings) and training
    continues — elastic re-sharding across pod topologies."""
    import flax.linen as nn

    cfg = DecoderConfig.tiny()
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)

    ctx_a = TrainContext.create(ShardingSpec(fsdp=8))
    tr_a = ctx_a.trainer(Decoder(cfg), optax.adamw(1e-3))
    state_a = tr_a.make_state(jax.random.key(0), next(data))
    state_a, _ = tr_a.step(state_a, tr_a.shard_batch(next(data)))
    ck = Checkpointer(str(tmp_path / "xmesh"), async_save=False)
    ck.save(1, state_a)
    ck.wait()

    ctx_b = TrainContext.create(ShardingSpec(dp=2, fsdp=2, tp=2))
    tr_b = ctx_b.trainer(Decoder(cfg), optax.adamw(1e-3))
    template = tr_b.make_state(jax.random.key(9), next(data))
    restored = ck.restore(template)
    ck.close()

    def unwrap(x):
        return x.value if isinstance(x, nn.Partitioned) else x

    a = unwrap(state_a.params["embedding"])
    b = unwrap(restored.params["embedding"])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert "tensor" in str(b.sharding.spec)  # re-laid-out for the new mesh
    restored, m = tr_b.step(restored, tr_b.shard_batch(next(data)))
    assert np.isfinite(float(m["loss"]))


def test_checkpointer_missing(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "empty"), async_save=False)
    with pytest.raises(FileNotFoundError):
        ckpt.restore({"x": np.zeros(2)})
    ckpt.close()


def test_experiment_resume_skips_finished(tmp_env):
    calls = []

    def train(hparams, reporter):
        calls.append(round(hparams["x"], 6))
        return hparams["x"]

    sp = Searchspace(x=("DOUBLE", [0.0, 1.0]))
    cfg1 = HyperparameterOptConfig(
        num_trials=4, optimizer="randomsearch", searchspace=sp,
        num_executors=2, es_policy="none", hb_interval=0.05, seed=42,
    )
    r1 = experiment.lagom(train, cfg1)
    assert r1["num_trials"] == 4
    first_run_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    assert len(load_finalized_trials(first_run_dir)) == 4
    first_calls = list(calls)

    # resume with a larger budget and the same seed: the 4 finished configs
    # must not run again
    calls.clear()
    cfg2 = HyperparameterOptConfig(
        num_trials=8, optimizer="randomsearch", searchspace=sp,
        num_executors=2, es_policy="none", hb_interval=0.05, seed=42,
        resume_from=first_run_dir,
    )
    r2 = experiment.lagom(train, cfg2)
    assert r2["num_trials"] == 8  # 4 preloaded + 4 new
    assert len(calls) == 4
    assert not set(calls) & set(first_calls)


def test_resume_from_missing_dir(tmp_env):
    cfg = HyperparameterOptConfig(
        num_trials=2, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        resume_from="/nonexistent/dir", es_policy="none",
    )
    with pytest.raises(FileNotFoundError):
        experiment.lagom(lambda hparams: 1.0, cfg)
