"""Checkpoint/resume: sharded TrainState round-trip via orbax, and HPO
experiment resume skipping finalized trials."""

import jax
import numpy as np
import optax
import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext
from maggy_tpu.train.checkpoint import Checkpointer, load_finalized_trials
from maggy_tpu.train.data import synthetic_lm_batches


@pytest.mark.slow
def test_sharded_state_roundtrip(tmp_path):
    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create(ShardingSpec(dp=2, fsdp=2, tp=2))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))
    for _ in range(3):
        state, _ = trainer.step(state, trainer.shard_batch(next(data)))

    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    ckpt.save(int(state.step), state)
    ckpt.wait()
    assert ckpt.latest_step() == 3

    # fresh template (different rng -> different values), restore over it
    template = trainer.make_state(jax.random.key(9), next(data))
    restored = ckpt.restore(template)
    ckpt.close()

    import flax.linen as nn

    def unwrap(x):
        return x.value if isinstance(x, nn.Partitioned) else x

    a = unwrap(state.params["embedding"])
    b = unwrap(restored.params["embedding"])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert b.sharding == a.sharding  # restored onto the same mesh layout
    assert int(restored.step) == 3
    # training continues from the restored state
    restored, m = trainer.step(restored, trainer.shard_batch(next(data)))
    assert int(restored.step) == 4


@pytest.mark.slow
def test_cross_mesh_restore(tmp_path):
    """A checkpoint saved under one ShardingSpec restores onto a different
    mesh layout (orbax reshards to the template's NamedShardings) and training
    continues — elastic re-sharding across pod topologies."""
    import flax.linen as nn

    cfg = DecoderConfig.tiny()
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=0)

    ctx_a = TrainContext.create(ShardingSpec(fsdp=8))
    tr_a = ctx_a.trainer(Decoder(cfg), optax.adamw(1e-3))
    state_a = tr_a.make_state(jax.random.key(0), next(data))
    state_a, _ = tr_a.step(state_a, tr_a.shard_batch(next(data)))
    ck = Checkpointer(str(tmp_path / "xmesh"), async_save=False)
    ck.save(1, state_a)
    ck.wait()

    ctx_b = TrainContext.create(ShardingSpec(dp=2, fsdp=2, tp=2))
    tr_b = ctx_b.trainer(Decoder(cfg), optax.adamw(1e-3))
    template = tr_b.make_state(jax.random.key(9), next(data))
    restored = ck.restore(template)
    ck.close()

    def unwrap(x):
        return x.value if isinstance(x, nn.Partitioned) else x

    a = unwrap(state_a.params["embedding"])
    b = unwrap(restored.params["embedding"])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert "tensor" in str(b.sharding.spec)  # re-laid-out for the new mesh
    restored, m = tr_b.step(restored, tr_b.shard_batch(next(data)))
    assert np.isfinite(float(m["loss"]))


def test_checkpointer_missing(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "empty"), async_save=False)
    with pytest.raises(FileNotFoundError):
        ckpt.restore({"x": np.zeros(2)})
    ckpt.close()


def test_experiment_resume_skips_finished(tmp_env):
    calls = []

    def train(hparams, reporter):
        calls.append(round(hparams["x"], 6))
        return hparams["x"]

    sp = Searchspace(x=("DOUBLE", [0.0, 1.0]))
    cfg1 = HyperparameterOptConfig(
        num_trials=4, optimizer="randomsearch", searchspace=sp,
        num_executors=2, es_policy="none", hb_interval=0.05, seed=42,
    )
    r1 = experiment.lagom(train, cfg1)
    assert r1["num_trials"] == 4
    first_run_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    assert len(load_finalized_trials(first_run_dir)) == 4
    first_calls = list(calls)

    # resume with a larger budget and the same seed: the 4 finished configs
    # must not run again
    calls.clear()
    cfg2 = HyperparameterOptConfig(
        num_trials=8, optimizer="randomsearch", searchspace=sp,
        num_executors=2, es_policy="none", hb_interval=0.05, seed=42,
        resume_from=first_run_dir,
    )
    r2 = experiment.lagom(train, cfg2)
    assert r2["num_trials"] == 8  # 4 preloaded + 4 new
    assert len(calls) == 4
    assert not set(calls) & set(first_calls)


def test_resume_from_missing_dir(tmp_env):
    cfg = HyperparameterOptConfig(
        num_trials=2, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        resume_from="/nonexistent/dir", es_policy="none",
    )
    with pytest.raises(FileNotFoundError):
        experiment.lagom(lambda hparams: 1.0, cfg)


def test_checkpoint_records_system_meta(tmp_path):
    """Checkpointer.save records the active ShardingSpec + trainer knobs
    (ISSUE 3 satellite); restore warns when the live config differs and is
    silent when it matches."""
    import warnings

    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create("fsdp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=0)
    batch = next(data)
    state = trainer.make_state(jax.random.key(0), batch)

    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    ckpt.save(0, state, meta=trainer.checkpoint_meta())
    ckpt.wait()

    saved = ckpt.saved_meta(0)
    assert saved is not None
    assert saved["mesh_axes"] == {"fsdp": 8}
    assert saved["n_microbatches"] is None
    assert "bfloat16" in saved["dtype"]

    # matching live config: no warning
    template = trainer.make_state(jax.random.key(1), batch)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ckpt.restore(template, expect_meta=trainer.checkpoint_meta())

    # mismatched live config (different mesh + microbatching): warns, still
    # restores onto the live layout
    ctx2 = TrainContext.create("dp")
    trainer2 = ctx2.trainer(Decoder(cfg), optax.adamw(1e-3), n_microbatches=4)
    template2 = trainer2.make_state(jax.random.key(2), batch)
    with pytest.warns(UserWarning, match="different system config"):
        restored = ckpt.restore(template2, expect_meta=trainer2.checkpoint_meta())
    ckpt.close()
    assert int(restored.step) == 0

    # Trainer.fit's periodic saves carry the metadata automatically
    ckpt2 = Checkpointer(str(tmp_path / "ckpt2"), async_save=False)
    state, _ = trainer.fit(
        state, data, num_steps=2, checkpointer=ckpt2, checkpoint_every=1
    )
    ckpt2.wait()
    assert ckpt2.saved_meta() is not None
    assert ckpt2.saved_meta()["mesh_axes"] == {"fsdp": 8}
    ckpt2.close()
