"""python -m maggy_tpu.run: the multi-process launcher forms one experiment
from N copies of an unmodified user script."""

import os
import subprocess
import sys
import textwrap

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

SCRIPT = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig

    def train(hparams, reporter, ctx):
        reporter.broadcast(1.0, step=0)
        return {{"metric": 2.5}}

    result = experiment.lagom(
        train,
        DistributedConfig(
            num_executors=3,
            sharding="dp",
            data_plane="local",
            hb_interval=0.05,
        ),
    )
    print("RESULT", result, flush=True)
    """
).format(repo=REPO)


def test_run_launcher_three_processes(tmp_path):
    script = tmp_path / "user_script.py"
    script.write_text(SCRIPT)
    env = dict(os.environ)
    env["MAGGY_TPU_LOG_ROOT"] = str(tmp_path / "logs")
    proc = subprocess.run(
        [sys.executable, "-m", "maggy_tpu.run", "--workers", "3", str(script)],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # driver's result aggregates all three workers
    driver_lines = [l for l in proc.stdout.splitlines() if "num_workers" in l]
    assert driver_lines, proc.stdout[-2000:]
    assert "'num_workers': 3" in driver_lines[0]
    assert "'metric': 2.5" in driver_lines[0]
    # worker ranks report their role
    assert proc.stdout.count("'role': 'worker'") == 2


def test_run_launcher_arg_validation():
    proc = subprocess.run(
        [sys.executable, "-m", "maggy_tpu.run", "--workers", "0", "nope.py"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode != 0
    assert "--workers" in proc.stderr
