"""python -m maggy_tpu.run: the multi-process launcher forms one experiment
from N copies of an unmodified user script."""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # subprocess/multi-process tier

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

SCRIPT = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig

    def train(hparams, reporter, ctx):
        reporter.broadcast(1.0, step=0)
        return {{"metric": 2.5}}

    result = experiment.lagom(
        train,
        DistributedConfig(
            num_executors=3,
            sharding="dp",
            data_plane="local",
            hb_interval=0.05,
        ),
    )
    print("RESULT", result, flush=True)
    """
).format(repo=REPO)


def test_run_launcher_three_processes(tmp_path):
    script = tmp_path / "user_script.py"
    script.write_text(SCRIPT)
    env = dict(os.environ)
    env["MAGGY_TPU_LOG_ROOT"] = str(tmp_path / "logs")
    proc = subprocess.run(
        [sys.executable, "-m", "maggy_tpu.run", "--workers", "3", str(script)],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # driver's result aggregates all three workers
    driver_lines = [l for l in proc.stdout.splitlines() if "num_workers" in l]
    assert driver_lines, proc.stdout[-2000:]
    assert "'num_workers': 3" in driver_lines[0]
    assert "'metric': 2.5" in driver_lines[0]
    # worker ranks report their role
    assert proc.stdout.count("'role': 'worker'") == 2


GLOBAL_MESH_SCRIPT = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import maggy_tpu
    formed = maggy_tpu.initialize_data_plane()
    assert formed, "launcher should have exported MAGGY_TPU_COORDINATOR"
    assert jax.process_count() == int(os.environ["MAGGY_TPU_NUM_EXECUTORS"]), (
        jax.process_count()
    )

    import optax
    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train.data import synthetic_lm_batches

    CFG = DecoderConfig.tiny()

    def train(model, dataset, hparams, reporter, ctx):
        assert ctx.num_processes == 2 and len(ctx.mesh.devices.flat) == 2
        trainer = ctx.trainer(model, optax.adamw(3e-3))
        state = trainer.make_state(jax.random.key(0), next(dataset))
        last = None
        for _ in range(5):
            # every process sees the same global batch; shard_batch slices
            state, m = trainer.step(state, trainer.shard_batch(next(dataset)))
            last = float(m["loss"])
        return {{"metric": last, "loss": last}}

    result = experiment.lagom(
        train,
        DistributedConfig(
            module=Decoder(CFG),
            dataset=synthetic_lm_batches(CFG.vocab_size, 8, 32, seed=7),
            sharding="dp",
            data_plane="auto",
            hb_interval=0.05,
        ),
    )
    if jax.process_index() == 0:
        with open(os.environ["MT_RESULT_FILE"], "w") as f:
            json.dump(result, f)
    print("GLOBAL_MESH_OK", flush=True)
    """
).format(repo=REPO)


def test_run_launcher_global_mesh(tmp_path):
    """Two launcher processes form ONE jax.distributed mesh (process_count==2)
    and train with the same loss as a single-process run over the same data —
    the multi-host data-plane proof (NCCL/MASTER_ADDR rendezvous parity)."""
    script = tmp_path / "global_mesh_script.py"
    script.write_text(GLOBAL_MESH_SCRIPT)
    result_file = tmp_path / "result.json"
    env = dict(os.environ)
    env["MAGGY_TPU_LOG_ROOT"] = str(tmp_path / "logs")
    env["MT_RESULT_FILE"] = str(result_file)
    # conftest's 8-device flag must not leak: 1 local device per process
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "maggy_tpu.run",
            "--workers", "2", "--global-mesh", str(script),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-2500:])
    assert proc.stdout.count("GLOBAL_MESH_OK") == 2
    import json

    multi = json.load(result_file.open())

    # same training single-process on a 1-device mesh with the same global batch
    single = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(
            f"""
            import sys; sys.path.insert(0, {REPO!r})
            import os; os.environ["JAX_PLATFORMS"] = "cpu"
            import jax; jax.config.update("jax_platforms", "cpu")
            import optax
            from maggy_tpu.models import Decoder, DecoderConfig
            from maggy_tpu.train import TrainContext
            from maggy_tpu.train.data import synthetic_lm_batches
            CFG = DecoderConfig.tiny()
            ctx = TrainContext.create("dp")
            trainer = ctx.trainer(Decoder(CFG), optax.adamw(3e-3))
            data = synthetic_lm_batches(CFG.vocab_size, 8, 32, seed=7)
            state = trainer.make_state(jax.random.key(0), next(data))
            for _ in range(5):
                state, m = trainer.step(state, trainer.shard_batch(next(data)))
            print("SINGLE_LOSS", float(m["loss"]))
            """
        )],
        env={
            **{k: v for k, v in os.environ.items() if k != "XLA_FLAGS"},
            "MAGGY_TPU_LOG_ROOT": str(tmp_path / "logs1"),
        },
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert single.returncode == 0, single.stderr[-2000:]
    single_loss = float(single.stdout.split("SINGLE_LOSS")[1].strip().split()[0])
    assert abs(multi["loss"] - single_loss) < 2e-4, (multi["loss"], single_loss)


def test_run_launcher_arg_validation():
    proc = subprocess.run(
        [sys.executable, "-m", "maggy_tpu.run", "--workers", "0", "nope.py"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode != 0
    assert "--workers" in proc.stderr


ELASTIC_SCRIPT = textwrap.dedent(
    """
    import os, signal, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import maggy_tpu
    assert maggy_tpu.initialize_data_plane()

    import optax
    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train.checkpoint import Checkpointer
    from maggy_tpu.train.data import synthetic_lm_batches

    CFG = DecoderConfig.tiny()
    GEN = int(os.environ["MAGGY_TPU_GENERATION"])
    RANK = int(os.environ["MAGGY_TPU_PARTITION"])
    TOTAL = 24

    def train(model, dataset, reporter, ctx):
        trainer = ctx.trainer(model, optax.adamw(3e-3))
        state = trainer.make_state(jax.random.key(0), next(dataset))
        ckpt = Checkpointer(os.environ["MT_CKPT_DIR"], async_save=False)
        start = ckpt.latest_step()
        if start is not None:
            state = ckpt.restore(state, step=start)
            for _ in range(start):  # realign the deterministic batch stream
                next(dataset)
        else:
            start = 0
        with open(os.environ["MT_TRACE_FILE"] + f".g{{GEN}}.r{{RANK}}", "w") as f:
            f.write(str(start))
        last = None
        for i in range(start, TOTAL):
            state, m = trainer.step(state, trainer.shard_batch(next(dataset)))
            last = float(m["loss"])
            if (i + 1) % 4 == 0:
                ckpt.save(i + 1, state)
                ckpt.wait()
            if GEN == 0 and RANK == 2 and i + 1 == 10:
                os.kill(os.getpid(), signal.SIGKILL)  # simulated host loss
        ckpt.close()
        return {{"metric": last, "loss": last, "end_step": int(state.step)}}

    result = experiment.lagom(
        train,
        DistributedConfig(
            module=Decoder(CFG),
            dataset=synthetic_lm_batches(CFG.vocab_size, 12, 32, seed=7),
            sharding="dp",
            data_plane="auto",
            hb_interval=0.05,
        ),
    )
    if jax.process_index() == 0:
        import json
        with open(os.environ["MT_RESULT_FILE"], "w") as f:
            json.dump(result, f)
    print("ELASTIC_OK", flush=True)
    """
).format(repo=REPO)


def test_run_launcher_elastic_restart(tmp_path):
    """Kill one of three global-mesh workers mid-run: the launcher restarts the
    generation, the experiment dir is pinned, training resumes from the latest
    checkpoint (not step 0), and the run still completes and converges."""
    script = tmp_path / "elastic_script.py"
    script.write_text(ELASTIC_SCRIPT)
    result_file = tmp_path / "result.json"
    trace = tmp_path / "trace"
    env = dict(os.environ)
    env["MAGGY_TPU_LOG_ROOT"] = str(tmp_path / "logs")
    env["MT_RESULT_FILE"] = str(result_file)
    env["MT_TRACE_FILE"] = str(trace)
    env["MT_CKPT_DIR"] = str(tmp_path / "ckpt")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "maggy_tpu.run",
            "--workers", "3", "--global-mesh", "--elastic", "2", str(script),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-2500:])
    assert "restarting generation 0 -> 1" in proc.stderr, proc.stderr[-2000:]

    # generation 0 started cold, generation 1 resumed from a checkpoint
    g0 = int((tmp_path / "trace.g0.r0").read_text())
    g1 = int((tmp_path / "trace.g1.r0").read_text())
    assert g0 == 0
    assert 0 < g1 < 24, g1

    import json

    result = json.load(result_file.open())
    assert result["num_workers"] == 3
    assert result["end_step"] == 24.0


PACKED_SP_SCRIPT = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import maggy_tpu
    formed = maggy_tpu.initialize_data_plane()
    assert formed and jax.process_count() == 2
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np
    import optax
    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.ringattention import make_ring_attention
    from maggy_tpu.parallel.spec import ShardingSpec

    B, S = 4, 128

    def make_batch():
        rng = np.random.default_rng(5)
        seg = np.zeros((B, S), np.int32); seg[:, S // 2:] = 1
        pos = np.concatenate([np.arange(S // 2), np.arange(S - S // 2)])
        return {{
            "tokens": rng.integers(0, 256, (B, S)).astype(np.int32),
            "positions": pos[None].repeat(B, 0).astype(np.int32),
            "segment_ids": seg,
        }}

    def train(hparams, reporter, ctx):
        cfg = DecoderConfig.tiny(attention_fn=make_ring_attention(ctx.mesh))
        trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
        batch = make_batch()
        state = trainer.make_state(jax.random.key(0), batch)
        sb = trainer.shard_batch(batch)
        # the seq mesh axis SPANS the two processes: each process must carve
        # its own seq chunk out of the global side inputs
        from jax.sharding import PartitionSpec as P
        assert sb["segment_ids"].sharding.spec == P(("data", "fsdp"), "seq")
        last = None
        for _ in range(4):
            state, m = trainer.step(state, sb)
            last = float(m["loss"])
        return {{"metric": last, "loss": last}}

    result = experiment.lagom(
        train,
        DistributedConfig(
            sharding=ShardingSpec(sp=8),
            data_plane="auto",
            hb_interval=0.05,
        ),
    )
    if jax.process_index() == 0:
        with open(os.environ["MT_RESULT_FILE"], "w") as f:
            json.dump(result, f)
    print("PACKED_SP_OK", flush=True)
    """
).format(repo=REPO)


def test_run_launcher_packed_sp_spans_processes(tmp_path):
    """VERDICT r4 item 5, multi-process arm: packed side inputs stay
    seq-sharded when the seq mesh axis SPANS processes (2 procs x 4 local
    devices, sp=8) — shard_batch slices each process's seq chunk from the
    sharding's index map — and the loss matches a single-process sp=8 run
    of the same data."""
    script = tmp_path / "packed_sp_script.py"
    script.write_text(PACKED_SP_SCRIPT)
    result_file = tmp_path / "result.json"
    env = dict(os.environ)
    env["MAGGY_TPU_LOG_ROOT"] = str(tmp_path / "logs")
    env["MT_RESULT_FILE"] = str(result_file)
    env.pop("XLA_FLAGS", None)  # the script pins its own 4-device count
    proc = subprocess.run(
        [
            sys.executable, "-m", "maggy_tpu.run",
            "--workers", "2", "--global-mesh", str(script),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-2500:])
    assert proc.stdout.count("PACKED_SP_OK") == 2
    import json

    multi = json.load(result_file.open())

    single = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(
            f"""
            import sys; sys.path.insert(0, {REPO!r})
            import os; os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
            import jax; jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import optax
            from maggy_tpu.models import Decoder, DecoderConfig
            from maggy_tpu.parallel.ringattention import make_ring_attention
            from maggy_tpu.parallel.spec import ShardingSpec
            from maggy_tpu.train import TrainContext

            B, S = 4, 128
            rng = np.random.default_rng(5)
            seg = np.zeros((B, S), np.int32); seg[:, S // 2:] = 1
            pos = np.concatenate([np.arange(S // 2), np.arange(S - S // 2)])
            batch = {{
                "tokens": rng.integers(0, 256, (B, S)).astype(np.int32),
                "positions": pos[None].repeat(B, 0).astype(np.int32),
                "segment_ids": seg,
            }}
            ctx = TrainContext.create(ShardingSpec(sp=8))
            cfg = DecoderConfig.tiny(attention_fn=make_ring_attention(ctx.mesh))
            trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
            state = trainer.make_state(jax.random.key(0), batch)
            sb = trainer.shard_batch(batch)
            for _ in range(4):
                state, m = trainer.step(state, sb)
            print("SINGLE_LOSS", float(m["loss"]))
            """
        )],
        env={
            **{k: v for k, v in os.environ.items() if k != "XLA_FLAGS"},
            "MAGGY_TPU_LOG_ROOT": str(tmp_path / "logs1"),
        },
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert single.returncode == 0, single.stderr[-2000:]
    single_loss = float(single.stdout.split("SINGLE_LOSS")[1].strip().split()[0])
    assert abs(multi["loss"] - single_loss) < 1e-3, (multi["loss"], single_loss)
