"""Time-series store, alert rules, and the recompile sentinel (ISSUE 13).

Covers the windowed ring-buffer series (`telemetry/timeseries.py`), the
declarative alert registry + evaluator (`telemetry/alerts.py`), the
fleet-merge reproducibility contract (`tools/metrics_query.py` equals the
router's fleet store), the chaos acceptance (degraded replica -> fleet
burn-rate alert -> monitor ALERTS line -> resolve; out-of-band reconfigure
trips the sentinel), the registry lints, the flight-recorder alert
enrichment, and concurrent sink rotation.
"""

import importlib.util
import json
import os
import sys
import threading
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from maggy_tpu.telemetry import timeseries
from maggy_tpu.telemetry.alerts import (
    ALERT_FIRING,
    ALERT_RESOLVED,
    BY_NAME,
    AlertEvaluator,
    RecompileSentinel,
)
from maggy_tpu.telemetry.histogram import LatencyHistogram
from maggy_tpu.telemetry.recorder import Telemetry
from maggy_tpu.telemetry.timeseries import (
    Series,
    SeriesStore,
    merge_windowed_percentile,
)


def load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


class _EventTap:
    """Minimal recorder stand-in capturing alert transition events."""

    def __init__(self):
        self.events = []

    def event(self, name, trace=None, **attrs):
        self.events.append((name, attrs))

    def gauge(self, *a, **k):
        pass

    def count(self, *a, **k):
        pass

    def names(self, kind):
        return [n for n, _ in self.events if n == kind]


# ------------------------------------------------------------ series queries


def test_counter_delta_rate_and_reset_clamp():
    s = Series("c", "counter")
    for i in range(20):
        s.append(1000.0 + i, float(i * 5))
    # window of 10s back from ts=1019: base is the point at ts<=1009 (45)
    assert s.delta(10.0, 1019.0) == 95.0 - 45.0
    assert s.rate(10.0, 1019.0) == pytest.approx(5.0)
    # ring shorter than the window: difference against the oldest point
    assert s.delta(1e6, 1019.0) == 95.0
    # counter reset (process restart) clamps to zero, never negative
    s.append(1020.0, 0.0)
    assert s.delta(5.0, 1020.0) == 0.0


def test_hist_series_windowed_percentile_is_window_only():
    s = Series("h", "hist")
    h = LatencyHistogram()
    # old regime: 100 fast observations, then 10 slow ones recently
    for _ in range(100):
        h.observe(5.0)
    s.append(1000.0, h.to_dict())
    for i in range(10):
        h.observe(500.0)
        s.append(1010.0 + i, h.to_dict())
    # lifetime view is dominated by the fast old samples ...
    assert LatencyHistogram.from_dict(s.latest()[1]).percentile(0.5) < 10.0
    # ... the windowed view sees only the recent slow ones
    p50 = s.percentile(0.5, 8.0, 1019.0)
    assert p50 is not None and p50 > 100.0
    att = s.attainment(100.0, 8.0, 1019.0)
    assert att == pytest.approx(0.0, abs=0.01)


def test_store_sample_snapshot_roundtrip_and_version_guard():
    tel = Telemetry(worker="ts-test")
    tel.gauge("serve.queue_depth", 3.0)
    tel.count("serve.requests_done", 7)
    tel.histogram("serve.ttft_ms", 12.5)
    store = SeriesStore()
    store.sample(tel, 2000.0)
    tel.gauge("serve.queue_depth", 5.0)
    tel.count("serve.requests_done", 2)
    store.sample(tel, 2001.0)

    snap = store.snapshot()
    back = SeriesStore.from_snapshot(snap)
    assert back.names() == store.names()
    assert back.get("serve.queue_depth").latest()[1] == 5.0
    assert back.get("serve.requests_done").delta(10.0, 2001.0) == 2.0
    assert back.get("serve.ttft_ms").percentile(0.5, 10.0, 2001.0) is not None
    # versioned form: a future schema refuses rather than misreads
    with pytest.raises(ValueError, match="newer"):
        SeriesStore.from_snapshot(dict(snap, v=timeseries.SCHEMA_VERSION + 1))
    # tick gating: same second -> no second sample
    assert store.maybe_sample(tel, 2001.2) is False
    assert store.maybe_sample(tel, 2002.5) is True


def test_merge_of_windowed_equals_windowed_of_merge():
    """The reproducibility contract: per-replica windowed distributions
    merged == the fleet-aggregate series (merged-then-appended) windowed,
    when every append shares the tick timestamp."""
    replica_stores = [SeriesStore(), SeriesStore()]
    fleet = SeriesStore()
    hists = [LatencyHistogram(), LatencyHistogram()]
    t0 = 3000.0
    for tick in range(40):
        now = t0 + tick
        for r, h in enumerate(hists):
            for _ in range(3):
                h.observe(4.0 * (r + 1) + tick * 0.3)
            replica_stores[r].ingest(now, hists={"serve.ttft_ms": h.to_dict()})
        merged = hists[0].merge(hists[1])
        fleet.ingest(now, hists={"serve.ttft_ms": merged.to_dict()})
    now = t0 + 39
    for window in (5.0, 15.0, 30.0):
        for q in (0.5, 0.95):
            via_merge = merge_windowed_percentile(
                replica_stores, "serve.ttft_ms", q, window, now
            )
            via_fleet = fleet.get("serve.ttft_ms").percentile(q, window, now)
            assert via_merge == pytest.approx(via_fleet), (window, q)


# ------------------------------------------------------------------- alerts


def test_threshold_rule_for_duration_and_transitions():
    tap = _EventTap()
    store = SeriesStore()
    ev = AlertEvaluator(
        store, tap, scope="worker", rules=(BY_NAME["alert.queue_depth_high"],)
    )
    t0 = 5000.0
    s = store.series("serve.queue_depth", "gauge")
    # over threshold but shorter than for_s=3 -> pending, not firing
    for i in range(3):
        s.append(t0 + i, 100.0)
        ev.evaluate(t0 + i)
    assert ev.firing() == []
    s.append(t0 + 3, 100.0)
    fired = ev.evaluate(t0 + 3)
    assert [t["alert"] for t in fired] == ["alert.queue_depth_high"]
    assert ev.firing()[0]["severity"] == "warning"
    assert tap.names(ALERT_FIRING)
    # a one-tick dip resets the for-duration clock AND resolves
    s.append(t0 + 4, 1.0)
    resolved = ev.evaluate(t0 + 4)
    assert resolved and resolved[0]["event"] == ALERT_RESOLVED
    assert ev.firing() == [] and tap.names(ALERT_RESOLVED)
    # stale series (no samples within stale_s) never fires
    ev2 = AlertEvaluator(
        store, None, scope="worker", rules=(BY_NAME["alert.queue_depth_high"],)
    )
    s.append(t0 + 5, 100.0)
    for dt in (5, 6, 7, 8):
        ev2.evaluate(t0 + 100 + dt)
    assert ev2.firing() == []


def test_burn_rate_multiwindow_fire_and_resolve():
    tap = _EventTap()
    store = SeriesStore()
    ev = AlertEvaluator(
        store, tap, scope="worker", rules=(BY_NAME["alert.ttft_slo_burn"],)
    )
    t0 = 6000.0
    ok, miss = 0, 0
    tick = 0
    # healthy: 35 ticks of pure attainment -> never fires
    for _ in range(35):
        ok += 10
        store.ingest(t0 + tick, counters={"serve.slo_ok": ok, "serve.slo_miss": miss})
        assert ev.evaluate(t0 + tick) == []
        tick += 1
    # degrade: 40% miss rate; both the 30s and 5s windows blow their
    # 2x-budget factor within a couple of evaluation ticks
    fired_at = None
    for i in range(6):
        ok += 6
        miss += 4
        store.ingest(t0 + tick, counters={"serve.slo_ok": ok, "serve.slo_miss": miss})
        if ev.evaluate(t0 + tick) and fired_at is None:
            fired_at = i
        tick += 1
    assert fired_at is not None and fired_at <= 5
    assert ev.firing()[0]["alert"] == "alert.ttft_slo_burn"
    assert ev.firing()[0]["severity"] == "critical"
    # recover: the short window drains within ~5 ticks and resolves the page
    resolved_at = None
    for i in range(12):
        ok += 10
        store.ingest(t0 + tick, counters={"serve.slo_ok": ok, "serve.slo_miss": miss})
        trans = ev.evaluate(t0 + tick)
        if any(t["event"] == ALERT_RESOLVED for t in trans):
            resolved_at = i
        tick += 1
    assert resolved_at is not None
    assert ev.firing() == []
    assert tap.names(ALERT_FIRING) and tap.names(ALERT_RESOLVED)


def test_recompile_sentinel_warm_expected_and_trip():
    tap = _EventTap()
    store = SeriesStore()
    dumps = []
    wd = types.SimpleNamespace(dump=lambda reason: dumps.append(reason))
    sent = RecompileSentinel(store, tap, steady=("decode", "admit"))
    t0 = 7000.0
    # first observation baselines silently (even at a nonzero count)
    assert sent.observe({"decode": 0, "prefill": 1}, t0, wd) == []
    # the warm first compile (0 -> 1) is silent
    assert sent.observe({"decode": 1, "prefill": 1}, t0 + 1, wd) == []
    # a declared reconfigure re-baselines silently
    sent.expect()
    assert sent.observe({"decode": 2, "prefill": 1}, t0 + 2, wd) == []
    # prefill is a bucketed ladder: new buckets compile by design, no alert
    assert sent.observe({"decode": 2, "prefill": 5}, t0 + 3, wd) == []
    assert not dumps and not tap.names(ALERT_FIRING)
    # the unexplained retrace past a warm baseline trips, dumps, emits
    assert sent.observe({"decode": 3, "prefill": 5}, t0 + 4, wd) == ["decode"]
    firing = sent.firing(t0 + 5)
    assert firing and firing[0]["alert"] == "alert.recompile"
    assert firing[0]["program"] == "decode"
    assert dumps == ["alert:alert.recompile:decode"]
    assert tap.names(ALERT_FIRING)
    # every count landed as a compile.<prog> series
    assert store.get("compile.decode").latest()[1] == 3.0
    assert store.get("compile.prefill").latest()[1] == 5.0
    # the hold window expires -> auto-resolve with an event
    assert sent.firing(t0 + 4 + sent.HOLD_S + 1) == []
    assert tap.names(ALERT_RESOLVED)


def test_flightrec_dump_embeds_firing_alerts_and_series_tails():
    from maggy_tpu.telemetry import flightrec

    store = SeriesStore()
    ev = AlertEvaluator(
        store, None, scope="worker", rules=(BY_NAME["alert.queue_depth_high"],)
    )
    t0 = 8000.0
    s = store.series("serve.queue_depth", "gauge")
    for i in range(5):
        s.append(t0 + i, 200.0)
        ev.evaluate(t0 + i)
    assert ev.firing()
    wd = flightrec.Watchdog(stall_s=60.0, dump_dir=None)
    wd.dump("unit-test")
    payload = wd.last_dump
    assert any(a["alert"] == "alert.queue_depth_high" for a in payload["alerts"])
    tail = payload["alert_series"]["worker/serve.queue_depth"]
    assert tail and tail[-1] == [t0 + 4, 200.0]


# --------------------------------------------------- registry + lint checks


def test_every_metric_has_a_unit():
    from maggy_tpu.telemetry import metrics as M

    assert set(M.UNITS) >= set(M.ALL)
    assert {u for u in M.UNITS.values()} <= set(M.VALID_UNITS)


def test_lint_units_and_alert_registry_self_checks():
    mod = load_tool("check_telemetry_names")
    registry = mod.load_registry(REPO)
    alerts = mod.load_alerts(REPO)
    assert mod.check_units(registry) == []
    assert mod.check_alert_registry(alerts, registry) == []

    # a registered metric without a unit is flagged
    broken = types.SimpleNamespace(
        ALL=registry.ALL | {"serve.mystery"},
        UNITS=dict(registry.UNITS, bogus="ms"),
        VALID_UNITS=registry.VALID_UNITS,
    )
    out = mod.check_units(broken)
    assert any("serve.mystery" in v for v in out)
    assert any("bogus" in v for v in out)

    # malformed rules are flagged structurally
    bad_rules = types.SimpleNamespace(
        RULES=(
            alerts.Rule(name="no_prefix", summary="x", kind="threshold"),
            alerts.Rule(
                name="alert.bad_burn", summary="x", kind="burn_rate", objective=2.0
            ),
            alerts.Rule(
                name="alert.ghost_metric",
                summary="x",
                kind="threshold",
                metric="serve.not_registered_anywhere",
            ),
        ),
        KINDS=alerts.KINDS,
        SEVERITIES=alerts.SEVERITIES,
        SCOPES=alerts.SCOPES,
        ALERT_FIRING=alerts.ALERT_FIRING,
        ALERT_RESOLVED=alerts.ALERT_RESOLVED,
    )
    out = mod.check_alert_registry(bad_rules, registry)
    assert any("must start with 'alert.'" in v for v in out)
    assert any("objective" in v for v in out)
    assert any("needs a metric" in v or "ok/miss" in v for v in out)
    assert any("unregistered metric" in v for v in out)

    # a typo'd alert literal in source is caught; registered names pass
    names = {r.name for r in alerts.RULES} | {alerts.ALERT_FIRING}
    bad_src = 'tel.event("alert.firing", alert="alert.definitely_a_typo")\n'
    hits = mod.check_source(bad_src, "x.py", registry, names)
    assert any("definitely_a_typo" in msg for _, msg in hits)
    ok_src = 'tel.event("alert.firing", alert="alert.recompile")\n'
    names |= {"alert.recompile"}
    assert mod.check_source(ok_src, "x.py", registry, names) == []
    # 3-arg form (no alert validation) stays supported
    assert mod.check_source(ok_src, "x.py", registry) == []


def test_telemetry_names_lint_clean():
    mod = load_tool("check_telemetry_names")
    assert mod.main([]) == 0


# ------------------------------------------------- sink rotation concurrency


def test_sink_rotation_with_concurrent_writers(tmp_env, tmp_path):
    """N writer threads through one rotating sink: no dropped, duplicated,
    or torn records, and per-thread order survives rotation + the
    oldest-first segment fold."""
    from maggy_tpu.telemetry.export import load_records
    from maggy_tpu.telemetry.sink import JsonlSink

    tdir = os.path.join(str(tmp_path), "exp", "telemetry")
    os.makedirs(tdir)
    path = os.path.join(tdir, "worker_cc.jsonl")
    n_threads, n_records = 4, 150
    # small segments force many rotations mid-traffic; enough segment slots
    # that nothing ages out, so every record must survive
    sink = JsonlSink(path, env=tmp_env, max_bytes=2048, max_segments=64)

    def writer(t):
        for i in range(n_records):
            sink.write(
                [{"kind": "event", "name": "e", "ts": float(i), "worker": str(t),
                  "attrs": {"thread": t, "seq": i}}]
            )

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    sink.close()

    recs = load_records(tmp_env, os.path.join(str(tmp_path), "exp"))["worker_cc"]
    assert len(recs) == n_threads * n_records
    by_thread = {}
    for r in recs:
        by_thread.setdefault(r["attrs"]["thread"], []).append(r["attrs"]["seq"])
    for t in range(n_threads):
        assert by_thread[t] == list(range(n_records)), f"thread {t} order broken"


# -------------------------------------------- chaos acceptance: fleet alert


def _replica_stats(h, ok, miss, done, qd=1):
    return {
        "num_slots": 4, "active_slots": 2, "queue_depth": qd,
        "tokens_per_sec": 120.0, "requests_done": done,
        "ttft_ms_p50": h.percentile(0.5), "ttft_ms_p95": h.percentile(0.95),
        "latency": {"ttft_ms": h.to_dict()},
        "slo_ok": ok, "slo_miss": miss,
    }


def test_fleet_burn_alert_fires_on_degraded_replica_and_resolves():
    """Chaos acceptance: one replica of two degrades its TTFT -> the
    fleet-scope burn-rate alert fires within an evaluation window, lands in
    alert.* events, renders on the monitor ALERTS line, and resolves once
    the replica recovers."""
    from maggy_tpu.monitor import _alert_lines, render_status
    from maggy_tpu.serve.fleet import Router, RouterConfig
    from tests.test_serve_fleet import fake_replica

    tel = Telemetry(worker="fleet-alert-test")
    router = Router(
        [fake_replica(0), fake_replica(1)],
        config=RouterConfig(),
        telemetry_recorder=tel,
    )
    hists = [LatencyHistogram(), LatencyHistogram()]
    ok = [0, 0]
    miss = [0, 0]
    done = [0, 0]
    t0 = 9000.0
    tick = 0

    def advance(degraded=None):
        nonlocal tick
        for r in range(2):
            if r == degraded:
                hists[r].observe(900.0)  # injected TTFT degradation
                ok[r] += 2
                miss[r] += 8
            else:
                hists[r].observe(20.0)
                ok[r] += 10
            done[r] += 5
            router._stats_cache[r] = _replica_stats(
                hists[r], ok[r], miss[r], done[r]
            )
        router._sample_metrics(t0 + tick)
        tick += 1

    # healthy steady state: no alert
    for _ in range(35):
        advance()
    assert router.alerts.firing() == []
    # degrade replica 1; fleet-scope burn fires within a handful of ticks
    fired_after = None
    for i in range(6):
        advance(degraded=1)
        if router.alerts.firing() and fired_after is None:
            fired_after = i
    assert fired_after is not None and fired_after <= 5
    names = [a["alert"] for a in router.alerts.firing()]
    assert "alert.ttft_slo_burn" in names
    assert all(a["scope"] == "fleet" for a in router.alerts.firing())
    # the transition landed in the telemetry journal as an alert.* event
    flight = [r.get("name") for r in list(tel.flight)]
    assert ALERT_FIRING in flight

    # SSTATS carries the firing set + trends; the monitor renders both
    stats = router._fleet_stats()
    assert any(a["alert"] == "alert.ttft_slo_burn" for a in stats["alerts"])
    assert stats["trends"].get("serve.queue_depth")
    lines = _alert_lines(stats, 78)
    assert lines and "ALERTS[" in lines[0] and "ttft_slo_burn(!)" in lines[0]
    panel = render_status(router._on_status({}))
    assert "ALERTS[" in panel and "ttft_slo_burn(!)" in panel

    # recovery: the short window drains, the burn alert resolves, and the
    # brownout ladder (stepped up while the burn fired) walks back to 0 one
    # level per recover_s — only then does alert.brownout clear too
    for _ in range(30):
        advance()
    assert router.brownout.level() == 0
    assert router.alerts.firing() == []
    flight = [r.get("name") for r in list(tel.flight)]
    assert ALERT_RESOLVED in flight

    # the exported snapshots reproduce the fleet percentile offline
    body = router._metrics_body()
    stores = [
        SeriesStore.from_snapshot(body["replicas"][k]) for k in sorted(body["replicas"])
    ]
    fleet_store = SeriesStore.from_snapshot(body["metrics"])
    now = t0 + tick - 1
    reproduced = merge_windowed_percentile(stores, "serve.ttft_ms", 0.95, 30.0, now)
    direct = fleet_store.get("serve.ttft_ms").percentile(0.95, 30.0, now)
    assert reproduced == pytest.approx(direct)


def test_metrics_query_cli_reproduces_fleet_percentile(tmp_path, capsys):
    mq = load_tool("metrics_query")
    stores = [SeriesStore(), SeriesStore()]
    fleet = SeriesStore()
    hists = [LatencyHistogram(), LatencyHistogram()]
    t0 = 10_000.0
    for tick in range(40):
        now = t0 + tick
        for r, h in enumerate(hists):
            h.observe(10.0 * (r + 1) + tick)
            stores[r].ingest(now, hists={"serve.ttft_ms": h.to_dict()},
                             counters={"serve.requests_done": tick * 2})
        fleet.ingest(now, hists={"serve.ttft_ms": hists[0].merge(hists[1]).to_dict()})
    paths = []
    for r, st in enumerate(stores):
        p = os.path.join(str(tmp_path), f"r{r}.json")
        with open(p, "w") as f:
            json.dump(st.snapshot(), f)
        paths.append(p)
    now = t0 + 39
    expected = fleet.get("serve.ttft_ms").percentile(0.95, 30.0, now)

    assert mq.main(["--merge", *paths, "--name", "serve.ttft_ms",
                    "--q", "0.95", "--window", "30", "--now", str(now)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["merged_from"] == 2
    assert out["p95"] == pytest.approx(expected)

    # METRICS-reply unwrapping + counter rate on a single store
    reply = os.path.join(str(tmp_path), "reply.json")
    with open(reply, "w") as f:
        json.dump({"scope": "worker", "metrics": stores[0].snapshot()}, f)
    assert mq.main([reply, "--name", "serve.requests_done",
                    "--window", "30", "--now", str(now)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["kind"] == "counter" and out["delta"] == 60.0
    assert mq.main([reply, "--list"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert {s["name"] for s in out["series"]} == {
        "serve.ttft_ms", "serve.requests_done"
    }


# ----------------------------------- chaos acceptance: out-of-band retrace


@pytest.mark.slow
def test_scheduler_sentinel_trips_on_out_of_band_reconfigure():
    """An engine reconfigure through the scheduler seam re-baselines the
    sentinel; the same geometry change injected OUTSIDE the seam (the
    chaos case: something recompiles decode behind the scheduler's back)
    trips alert.recompile onto SSTATS and the monitor ALERTS line."""
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.monitor import _alert_lines
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import Engine, Request, SamplingParams, Scheduler

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    engine = Engine(cfg, params, num_slots=2)
    sched = Scheduler(engine)  # not started: tick driven by hand
    # warm decode so the sentinel has a nonzero baseline
    slot, _ = engine.admit(Request(prompt=[1, 2, 3], params=SamplingParams(max_new=4)))
    engine.step()
    engine.release(slot)
    assert engine.compile_counts["decode"] >= 1

    import time as _time

    # wall-clock ticks: stats()/firing() judge the sentinel hold window
    # against real time
    t0 = _time.time()
    sched._metrics_tick(t0)
    assert sched.sentinel.firing() == []

    # legit path: reconfigure through the scheduler seam -> expect() -> quiet
    sched._pending_slots = 3
    sched._maybe_reconfigure()
    before = engine.compile_counts["decode"]
    sched._metrics_tick(t0 + 1)
    assert sched.sentinel.firing() == [], "declared reconfigure must not alert"

    # chaos: the same change outside the seam trips the sentinel
    engine.reconfigure(4)
    assert engine.compile_counts["decode"] > before
    sched._metrics_tick(t0 + 2)
    firing = sched.sentinel.firing()
    assert firing and firing[0]["alert"] == "alert.recompile"
    stats = sched.stats()
    assert any(a["alert"] == "alert.recompile" for a in stats["alerts"])
    lines = _alert_lines(stats, 78)
    assert lines and "recompile" in lines[0] and "(!)" in lines[0]
